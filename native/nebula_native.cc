// nebula_native — C-ABI native kernels for the host (CPU) plane.
//
// The reference implements its storage scan path, row/key codec, and
// bulk loaders in C++ (src/storage, src/codec [UNVERIFIED — empty
// reference mount, SURVEY §0]).  In the TPU-first rebuild the device
// compute path is XLA-generated native code; the pieces that still
// merit handwritten C++ are the host-side bulk-data kernels feeding
// HBM: CSV ingest, COO→padded-CSR assembly (the sort+indptr hot loop
// of the snapshot builder), and the binary row codec used for bulk
// export.  Exposed via a plain C ABI consumed with ctypes
// (nebula_tpu/native/__init__.py), with Python/NumPy fallbacks.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -o libnebula_native.so nebula_native.cc
#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <numeric>
#include <string>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CSV edge/vertex ingest
//
// Parses a delimited text file of records.  Column types:
//   0 = int64, 1 = float64, 2 = string (FNV-1a 64-bit hash; the Python
//       side resolves hashes to pool codes), 3 = skip.
// Values land column-major into caller-allocated buffers (int64/double
// per column, capacity max_rows).  Returns rows parsed, -1 on I/O
// error, -2 if the file holds more than max_rows rows, or -3 on a
// malformed record (short row, or an int/float field that does not
// parse) — a bulk loader must fail loudly, never silently skip/zero.
// ---------------------------------------------------------------------------

static inline uint64_t fnv1a(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; i++) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ull;
    }
    return h;
}

long long csv_ingest(const char* path, char delim, int skip_header,
                     int n_cols, const int* col_types,
                     long long max_rows, int64_t** int_cols,
                     double** dbl_cols) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    std::vector<char> buf(1 << 20);
    std::string line;
    line.reserve(4096);
    long long row = 0;
    bool first = true;
    bool malformed = false;
    int c;
    std::string cur;
    std::vector<std::string> fields;
    auto flush_line = [&]() -> bool {
        if (cur.empty() && fields.empty()) return true;
        fields.push_back(cur);
        cur.clear();
        if (first && skip_header) {
            first = false;
            fields.clear();
            return true;
        }
        first = false;
        if ((int)fields.size() != n_cols) {
            malformed = true;   // short OR over-long record (field shift)
            fields.clear();
            return false;
        }
        if (row >= max_rows) { fields.clear(); return false; }
        for (int i = 0; i < n_cols; i++) {
            const std::string& s = fields[i];
            char* end = nullptr;
            switch (col_types[i]) {
                case 0:
                    errno = 0;
                    int_cols[i][row] = std::strtoll(s.c_str(), &end, 10);
                    if (end == s.c_str() || *end != '\0' ||
                        errno == ERANGE) {   // reject silent clamping too
                        malformed = true;
                        fields.clear();
                        return false;
                    }
                    break;
                case 1:
                    errno = 0;
                    dbl_cols[i][row] = std::strtod(s.c_str(), &end);
                    if (end == s.c_str() || *end != '\0' ||
                        errno == ERANGE) {
                        malformed = true;
                        fields.clear();
                        return false;
                    }
                    break;
                case 2: int_cols[i][row] = (int64_t)fnv1a(s.data(), s.size()); break;
                default: break;
            }
        }
        row++;
        fields.clear();
        return true;
    };
    bool keep = true;
    while (keep) {
        size_t n = std::fread(buf.data(), 1, buf.size(), f);
        if (n == 0) break;
        for (size_t i = 0; i < n && keep; i++) {
            c = buf[i];
            if (c == '\n') {
                keep = flush_line();
            } else if (c == '\r') {
                // ignore
            } else if (c == delim) {
                fields.push_back(cur);
                cur.clear();
            } else {
                cur.push_back((char)c);
            }
        }
    }
    if (keep) flush_line();
    std::fclose(f);
    if (malformed) return -3;
    if (!keep) return -2;          // max_rows exceeded
    return row;
}

// ---------------------------------------------------------------------------
// COO → padded per-part CSR (the snapshot builder's hot loop)
//
// Inputs: n_edges COO entries with dense src/dst ids (dense % P = owner
// part, dense / P = local row), rank.  Emits, for the part-major padded
// layout (P, vmax+1)/(P, emax):
//   perm      (n_edges)    — input index in output slot order, so the
//                            caller gathers property columns with one
//                            numpy fancy-index per column
//   indptr    (P, vmax+1)
//   nbr,rank  (P, emax)    — -1 / 0 padded
// Sort order per part: (local_src, rank, dst) — matching the host
// get_neighbors iteration order for integer vids.
// Returns emax (max edges in any part), or -1 on error.
// ---------------------------------------------------------------------------

long long build_csr(long long n_edges, int P, long long vmax,
                    const int64_t* src_dense, const int64_t* dst_dense,
                    const int64_t* rank, const int64_t* dst_key,
                    int64_t* perm, int32_t* indptr,
                    int32_t* nbr, int32_t* rank_out,
                    long long emax_cap) {
    std::vector<int64_t> order(n_edges);
    std::iota(order.begin(), order.end(), 0);
    std::sort(order.begin(), order.end(),
              [&](int64_t a, int64_t b) {
                  int pa = (int)(src_dense[a] % P), pb = (int)(src_dense[b] % P);
                  if (pa != pb) return pa < pb;
                  int64_t la = src_dense[a] / P, lb = src_dense[b] / P;
                  if (la != lb) return la < lb;
                  if (rank[a] != rank[b]) return rank[a] < rank[b];
                  // dst_key: caller-provided neighbor order (vid value
                  // for int spaces, sorted-string ordinal otherwise)
                  if (dst_key[a] != dst_key[b]) return dst_key[a] < dst_key[b];
                  return a < b;
              });
    // validate + per-part counts (an out-of-range local index must be a
    // clean error, not a write past the indptr row)
    std::vector<long long> pcount(P, 0);
    for (long long i = 0; i < n_edges; i++) {
        if (src_dense[i] < 0 || src_dense[i] / P >= vmax) return -1;
        pcount[src_dense[i] % P]++;
    }
    long long emax = 1;
    for (int p = 0; p < P; p++) emax = std::max(emax, pcount[p]);
    if (emax > emax_cap) return -1;

    // fill
    std::vector<long long> ppos(P, 0);
    const long long stride_i = vmax + 1;
    for (int p = 0; p < P; p++)
        for (long long v = 0; v <= vmax; v++) indptr[p * stride_i + v] = 0;
    for (long long k = 0; k < n_edges; k++) {
        int64_t e = order[k];
        int p = (int)(src_dense[e] % P);
        int64_t local = src_dense[e] / P;
        long long slot = ppos[p]++;
        perm[p * emax_cap + slot] = e;
        nbr[p * emax_cap + slot] = (int32_t)dst_dense[e];
        rank_out[p * emax_cap + slot] = (int32_t)rank[e];
        indptr[p * stride_i + local + 1]++;
    }
    for (int p = 0; p < P; p++) {
        int32_t acc = 0;
        for (long long v = 1; v <= vmax; v++) {
            acc += indptr[p * stride_i + v];
            indptr[p * stride_i + v] = acc;
        }
    }
    return emax;
}

// ---------------------------------------------------------------------------
// Binary row codec (RowWriterV2/RowReaderWrapper analog)
//
// Fixed little-endian layout per row:
//   u16 schema_version | u16 n_props | per prop:
//     u8 kind (0=null,1=int64,2=double,3=bool,4=str) |
//     int64/double/u8 | (str: u32 len + bytes)
// Encode: caller passes parallel arrays describing one row; returns
// bytes written or -1 if the buffer is too small.  Used for bulk export
// and WAL-compaction payloads.
// ---------------------------------------------------------------------------

long long row_encode(int version, int n_props, const int* kinds,
                     const int64_t* ivals, const double* dvals,
                     const char** svals, const int* slens,
                     unsigned char* out, long long cap) {
    long long need = 4;
    for (int i = 0; i < n_props; i++) {
        need += 1;
        if (kinds[i] == 1) need += 8;
        else if (kinds[i] == 2) need += 8;
        else if (kinds[i] == 3) need += 1;
        else if (kinds[i] == 4) need += 4 + slens[i];
    }
    if (need > cap) return -1;
    unsigned char* w = out;
    uint16_t v16 = (uint16_t)version, n16 = (uint16_t)n_props;
    std::memcpy(w, &v16, 2); w += 2;
    std::memcpy(w, &n16, 2); w += 2;
    for (int i = 0; i < n_props; i++) {
        *w++ = (unsigned char)kinds[i];
        if (kinds[i] == 1) { std::memcpy(w, &ivals[i], 8); w += 8; }
        else if (kinds[i] == 2) { std::memcpy(w, &dvals[i], 8); w += 8; }
        else if (kinds[i] == 3) { *w++ = (unsigned char)(ivals[i] != 0); }
        else if (kinds[i] == 4) {
            uint32_t l = (uint32_t)slens[i];
            std::memcpy(w, &l, 4); w += 4;
            std::memcpy(w, svals[i], l); w += l;
        }
    }
    return (long long)(w - out);
}

// Decode: fills kinds/ivals/dvals and, for strings, offsets+lengths
// into the input buffer (zero-copy).  Returns n_props or -1.
long long row_decode(const unsigned char* in, long long len,
                     int* version, int* kinds, int64_t* ivals,
                     double* dvals, long long* soffs, int* slens,
                     int max_props) {
    if (len < 4) return -1;
    uint16_t v16, n16;
    std::memcpy(&v16, in, 2);
    std::memcpy(&n16, in + 2, 2);
    if (n16 > max_props) return -1;
    const unsigned char* r = in + 4;
    const unsigned char* end = in + len;
    for (int i = 0; i < n16; i++) {
        if (r >= end) return -1;
        int k = *r++;
        kinds[i] = k;
        if (k == 1) { if (r + 8 > end) return -1; std::memcpy(&ivals[i], r, 8); r += 8; }
        else if (k == 2) { if (r + 8 > end) return -1; std::memcpy(&dvals[i], r, 8); r += 8; }
        else if (k == 3) { if (r + 1 > end) return -1; ivals[i] = *r++; }
        else if (k == 4) {
            uint32_t l;
            if (r + 4 > end) return -1;
            std::memcpy(&l, r, 4); r += 4;
            if (r + l > end) return -1;
            soffs[i] = (long long)(r - in);
            slens[i] = (int)l;
            r += l;
        } else if (k != 0) return -1;
    }
    *version = v16;
    return n16;
}

}  // extern "C"
