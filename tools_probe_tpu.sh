#!/bin/bash
# TPU tunnel probe loop (VERDICT r2 item 1, r3 item 1): log every probe
# with a timestamp so a wedged tunnel is attributable to environment,
# not the framework.  Appends one line per probe to .tpu_probe.log.
#
# r4 fixes: the r3 loop grepped for PLATFORM=tpu, which can never match
# the axon tunnel's platform string ("axon") — successful probes were
# logged as anonymous rc=0 lines and the loop never exited.
#
# ISSUE 17 rewrite: the probe itself moved into
# nebula_tpu/tools/probe_device.py — ONE bounded-timeout subprocess
# probe shared with bench.py, emitting a structured JSON verdict
# ({"probe_status": ok|no_devices|timeout|error, ...}) and a
# script-friendly exit code (0=ok 2=no_devices 3=timeout 4=error).
# This loop now branches on the EXIT CODE, not on stdout greps — the
# class of "platform string never matches" wedges is gone, and the
# same verdict lands verbatim in the bench multichip block.
#
# A lockfile (.tpu_in_use, created by bench.py around device runs)
# skips probing while a bench run holds the chip; when a probe lands
# OK and the .auto_bench flag file exists, the flag is consumed and a
# full-scale bench.py launches immediately (r4 continuation: a tunnel
# recovery is never wasted waiting for a turn of the build loop).
LOG=/root/repo/.tpu_probe.log
LOCK=/root/repo/.tpu_in_use
FLAG=/root/repo/.auto_bench
while true; do
  TS=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
  if [ -e "$LOCK" ]; then
    echo "$TS probe SKIPPED (chip held by $(cat "$LOCK" 2>/dev/null))" >> "$LOG"
  else
    OUT=$(cd /root/repo && python -m nebula_tpu.tools.probe_device --timeout 150 2>/dev/null)
    RC=$?
    case $RC in
      0)
        echo "$TS probe OK: $OUT" >> "$LOG"
        if [ -e "$FLAG" ]; then
          rm -f "$FLAG"
          echo "$TS AUTO-LAUNCH full-scale bench.py" >> "$LOG"
          (cd /root/repo && nohup python bench.py > bench_r5_tpu_auto.log 2>&1 &)
          sleep 120   # let the bench take the chip lock before re-probing
        fi
        ;;
      3)
        echo "$TS probe TIMEOUT (150s) — tunnel wedged: $OUT" >> "$LOG"
        ;;
      *)
        echo "$TS probe rc=$RC: $OUT" >> "$LOG"
        ;;
    esac
  fi
  sleep 600
done
