#!/bin/bash
# TPU tunnel probe loop (VERDICT r2 item 1, r3 item 1): log every probe
# with a timestamp so a wedged tunnel is attributable to environment,
# not the framework.  Appends one line per probe to .tpu_probe.log.
#
# r4 fixes: the r3 loop grepped for PLATFORM=tpu, which can never match
# the axon tunnel's platform string ("axon") — successful probes were
# logged as anonymous rc=0 lines and the loop never exited.  Now any
# non-cpu platform counts as OK, the FULL probe stdout is logged, the
# probe's own exit status is captured (not the log pipeline's), and a
# lockfile (.tpu_in_use, created by bench.py around device runs) skips
# probing while a bench run holds the chip (concurrent clients contend
# for the single chip claim and can wedge the tunnel).
#
# r4 continuation: auto-launch.  When a probe lands OK and the
# .auto_bench flag file exists, the flag is consumed and a full-scale
# bench.py launches immediately — a tunnel recovery is never wasted
# waiting for a turn of the build loop (VERDICT r3 item 1: "the moment
# a probe lands, run bench.py at full scale").
LOG=/root/repo/.tpu_probe.log
LOCK=/root/repo/.tpu_in_use
FLAG=/root/repo/.auto_bench
TMP=$(mktemp)
trap 'rm -f "$TMP"' EXIT
while true; do
  TS=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
  if [ -e "$LOCK" ]; then
    echo "$TS probe SKIPPED (chip held by $(cat "$LOCK" 2>/dev/null))" >> "$LOG"
  else
    timeout 150 python -c "import jax; d=jax.devices(); print('PLATFORM='+d[0].platform+' N='+str(len(d)))" > "$TMP" 2>&1
    RC=$?
    OUT=$(grep -v "^WARNING" "$TMP" | tail -2 | tr '\n' ' ')
    if [ $RC -eq 124 ] || [ $RC -eq 143 ]; then
      echo "$TS probe TIMEOUT (150s) — tunnel wedged" >> "$LOG"
    elif echo "$OUT" | grep -qE "PLATFORM=(tpu|axon)"; then
      echo "$TS probe OK: $OUT" >> "$LOG"
      if [ -e "$FLAG" ]; then
        rm -f "$FLAG"
        echo "$TS AUTO-LAUNCH full-scale bench.py" >> "$LOG"
        (cd /root/repo && nohup python bench.py > bench_r5_tpu_auto.log 2>&1 &)
        sleep 120   # let the bench take the chip lock before re-probing
      fi
    else
      echo "$TS probe rc=$RC: $OUT" >> "$LOG"
    fi
  fi
  sleep 600
done
