#!/bin/bash
# TPU tunnel probe loop (VERDICT r2 item 1): log every probe with a
# timestamp so a wedged tunnel is attributable to environment, not the
# framework.  Appends one line per probe to .tpu_probe.log; exits as
# soon as a probe succeeds (leaving PLATFORM=tpu as the last line).
LOG=/root/repo/.tpu_probe.log
while true; do
  TS=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
  OUT=$(timeout 150 python -c "import jax; d=jax.devices(); print('PLATFORM='+d[0].platform)" 2>&1 | tail -1)
  RC=$?
  if [ $RC -eq 124 ] || [ $RC -eq 143 ]; then
    echo "$TS probe TIMEOUT (150s) — tunnel wedged" >> "$LOG"
  elif echo "$OUT" | grep -q "PLATFORM=tpu"; then
    echo "$TS probe OK: $OUT" >> "$LOG"
    exit 0
  else
    echo "$TS probe rc=$RC: $OUT" >> "$LOG"
  fi
  sleep 600
done
