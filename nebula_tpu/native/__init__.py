"""ctypes loader for the C++ native kernels (native/nebula_native.cc).

Builds the shared library on first use if it's missing (g++ is in the
image; ~1s compile, cached next to the source).  Every entry point has a
NumPy/Python fallback so the framework runs without a toolchain — the
native path is the fast path, never the only path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_so = os.path.join(_dir, "libnebula_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_dir, "nebula_native.cc")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-o", _so, src],
            check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, or None (callers use their fallback)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_so) or (
                os.path.exists(os.path.join(_dir, "nebula_native.cc"))
                and os.path.getmtime(_so) <
                os.path.getmtime(os.path.join(_dir, "nebula_native.cc"))):
            if not _build() and not os.path.exists(_so):
                return None
        try:
            lib = ctypes.CDLL(_so)
            lib.csv_ingest.restype = ctypes.c_longlong
            lib.csv_ingest.argtypes = [
                ctypes.c_char_p, ctypes.c_char, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p)]
            lib.build_csr.restype = ctypes.c_longlong
            lib.build_csr.argtypes = [
                ctypes.c_longlong, ctypes.c_int, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong]
            lib.row_encode.restype = ctypes.c_longlong
            lib.row_encode.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong]
            lib.row_decode.restype = ctypes.c_longlong
            lib.row_decode.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_longlong),
                ctypes.POINTER(ctypes.c_int), ctypes.c_int]
        except (OSError, AttributeError):
            # unloadable OR stale .so missing a symbol — fall back to
            # the Python paths rather than crashing callers
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None
