"""High-level wrappers over the native library, with NumPy fallbacks.

build_coo_csr: COO edge arrays → padded per-part CSR + permutation (the
snapshot builder's hot loop).  csv_ingest: delimited file → typed
columns.  row codec: binary row encode/decode (bulk export format).
"""
from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import get_lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def build_coo_csr(src_dense: np.ndarray, dst_dense: np.ndarray,
                  rank: np.ndarray, dst_key: np.ndarray, P: int,
                  vmax: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                      np.ndarray, int]:
    """→ (indptr (P, vmax+1) i32, nbr (P, emax) i32, rank (P, emax) i32,
    perm (P, emax) i64, emax).  perm[p, slot] is the COO index whose
    edge landed in that slot (for property-column gathers); -1 pad."""
    n = int(src_dense.shape[0])
    if n == 0:
        return (np.zeros((P, vmax + 1), np.int32),
                np.full((P, 1), -1, np.int32),
                np.zeros((P, 1), np.int32),
                np.full((P, 1), -1, np.int64), 1)
    src_dense = np.ascontiguousarray(src_dense, np.int64)
    dst_dense = np.ascontiguousarray(dst_dense, np.int64)
    rank = np.ascontiguousarray(rank, np.int64)
    dst_key = np.ascontiguousarray(dst_key, np.int64)
    counts = np.bincount((src_dense % P).astype(np.int64), minlength=P)
    emax = max(1, int(counts.max()))

    lib = get_lib()
    if lib is not None:
        indptr = np.zeros((P, vmax + 1), np.int32)
        nbr = np.full((P, emax), -1, np.int32)
        rk = np.zeros((P, emax), np.int32)
        perm = np.full((P, emax), -1, np.int64)
        got = lib.build_csr(n, P, vmax, _ptr(src_dense), _ptr(dst_dense),
                            _ptr(rank), _ptr(dst_key), _ptr(perm),
                            _ptr(indptr), _ptr(nbr), _ptr(rk), emax)
        if got == emax:
            return indptr, nbr, rk, perm, emax
        # fall through to numpy on unexpected failure
    return _numpy_coo_csr(src_dense, dst_dense, rank, dst_key, P, vmax,
                          emax)


def _numpy_coo_csr(src_dense, dst_dense, rank, dst_key, P, vmax, emax):
    """The pure-numpy twin of the native build (identical slot order:
    part, local, rank, dst_key, idx) — the fallback AND the property
    tests' oracle for the C path."""
    n = int(src_dense.shape[0])
    part = src_dense % P
    local = src_dense // P
    order = np.lexsort((np.arange(n), dst_key, rank, local, part))
    indptr = np.zeros((P, vmax + 1), np.int32)
    nbr = np.full((P, emax), -1, np.int32)
    rk = np.zeros((P, emax), np.int32)
    perm = np.full((P, emax), -1, np.int64)
    pos = np.zeros(P, np.int64)
    sp = part[order]
    sl = local[order]
    for k in range(n):
        p = int(sp[k])
        slot = int(pos[p])
        pos[p] += 1
        e = int(order[k])
        perm[p, slot] = e
        nbr[p, slot] = dst_dense[e]
        rk[p, slot] = rank[e]
        indptr[p, sl[k] + 1] += 1
    np.cumsum(indptr, axis=1, out=indptr)
    return indptr, nbr, rk, perm, emax


def dst_sort_key(dst_vids: Sequence) -> np.ndarray:
    """int64 ordering key per neighbor: the vid itself for ints, the
    sorted-unique ordinal for strings (matches _nbr_key)."""
    if not dst_vids:
        return np.zeros(0, np.int64)
    if isinstance(dst_vids[0], int):
        return np.asarray(dst_vids, np.int64)
    arr = np.asarray([str(v) for v in dst_vids], dtype=object)
    _, inv = np.unique(arr, return_inverse=True)
    return inv.astype(np.int64)


def csv_ingest(path: str, col_types: List[str], delim: str = ",",
               skip_header: bool = True, max_rows: Optional[int] = None
               ) -> Optional[List[np.ndarray]]:
    """Parse a delimited file natively. col_types: 'int' | 'float' |
    'strhash' | 'skip'.  Returns per-column arrays (int64 for
    int/strhash, float64 for float, None for skip); None if the native
    library is unavailable (caller uses csv.reader).  Raises ValueError
    if the file exceeds max_rows (never truncates silently)."""
    import os
    lib = get_lib()
    if lib is None:
        return None
    tmap = {"int": 0, "float": 1, "strhash": 2, "skip": 3}
    kinds = [tmap[t] for t in col_types]
    n_cols = len(kinds)
    if max_rows is None:
        # a row needs >= n_cols delimiters/newline bytes, so the row
        # count is bounded by size/n_cols — sizes buffers to the file
        # instead of a fixed half-GB-per-column worst case
        max_rows = os.path.getsize(path) // max(1, n_cols) + 2
    ctypes_kinds = (ctypes.c_int * n_cols)(*kinds)
    icols = [np.zeros(max_rows, np.int64) if k in (0, 2)
             else np.zeros(0, np.int64) for k in kinds]
    dcols = [np.zeros(max_rows, np.float64) if k == 1
             else np.zeros(0, np.float64) for k in kinds]
    iptrs = (ctypes.c_void_p * n_cols)(*[_ptr(a) for a in icols])
    dptrs = (ctypes.c_void_p * n_cols)(*[_ptr(a) for a in dcols])
    n = lib.csv_ingest(path.encode(), delim.encode(), int(skip_header),
                       n_cols, ctypes_kinds, max_rows, iptrs, dptrs)
    if n == -2:
        raise ValueError(f"{path}: more rows than max_rows={max_rows}")
    if n == -3:
        raise ValueError(f"{path}: malformed record (short row or "
                         f"unparseable int/float field)")
    if n < 0:
        return None
    out: List[Optional[np.ndarray]] = []
    for i, k in enumerate(kinds):
        if k in (0, 2):
            out.append(icols[i][:n].copy())
        elif k == 1:
            out.append(dcols[i][:n].copy())
        else:
            out.append(None)
    return out


def encode_row(version: int, props: List[tuple]) -> Optional[bytes]:
    """Binary row encode (RowWriterV2 analog).  props: list of
    (kind, value) with kind in {'null','int','double','bool','str'}.
    None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    kmap = {"null": 0, "int": 1, "double": 2, "bool": 3, "str": 4}
    n = len(props)
    kinds = (ctypes.c_int * n)(*[kmap[k] for k, _ in props])
    ivals = (ctypes.c_int64 * n)()
    dvals = (ctypes.c_double * n)()
    svals = (ctypes.c_char_p * n)()
    slens = (ctypes.c_int * n)()
    bufs = []                       # keep encoded strings alive
    need = 4
    for i, (k, v) in enumerate(props):
        need += 1
        if k == "int":
            ivals[i] = int(v)
            need += 8
        elif k == "double":
            dvals[i] = float(v)
            need += 8
        elif k == "bool":
            ivals[i] = int(bool(v))
            need += 1
        elif k == "str":
            b = v.encode() if isinstance(v, str) else bytes(v)
            bufs.append(b)
            svals[i] = b
            slens[i] = len(b)
            need += 4 + len(b)
    out = (ctypes.c_ubyte * need)()
    got = lib.row_encode(version, n, kinds, ivals, dvals, svals, slens,
                         out, need)
    if got < 0:
        return None
    return bytes(out[:got])


def decode_row(data: bytes, max_props: int = 256
               ) -> Optional[tuple]:
    """→ (version, [(kind, value), ...]) or None (lib unavailable or
    malformed input)."""
    lib = get_lib()
    if lib is None:
        return None
    buf = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
    ver = ctypes.c_int()
    kinds = (ctypes.c_int * max_props)()
    ivals = (ctypes.c_int64 * max_props)()
    dvals = (ctypes.c_double * max_props)()
    soffs = (ctypes.c_longlong * max_props)()
    slens = (ctypes.c_int * max_props)()
    n = lib.row_decode(buf, len(data), ctypes.byref(ver), kinds, ivals,
                       dvals, soffs, slens, max_props)
    if n < 0:
        return None
    rmap = {0: "null", 1: "int", 2: "double", 3: "bool", 4: "str"}
    out = []
    for i in range(n):
        k = rmap[kinds[i]]
        if k == "int":
            out.append((k, int(ivals[i])))
        elif k == "double":
            out.append((k, float(dvals[i])))
        elif k == "bool":
            out.append((k, bool(ivals[i])))
        elif k == "str":
            out.append((k, data[soffs[i]:soffs[i] + slens[i]].decode()))
        else:
            out.append((k, None))
    return ver.value, out


def fnv1a(s: str) -> int:
    """Python mirror of the native string hash (for joining strhash
    columns back to actual strings)."""
    h = 1469598103934665603
    for b in s.encode():
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h - (1 << 64) if h >= (1 << 63) else h
