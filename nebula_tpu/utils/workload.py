"""Live workload plane (ISSUE 9): who is running WHAT, right now.

PROFILE and the flight recorder (ISSUE 8) only report statements after
they complete; the admission-control work (ROADMAP item 2) needs to see
the cluster's in-flight state — and a wedged statement (the jaxlib
serve-while-repin deadlock, a stuck RPC) needs to leave evidence while
it is still wedged, not after the 870 s budget burned.  Three pieces:

  * `WorkloadRegistry` — every executing statement registers a
    `LiveQuery` carrying live progress: the plan node currently
    running, rows produced so far, queue-wait vs device vs host µs,
    memory charged.  The scheduler updates it per plan node (a handful
    of attribute writes — the ≤2 % overhead budget), the device
    runtime adds queue/dispatch time through the `current_live()`
    thread-local.  Served by `SHOW QUERIES` / `SHOW SESSIONS`,
    `GET /queries` on every daemon, and metad's federated
    `GET /cluster_queries`.

  * `DispatchTable` — the device dispatch queue's live state: each
    kernel dispatch registers queued→running→done transitions, so the
    queue depth gauge and the stall watchdog see a dispatch that never
    came back.  Kept here (not in the tpu package) so the webservice
    and watchdog never import jax.

  * `StallWatchdog` — a daemon thread that scans both tables every
    `stall_watchdog_interval_secs`.  Any statement exceeding its
    deadline-derived stall threshold (or any dispatch stuck past
    `stall_default_secs`) gets ONE capture: all thread stacks, the
    in-flight dispatch table, the kernel-ledger tail and the live
    registry snapshot, appended to a bounded ring (`GET /stalls`,
    `SHOW STALLS`) plus a forced flight-recorder entry — purely
    observational, the stalled statement is never touched.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from .config import define_flag, get_config

define_flag("workload_plane_enabled", True,
            "maintain the live per-statement registry behind "
            "SHOW QUERIES / GET /queries (off = register nothing; "
            "the A/B lever for the bench overhead probe)")
define_flag("stall_watchdog_interval_secs", 1.0,
            "how often the stall watchdog scans in-flight statements "
            "and device dispatches (0 disables the thread; scan_once() "
            "still works for tests)")
define_flag("stall_threshold_secs", 0.0,
            "flat stall threshold; 0 derives it per statement from the "
            "deadline budget (stall_deadline_fraction) or falls back "
            "to stall_default_secs when unbudgeted")
define_flag("stall_deadline_fraction", 0.5,
            "deadline-derived threshold: a statement is stalled once "
            "it has burned this fraction of its query_timeout_secs "
            "budget without finishing")
define_flag("stall_default_secs", 20.0,
            "stall threshold for unbudgeted statements and for device "
            "dispatches (which carry no deadline of their own)")
define_flag("stall_ring_capacity", 64,
            "stall captures retained in the ring behind GET /stalls")


# -- live statement registry ------------------------------------------------


class LiveQuery:
    """One in-flight statement's live progress.  Hot-path writers touch
    single attributes (GIL-atomic); the small lock only guards the
    read-modify-write accumulators."""

    __slots__ = ("qid", "session", "user", "stmt", "kind", "t0", "m0",
                 "deadline", "node_kind", "node_id", "nodes_done",
                 "rows", "queue_us", "device_us", "dispatches",
                 "tracker", "killed", "queued", "consistency",
                 "batch_id", "lane", "batch_lanes", "fingerprint",
                 "_lock")

    def __init__(self, qid: int, session: int, user: str, stmt: str,
                 kind: str, deadline: Optional[float] = None,
                 tracker=None, consistency: str = "leader",
                 fingerprint: Optional[str] = None):
        self.qid = qid
        self.session = session
        self.user = user
        self.stmt = stmt
        self.kind = kind
        self.t0 = time.time()
        self.m0 = time.monotonic()
        self.deadline = deadline          # absolute time.monotonic()
        self.node_kind = ""               # current plan node
        self.node_id = -1
        self.nodes_done = 0
        self.rows = 0                     # rows produced by DONE nodes
        self.queue_us = 0                 # device dispatch-queue wait
        self.device_us = 0                # device run time
        self.dispatches = 0
        self.tracker = tracker            # MemoryTracker (bytes charged)
        self.killed = False
        self.queued = False               # waiting in the admission queue
        # the statement's effective read-consistency level (ISSUE 11):
        # surfaced in SHOW QUERIES so an operator can see which reads
        # are leader-bound vs replica-spread at a glance
        self.consistency = consistency
        # multi-lane batched dispatch (ISSUE 15): while this statement
        # is enrolled in a forming/in-flight device batch, the group id
        # and this statement's lane — SHOW QUERIES renders "bid/lane"
        self.batch_id: Optional[int] = None
        self.lane: Optional[int] = None
        # lanes the statement actually shared a launch with (ISSUE 16:
        # the insights registry's batching-share column reads this at
        # completion; stays 0 for solo dispatches)
        self.batch_lanes: int = 0
        # statement fingerprint (ISSUE 16): joins this in-flight row
        # against the aggregate SHOW STATEMENTS table
        self.fingerprint = fingerprint or ""
        self._lock = threading.Lock()

    # -- scheduler hooks (one per plan node) -----------------------------

    def node_start(self, kind: str, node_id: int):
        self.node_kind = kind
        self.node_id = node_id

    def node_done(self, rows: int):
        with self._lock:
            self.nodes_done += 1
            self.rows += int(rows)

    def set_operator(self, label: str):
        """Finer-than-node progress (fused pipeline segments)."""
        self.node_kind = label

    # -- runtime hooks ---------------------------------------------------

    def add(self, field: str, n: int):
        with self._lock:
            setattr(self, field, getattr(self, field) + int(n))

    # -- reading ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        elapsed_us = int((time.monotonic() - self.m0) * 1e6)
        queue_us, device_us = self.queue_us, self.device_us
        host_us = max(elapsed_us - queue_us - device_us, 0)
        return {
            "qid": self.qid, "session": self.session, "user": self.user,
            "stmt": self.stmt[:500], "kind": self.kind,
            "status": ("KILLED" if self.killed
                       else "QUEUED" if self.queued else "RUNNING"),
            "start_ts": self.t0,
            "duration_us": elapsed_us,
            "operator": (f"{self.node_kind}#{self.node_id}"
                         if self.node_kind else ""),
            "nodes_done": self.nodes_done,
            "rows": self.rows,
            "queue_us": queue_us,
            "device_us": device_us,
            "host_us": host_us,
            "dispatches": self.dispatches,
            "memory_bytes": int(getattr(self.tracker, "used", 0) or 0),
            "consistency": self.consistency,
            "batch": (f"{self.batch_id}/{self.lane}"
                      if self.batch_id is not None else ""),
            "fingerprint": self.fingerprint,
        }


class WorkloadRegistry:
    """Process-wide map of in-flight statements (all engines)."""

    def __init__(self):
        self._live: Dict[int, LiveQuery] = {}
        self._lock = threading.Lock()

    @staticmethod
    def enabled() -> bool:
        try:
            return bool(get_config().get("workload_plane_enabled"))
        except Exception:  # noqa: BLE001 — config not initialized
            return True

    def register(self, **kw) -> Optional[LiveQuery]:
        if not self.enabled():
            return None
        lq = LiveQuery(**kw)
        with self._lock:
            self._live[lq.qid] = lq
            n = len(self._live)
        from .stats import stats
        stats().gauge("live_queries", float(n))
        return lq

    def deregister(self, qid: int):
        with self._lock:
            if self._live.pop(qid, None) is None:
                return
            n = len(self._live)
        from .stats import stats
        stats().gauge("live_queries", float(n))

    def get(self, qid: int) -> Optional[LiveQuery]:
        with self._lock:
            return self._live.get(qid)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            entries = list(self._live.values())
        return [e.snapshot() for e in sorted(entries,
                                             key=lambda x: x.qid)]

    def __len__(self):
        with self._lock:
            return len(self._live)


_registry = WorkloadRegistry()


def live_registry() -> WorkloadRegistry:
    """The process-wide registry (served at /queries, SHOW QUERIES)."""
    return _registry


_live_tls = threading.local()


def current_live() -> Optional[LiveQuery]:
    return getattr(_live_tls, "live", None)


class _LiveGuard:
    __slots__ = ("_lq", "_prev")

    def __init__(self, lq: Optional[LiveQuery]):
        self._lq = lq

    def __enter__(self):
        self._prev = getattr(_live_tls, "live", None)
        _live_tls.live = self._lq
        return self._lq

    def __exit__(self, *exc):
        _live_tls.live = self._prev
        return False


def use_live(lq: Optional[LiveQuery]) -> _LiveGuard:
    """Install `lq` as this thread's live-progress target (mirrors
    use_work/use_cost: the scheduler re-installs it on fan-out pool
    threads so device queue/dispatch time attributes to the right
    statement)."""
    return _LiveGuard(lq)


# -- device dispatch table --------------------------------------------------


class _DispatchToken:
    __slots__ = ("seq", "kernel", "qid", "t_queued", "t_run", "thread")

    def __init__(self, seq: int, kernel: str, qid: Optional[int]):
        self.seq = seq
        self.kernel = kernel
        self.qid = qid
        self.t_queued = time.monotonic()
        self.t_run: Optional[float] = None
        self.thread = threading.get_ident()

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        running = self.t_run is not None
        return {
            "seq": self.seq, "kernel": self.kernel, "qid": self.qid,
            "state": "running" if running else "queued",
            "wait_us": int(((self.t_run if running else now)
                            - self.t_queued) * 1e6),
            "run_us": int((now - self.t_run) * 1e6) if running else 0,
            "thread": self.thread,
        }


class DispatchTable:
    """Live device dispatches: queued (waiting on the dispatch gate) or
    running (inside the jitted call).  The runtime drives transitions;
    the watchdog and GET /queries read."""

    def __init__(self):
        self._inflight: Dict[int, _DispatchToken] = {}
        self._lock = threading.Lock()
        self._seq = 0

    def enter(self, kernel: str) -> _DispatchToken:
        lv = current_live()
        with self._lock:
            self._seq += 1
            tok = _DispatchToken(self._seq, kernel,
                                 lv.qid if lv is not None else None)
            self._inflight[tok.seq] = tok
        self._gauge()
        return tok

    def mark_running(self, tok: _DispatchToken) -> int:
        """Gate acquired → running.  Returns the queue wait in µs."""
        tok.t_run = time.monotonic()
        self._gauge()
        return int((tok.t_run - tok.t_queued) * 1e6)

    def exit(self, tok: _DispatchToken):
        with self._lock:
            self._inflight.pop(tok.seq, None)
        self._gauge()

    def _gauge(self):
        with self._lock:
            queued = sum(1 for t in self._inflight.values()
                         if t.t_run is None)
        from .stats import stats
        stats().gauge("tpu_dispatch_queue_depth", float(queued))

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            toks = list(self._inflight.values())
        return [t.snapshot() for t in sorted(toks, key=lambda x: x.seq)]

    def queued_depth(self) -> int:
        """Dispatches waiting on the gate right now — the overload
        signal `tpu_dispatch_queue_cap` (utils/admission.py) judges."""
        with self._lock:
            return sum(1 for t in self._inflight.values()
                       if t.t_run is None)

    def __len__(self):
        with self._lock:
            return len(self._inflight)


_dispatches = DispatchTable()


def dispatch_table() -> DispatchTable:
    return _dispatches


# -- stall watchdog ---------------------------------------------------------


def _thread_stacks() -> Dict[str, List[str]]:
    """Formatted stack of every live thread, keyed `name (ident)` —
    the post-mortem a wedged jaxlib dispatch otherwise denies us."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, '?')} ({ident})"
        out[key] = [ln.rstrip("\n") for ln in
                    traceback.format_stack(frame)]
    return out


class StallWatchdog:
    """Scans the live registry + dispatch table; captures each stalled
    entity exactly once into a bounded ring."""

    def __init__(self):
        self._ring: "deque[dict]" = deque()
        self._lock = threading.Lock()
        self._seq = 0
        self._seen_q: set = set()         # qids already captured
        self._seen_d: set = set()         # dispatch seqs already captured
        # serializes whole scans: the background thread and an on-
        # demand scan_once() caller (tests, tools) must not both pass
        # the seen-set check for one stalled entity — "captured
        # exactly once" is the documented contract
        self._scan_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- thresholds ------------------------------------------------------

    @staticmethod
    def _flags():
        cfg = get_config()

        def g(name, dflt):
            try:
                return float(cfg.get(name))
            except Exception:  # noqa: BLE001 — config not initialized
                return dflt
        return (g("stall_threshold_secs", 0.0),
                g("stall_deadline_fraction", 0.5),
                g("stall_default_secs", 20.0))

    @staticmethod
    def _stmt_threshold(lq: LiveQuery, flat: float, frac: float,
                        dflt: float) -> float:
        if flat > 0:
            return flat
        if lq.deadline is not None:
            budget = lq.deadline - lq.m0
            if budget > 0:
                return budget * frac
        return dflt

    @staticmethod
    def stmt_threshold_s(lq: LiveQuery) -> float:
        """Deadline-derived: a budgeted statement is stalled once it
        burned `stall_deadline_fraction` of its budget; an unbudgeted
        one after `stall_default_secs`.  `stall_threshold_secs` > 0
        overrides both (the test/ops lever)."""
        return StallWatchdog._stmt_threshold(lq, *StallWatchdog._flags())

    # -- scanning --------------------------------------------------------

    def scan_once(self) -> int:
        """One scan; returns the number of NEW stall captures THIS call
        made.  Scans serialize on _scan_lock, so the background thread
        and an on-demand caller can never double-capture one entity —
        whoever scans first wins, the other sees it in _seen_*."""
        with self._scan_lock:
            now = time.monotonic()
            captured = 0
            flat, frac, dflt = self._flags()
            for lq in list(live_registry()._live.values()):
                if lq.qid in self._seen_q:
                    continue
                elapsed = now - lq.m0
                thr = self._stmt_threshold(lq, flat, frac, dflt)
                if elapsed > thr:
                    self._seen_q.add(lq.qid)
                    self._capture("statement", lq.snapshot(), elapsed,
                                  thr)
                    self._flight_capture(lq, elapsed)
                    captured += 1
            d_thr = flat if flat > 0 else dflt
            for tok in list(dispatch_table()._inflight.values()):
                if tok.seq in self._seen_d:
                    continue
                elapsed = now - tok.t_queued
                if elapsed > d_thr:
                    self._seen_d.add(tok.seq)
                    self._capture("dispatch", tok.snapshot(), elapsed,
                                  d_thr)
                    captured += 1
            # forget finished entities so their ids can't leak the
            # sets (set(dict) is one C-level pass — atomic under the
            # GIL, unlike a comprehension racing register() inserts)
            self._seen_q &= set(live_registry()._live)
            self._seen_d &= set(dispatch_table()._inflight)
            return captured

    def _capture(self, kind: str, subject: Dict[str, Any],
                 elapsed: float, threshold: float):
        from .flight import kernel_ledger
        from .stats import stats
        entry = {
            "ts": time.time(),
            "kind": kind,
            "subject": subject,
            "elapsed_s": round(elapsed, 3),
            "threshold_s": round(threshold, 3),
            "stacks": _thread_stacks(),
            "dispatches": dispatch_table().snapshot(),
            "kernels": kernel_ledger().list(limit=16),
            "live": live_registry().snapshot(),
        }
        try:
            cap = int(get_config().get("stall_ring_capacity"))
        except Exception:  # noqa: BLE001
            cap = 64
        with self._lock:
            self._seq += 1
            entry["id"] = self._seq
            self._ring.append(entry)
            while len(self._ring) > max(cap, 1):
                self._ring.popleft()
        stats().inc_labeled("stall_events", {"kind": kind})

    @staticmethod
    def _flight_capture(lq: LiveQuery, elapsed: float):
        """Forced flight-recorder entry for the stalled statement — the
        incident evidence survives even if the statement never
        completes (its own completion record would then never land)."""
        from .flight import flight_recorder
        try:
            flight_recorder().record(
                stmt=lq.stmt, kind=lq.kind,
                latency_us=int(elapsed * 1e6), error=None,
                trace_id=None, session=lq.session,
                operators=[lq.snapshot()], force="stalled",
                fingerprint=lq.fingerprint)
        except Exception:  # noqa: BLE001 — watchdog must never throw
            pass

    # -- reading ---------------------------------------------------------

    def get(self, entry_id: int) -> Optional[dict]:
        with self._lock:
            for e in self._ring:
                if e["id"] == entry_id:
                    return e
        return None

    def list(self, limit: int = 20) -> List[dict]:
        """Newest-first summaries (no stack bodies)."""
        if limit <= 0:
            return []
        with self._lock:
            entries = list(self._ring)
        return [{"id": e["id"], "ts": e["ts"], "kind": e["kind"],
                 "elapsed_s": e["elapsed_s"],
                 "threshold_s": e["threshold_s"],
                 "subject": {k: v for k, v in e["subject"].items()
                             if k != "stmt"} | (
                     {"stmt": e["subject"]["stmt"][:120]}
                     if "stmt" in e["subject"] else {}),
                 "threads": len(e["stacks"])}
                for e in reversed(entries[-limit:])]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._seen_q.clear()
            self._seen_d.clear()

    # -- lifecycle -------------------------------------------------------

    def ensure_started(self):
        """Idempotent: start the scan thread if the interval flag says
        so and it is not already running (engines call this at
        construction; tests drive scan_once() directly)."""
        if self._thread is not None and self._thread.is_alive():
            return
        try:
            interval = float(get_config().get(
                "stall_watchdog_interval_secs"))
        except Exception:  # noqa: BLE001
            interval = 1.0
        if interval <= 0:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scan_once()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="stall-watchdog")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2)


_watchdog = StallWatchdog()


def stall_watchdog() -> StallWatchdog:
    """The process-wide watchdog (served at /stalls, SHOW STALLS)."""
    return _watchdog
