"""Cooperative cancellation + deadline budgets (ISSUE 5).

One thread-local pair (kill event, absolute monotonic deadline) is the
statement's cancellation context:

  - graphd's engine installs it around the scheduler run (the statement
    timeout flag `query_timeout_secs` becomes the deadline);
  - the scheduler re-installs it on plan-branch pool threads (like the
    trace/work contexts) and checks it between plan nodes;
  - the RPC client clamps every call's timeout to the remaining budget
    and stamps the REMAINING seconds into the request envelope ("dl"),
    so each hop re-derives an absolute deadline from its own clock —
    relative propagation is clock-skew-free;
  - the RPC server re-installs the context around the handler, which is
    what decrements the budget across graphd → storaged → metad hops;
  - long waits (storage fan-out, TPU pipeline segments) poll it.

`DeadlineExceeded` surfaces to the client as `E_QUERY_TIMEOUT`.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["DeadlineExceeded", "QueryKilled", "use_cancel", "check",
           "current_kill", "current_deadline", "remaining"]


class DeadlineExceeded(Exception):
    """The statement's deadline budget is spent (→ E_QUERY_TIMEOUT)."""


class QueryKilled(Exception):
    """The statement's kill event fired (KILL QUERY)."""


_tls = threading.local()


def current_kill() -> Optional[threading.Event]:
    return getattr(_tls, "kill", None)


def current_deadline() -> Optional[float]:
    """Absolute time.monotonic() deadline, or None when unbudgeted."""
    return getattr(_tls, "deadline", None)


def remaining() -> Optional[float]:
    dl = current_deadline()
    if dl is None:
        return None
    return dl - time.monotonic()


def check():
    """Raise if the current context is killed or out of budget."""
    ev = current_kill()
    if ev is not None and ev.is_set():
        raise QueryKilled("query was killed")
    rem = remaining()
    if rem is not None and rem <= 0:
        raise DeadlineExceeded(
            f"deadline exceeded by {-rem:.3f}s")


class use_cancel:
    """Install (kill, deadline) for the with-block; nests by stacking —
    an inner deadline never LOOSENS the outer one (min wins), and
    None leaves the outer value in place."""

    def __init__(self, kill: Optional[threading.Event] = None,
                 deadline: Optional[float] = None):
        self.kill = kill
        self.deadline = deadline

    def __enter__(self):
        self._pk = getattr(_tls, "kill", None)
        self._pd = getattr(_tls, "deadline", None)
        if self.kill is not None:
            _tls.kill = self.kill
        if self.deadline is not None:
            _tls.deadline = self.deadline if self._pd is None \
                else min(self._pd, self.deadline)
        return self

    def __exit__(self, *exc):
        _tls.kill = self._pk
        _tls.deadline = self._pd
        return False
