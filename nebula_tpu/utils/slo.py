"""SLO engine: multi-window burn rates from the live histograms (ISSUE 8).

Two objectives, computed from telemetry the engine already emits (no
new instrumentation on the hot path):

  * **availability** — fraction of statements that did not error
    (`num_queries` / `num_query_errors` counters); target
    `slo_availability_target` (default 99.9%).
  * **latency** — fraction of statements that finished within
    `slo_latency_target_ms` (from the `query_latency_us_hist`
    cumulative buckets — the threshold snaps to the nearest bucket
    upper bound ≤ target); target `slo_latency_target_pct`.

Burn rate is the standard SRE definition: (bad fraction over a window)
divided by the error budget (1 − target).  Burn 1.0 = consuming budget
exactly at the sustainable rate; 14.4 on the 1h window is the classic
page-now threshold for a 30d budget.  Windows are computed by diffing
periodic snapshots of the cumulative counters (`tick()` — called by the
webservice /slo endpoint, `SHOW SLO`, and the metad federation loop),
so the engine needs no per-request bookkeeping at all.

Surfaced as `slo_burn_*` gauges in /metrics (and therefore in metad's
/cluster_metrics), `GET /slo`, and `SHOW SLO`.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .config import define_flag, get_config

define_flag("slo_availability_target", 0.999,
            "availability objective: fraction of statements that must "
            "not error")
define_flag("slo_latency_target_ms", 1000.0,
            "latency objective threshold (per-statement wall time)")
define_flag("slo_latency_target_pct", 0.99,
            "latency objective: fraction of statements that must "
            "finish under slo_latency_target_ms")

# multi-window burn rates (name → seconds); the long window smooths
# noise, the short window catches a fresh incident fast
WINDOWS: Tuple[Tuple[str, float], ...] = (
    ("5m", 300.0), ("1h", 3600.0), ("6h", 21600.0))

# literal gauge names (one per objective × window) so the metric
# catalogue lint can see them in source — see docs/OBSERVABILITY.md
_BURN_GAUGES: Dict[Tuple[str, str], str] = {
    ("availability", "5m"): "slo_burn_availability_5m",
    ("availability", "1h"): "slo_burn_availability_1h",
    ("availability", "6h"): "slo_burn_availability_6h",
    ("latency", "5m"): "slo_burn_latency_5m",
    ("latency", "1h"): "slo_burn_latency_1h",
    ("latency", "6h"): "slo_burn_latency_6h",
}


class SloEngine:
    """Snapshot-diffing burn-rate calculator over the process stats."""

    def __init__(self):
        self._snaps: List[Tuple[float, Dict[str, float]]] = []
        self._lock = threading.Lock()

    # -- raw totals -------------------------------------------------------

    @staticmethod
    def _totals() -> Dict[str, float]:
        from .stats import stats
        sm = stats()
        with sm.lock:
            queries = float(sm.counters.get("num_queries", 0))
            errors = float(sm.counters.get("num_query_errors", 0))
        lat_total = lat_good = 0.0
        ht = sm.hist_totals("query_latency_us_hist")
        if ht is not None:
            buckets, row = ht
            target_us = float(
                get_config().get("slo_latency_target_ms")) * 1000.0
            cum = 0.0
            for ub, c in zip(buckets, row):
                cum += c
                if ub <= target_us:
                    lat_good = cum
            lat_total = row[-2]
        return {"queries": queries, "errors": errors,
                "lat_total": lat_total, "lat_good": lat_good}

    def tick(self):
        """Record one snapshot; trim history past the longest window."""
        now = time.monotonic()
        tot = self._totals()
        horizon = max(s for _, s in WINDOWS) * 1.2
        with self._lock:
            # collapse bursts: at most ~1 snapshot per second.  SKIP the
            # append (keeping the OLDER snapshot), never replace it — a
            # sub-second poller replacing the newest entry would pin the
            # whole history to "now" and collapse every window base to
            # the last poll interval
            if not self._snaps or now - self._snaps[-1][0] >= 1.0:
                self._snaps.append((now, tot))
            while self._snaps and now - self._snaps[0][0] > horizon:
                self._snaps.pop(0)
        return tot

    def _window_base(self, now: float, secs: float,
                     latest: Dict[str, float]) -> Dict[str, float]:
        """Newest snapshot at least `secs` old.  When history is
        shorter than the window, the base is ZEROS — i.e. the window
        covers the whole process lifetime.  (Diffing from a young
        snapshot instead would silently DROP the pre-snapshot traffic,
        reporting burn 0 over a window that did see errors.)"""
        with self._lock:
            base: Optional[Dict[str, float]] = None
            for ts, tot in self._snaps:
                if now - ts >= secs:
                    base = tot
                else:
                    break
        if base is None:
            base = {k: 0.0 for k in latest}
        return base

    # -- burn rates -------------------------------------------------------

    def burn_rates(self) -> List[Dict[str, Any]]:
        """One row per (objective, window):
        {objective, window, target, total, bad, bad_ratio, burn}.
        Also publishes the `slo_burn_*` gauges."""
        from .stats import stats
        now = time.monotonic()
        latest = self.tick()
        avail_target = float(get_config().get("slo_availability_target"))
        lat_pct = float(get_config().get("slo_latency_target_pct"))
        rows: List[Dict[str, Any]] = []
        for wname, secs in WINDOWS:
            base = self._window_base(now, secs, latest)
            dq = max(latest["queries"] - base.get("queries", 0.0), 0.0)
            de = max(latest["errors"] - base.get("errors", 0.0), 0.0)
            dlt = max(latest["lat_total"] - base.get("lat_total", 0.0), 0.0)
            dlg = max(latest["lat_good"] - base.get("lat_good", 0.0), 0.0)
            for obj, target, total, bad in (
                    ("availability", avail_target, dq, min(de, dq)),
                    ("latency", lat_pct, dlt, max(dlt - dlg, 0.0))):
                budget = 1.0 - target
                ratio = (bad / total) if total > 0 else 0.0
                burn = (ratio / budget) if budget > 0 else 0.0
                rows.append({"objective": obj, "window": wname,
                             "target": target, "total": int(total),
                             "bad": int(bad),
                             "bad_ratio": round(ratio, 6),
                             "burn": round(burn, 4)})
                stats().gauge(_BURN_GAUGES[(obj, wname)], round(burn, 4))
        return rows

    def reset(self):
        with self._lock:
            self._snaps.clear()


_engine = SloEngine()


def slo_engine() -> SloEngine:
    """The process-wide SLO engine (served at /slo and SHOW SLO)."""
    return _engine
