"""Lock-order watchdog — the host plane's sanitizer analog.

The reference gates its threaded C++ through TSan/ASan CI jobs
(reference: .github workflows [UNVERIFIED — empty mount, SURVEY §5 race
detection]).  Python's GIL removes data races on single bytecodes but
NOT deadlocks or atomicity races across await points — the two failure
modes this module targets:

* **Lock-order cycles.**  `make_lock(name)` returns a plain RLock in
  production; with NEBULA_LOCKCHECK=1 it returns a checked wrapper that
  records every cross-lock acquisition edge (held → acquiring) into a
  global graph and raises `LockOrderError` the moment an edge closes a
  cycle — a potential deadlock caught deterministically on ANY
  interleaving that exhibits the order, not only the one that hangs.
  Re-entrant acquires and identical names (per-space sd.locks) are
  exempt.

* **Interleaving amplification.**  `race_amplifier()` is a context
  manager that drops sys.setswitchinterval to 10 µs (from 5 ms), making
  the scheduler preempt between nearly every bytecode — the
  stress-test harness (tests/unit/test_race_stress.py) runs concurrent
  engine/raft/balance workloads under it.
"""
from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, Set, Tuple

_enabled = os.environ.get("NEBULA_LOCKCHECK") == "1"

# directed edges between lock NAMES: (held, acquiring)
_edges: Set[Tuple[str, str]] = set()
_edges_lock = threading.Lock()
_tls = threading.local()


class LockOrderError(RuntimeError):
    pass


def _would_cycle(frm: str, to: str) -> bool:
    """True if adding frm→to closes a directed cycle over _edges."""
    if frm == to:
        return False
    stack, seen = [to], set()
    while stack:
        cur = stack.pop()
        if cur == frm:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(b for (a, b) in _edges if a == cur)
    return False


class CheckedRLock:
    """RLock recording cross-lock acquisition order per thread."""

    __slots__ = ("_lock", "name")

    def __init__(self, name: str):
        self._lock = threading.RLock()
        self.name = name

    def _held(self):
        h = getattr(_tls, "held", None)
        if h is None:
            h = _tls.held = []
        return h

    def acquire(self, blocking=True, timeout=-1):
        held = self._held()
        already = any(n == self.name for n, _ in held)
        if held and not already:
            # a re-entrant acquire ANYWHERE in the stack can never block
            # (the thread owns the lock) — only first acquisitions of a
            # new lock record an order edge
            frm = held[-1][0]
            with _edges_lock:
                if (frm, self.name) not in _edges:
                    if _would_cycle(frm, self.name):
                        raise LockOrderError(
                            f"lock-order cycle: holding `{frm}', "
                            f"acquiring `{self.name}' — the reverse "
                            f"order was already observed")
                    _edges.add((frm, self.name))
        got = self._lock.acquire(blocking, timeout)
        if got:
            if already:
                for i in range(len(held) - 1, -1, -1):
                    if held[i][0] == self.name:
                        held[i] = (self.name, held[i][1] + 1)
                        break
            else:
                held.append((self.name, 1))
        return got

    def release(self):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                if held[i][1] > 1:
                    held[i] = (self.name, held[i][1] - 1)
                else:
                    del held[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


def make_lock(name: str):
    """A named re-entrant lock; order-checked when NEBULA_LOCKCHECK=1."""
    if _enabled:
        return CheckedRLock(name)
    return threading.RLock()


def edges() -> Set[Tuple[str, str]]:
    """Observed acquisition-order edges (for assertions in tests)."""
    return set(_edges)


def reset():
    with _edges_lock:
        _edges.clear()


@contextmanager
def race_amplifier(interval: float = 1e-5):
    """Preempt threads between (nearly) every bytecode for the scope."""
    old = sys.getswitchinterval()
    sys.setswitchinterval(interval)
    try:
        yield
    finally:
        sys.setswitchinterval(old)
