"""StatsManager — counters, gauges, rolling histograms, Prometheus export.

Analog of the reference's src/common/stats StatsManager [UNVERIFIED —
empty mount, SURVEY §0]: named counters (`num_queries`), value series
with rolling windows exposing sum/count/avg/rate and p50/p95/p99
(`query_latency_us`), served by every daemon's `/stats` endpoint.  The
TPU build adds device gauges (HBM bytes pinned, per-hop all_to_all
volume, kernel step time) through the same registry.

The observability layer (ISSUE 1) adds:
  * labeled counters (`inc_labeled`: per-op RPC error counts) and
    fixed-bucket histograms (`observe`: per-RPC-op latency,
    per-statement-kind query latency); raft append/commit counts are
    plain counters (`raft_appends`/`raft_commits`);
  * `to_prometheus()` — the text exposition format served at
    `GET /metrics` (cumulative `_bucket{le=...}` rows, `_sum`/`_count`,
    label escaping per the spec);
  * `WorkCounters` + `use_work`/`current_work` — per-query DETERMINISTIC
    work counts (edges traversed, frontier sizes, RPC calls, wire
    bytes, device dispatches).  Work counts are stable across noisy
    VMs even when timings are not, so bench.py emits them as the
    regression signal (VERDICT weak #8).
"""
from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# fixed latency buckets in MICROSECONDS (histograms carry their own
# bucket tuple, so other units just pass buckets= explicitly)
LATENCY_BUCKETS_US: Tuple[float, ...] = (
    100.0, 500.0, 1_000.0, 5_000.0, 10_000.0, 50_000.0, 100_000.0,
    500_000.0, 1_000_000.0, 5_000_000.0, 10_000_000.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, Any]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _prom_name(name: str) -> str:
    """Prometheus metric names allow [a-zA-Z0-9_:]; ours may carry dots."""
    return "".join(c if (c.isascii() and (c.isalnum() or c in "_:"))
                   else "_" for c in name)


def _prom_label_value(v: str) -> str:
    """Escape per the exposition format: backslash, quote, newline."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(key: _LabelKey, extra: str = "") -> str:
    parts = [f'{_prom_name(k)}="{_prom_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _prom_num(v: float) -> str:
    if isinstance(v, float) and v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class _Series:
    """A value series over a sliding window of seconds."""

    __slots__ = ("window_s", "points", "total_sum", "total_count", "lock")

    def __init__(self, window_s: float = 600.0):
        self.window_s = window_s
        self.points: List[Tuple[float, float]] = []   # (ts, value)
        self.total_sum = 0.0
        self.total_count = 0
        self.lock = threading.Lock()

    def add(self, v: float):
        now = time.monotonic()
        with self.lock:
            self.points.append((now, v))
            self.total_sum += v
            self.total_count += 1
            self._gc(now)

    def _gc(self, now: float):
        cutoff = now - self.window_s
        i = bisect.bisect_left(self.points, (cutoff, float("-inf")))
        if i > 0:
            del self.points[:i]

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self.lock:
            self._gc(now)
            vals = sorted(v for _, v in self.points)
            n = len(vals)
            out = {
                "sum": self.total_sum,
                "count": self.total_count,
                "rate": n / self.window_s,
            }
            if n:
                out["avg"] = sum(vals) / n
                for q in (50, 95, 99):
                    out[f"p{q}"] = vals[min(n - 1, int(n * q / 100))]
            return out


class _Histogram:
    """Fixed-bucket cumulative histogram, one count row per label set.

    Buckets are upper bounds; rendering emits CUMULATIVE counts plus the
    implicit +Inf bucket, so monotonicity holds by construction."""

    __slots__ = ("buckets", "per_label", "lock")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = tuple(sorted(buckets))
        # label key → [bucket counts..., count, sum]
        self.per_label: Dict[_LabelKey, List[float]] = {}
        self.lock = threading.Lock()

    def observe(self, value: float, key: _LabelKey):
        i = bisect.bisect_left(self.buckets, value)
        with self.lock:
            row = self.per_label.get(key)
            if row is None:
                row = self.per_label[key] = \
                    [0] * len(self.buckets) + [0, 0.0]
            if i < len(self.buckets):
                row[i] += 1
            row[-2] += 1
            row[-1] += value


class StatsManager:
    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}
        self.series: Dict[str, _Series] = {}
        self.labeled: Dict[str, Dict[_LabelKey, float]] = {}
        self.labeled_gauges: Dict[str, Dict[_LabelKey, float]] = {}
        self.histograms: Dict[str, _Histogram] = {}
        self.lock = threading.Lock()

    def inc(self, name: str, delta: int = 1):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def inc_labeled(self, name: str, labels: Dict[str, Any],
                    delta: float = 1):
        key = _label_key(labels)
        with self.lock:
            series = self.labeled.setdefault(name, {})
            series[key] = series.get(key, 0) + delta

    def gauge(self, name: str, value: float):
        with self.lock:
            self.gauges[name] = value

    def gauge_labeled(self, name: str, labels: Dict[str, Any],
                      value: float):
        """SET a per-label-set gauge (last write wins — unlike
        inc_labeled's accumulate): the per-shard HBM ledger
        (`tpu_shard_hbm_bytes{shard}`) re-states each shard's residency
        at every pin/unpin instead of summing deltas."""
        key = _label_key(labels)
        with self.lock:
            series = self.labeled_gauges.setdefault(name, {})
            series[key] = value

    def add_value(self, name: str, value: float):
        s = self.series.get(name)
        if s is None:
            with self.lock:
                s = self.series.setdefault(name, _Series())
        s.add(value)

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, Any]] = None,
                buckets: Tuple[float, ...] = LATENCY_BUCKETS_US):
        """Record into a fixed-bucket histogram (created on first use;
        the first caller's buckets win — fixed by design so dashboards
        can diff rounds)."""
        h = self.histograms.get(name)
        if h is None:
            with self.lock:
                h = self.histograms.setdefault(name, _Histogram(buckets))
        h.observe(value, _label_key(labels))

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            out: Dict[str, Any] = dict(self.counters)
            out.update(self.gauges)
            series = dict(self.series)
            labeled = {n: dict(v) for n, v in self.labeled.items()}
            for n, v in self.labeled_gauges.items():
                labeled.setdefault(n, {}).update(v)
            hists = dict(self.histograms)
        for name, s in series.items():
            for k, v in s.snapshot().items():
                out[f"{name}.{k}"] = v
        for name, per in labeled.items():
            for key, v in per.items():
                lbl = ",".join(f"{k}={val}" for k, val in key)
                out[f"{name}{{{lbl}}}"] = v
        for name, h in hists.items():
            with h.lock:
                per = {k: list(row) for k, row in h.per_label.items()}
            for key, row in per.items():
                lbl = ",".join(f"{k}={val}" for k, val in key)
                suffix = f"{{{lbl}}}" if lbl else ""
                out[f"{name}{suffix}.count"] = row[-2]
                out[f"{name}{suffix}.sum"] = row[-1]
        return out

    def to_text(self) -> str:
        snap = self.snapshot()
        return "\n".join(f"{k}={snap[k]}" for k in sorted(snap))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        with self.lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            series = dict(self.series)
            labeled = {n: dict(v) for n, v in self.labeled.items()}
            labeled_g = {n: dict(v)
                         for n, v in self.labeled_gauges.items()}
            hists = dict(self.histograms)
        lines: List[str] = []
        for name in sorted(counters):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {_prom_num(counters[name])}")
        for name in sorted(labeled):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            for key in sorted(labeled[name]):
                lines.append(f"{pn}{_prom_labels(key)} "
                             f"{_prom_num(labeled[name][key])}")
        for name in sorted(gauges):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_prom_num(gauges[name])}")
        for name in sorted(labeled_g):
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            for key in sorted(labeled_g[name]):
                lines.append(f"{pn}{_prom_labels(key)} "
                             f"{_prom_num(labeled_g[name][key])}")
        # rolling series export as gauges of their window aggregates
        for name in sorted(series):
            snap = series[name].snapshot()
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            lines.append(f"{pn}_count {_prom_num(snap['count'])}")
            lines.append(f"{pn}_sum {_prom_num(snap['sum'])}")
            for q in (50, 95, 99):
                if f"p{q}" in snap:
                    lines.append(
                        f'{pn}{{quantile="0.{q}"}} '
                        f"{_prom_num(snap[f'p{q}'])}")
        for name in sorted(hists):
            h = hists[name]
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} histogram")
            with h.lock:
                per = {k: list(row) for k, row in h.per_label.items()}
            for key in sorted(per):
                row = per[key]
                cum = 0
                for ub, c in zip(h.buckets, row):
                    cum += c
                    le = f'le="{_prom_num(ub)}"'
                    lines.append(f"{pn}_bucket{_prom_labels(key, le)} "
                                 f"{cum}")
                inf = 'le="+Inf"'
                lines.append(f"{pn}_bucket{_prom_labels(key, inf)} "
                             f"{_prom_num(row[-2])}")
                lines.append(f"{pn}_count{_prom_labels(key)} "
                             f"{_prom_num(row[-2])}")
                lines.append(f"{pn}_sum{_prom_labels(key)} "
                             f"{_prom_num(row[-1])}")
        return "\n".join(lines) + "\n"

    def hist_totals(self, name: str) -> Optional[Tuple[Tuple[float, ...],
                                                       List[float]]]:
        """(buckets, per-bucket counts summed over label sets, plus the
        trailing [count, sum]) — the SLO engine's raw-histogram surface
        (utils/slo.py reads `query_latency_us_hist` through this)."""
        h = self.histograms.get(name)
        if h is None:
            return None
        with h.lock:
            rows = [list(r) for r in h.per_label.values()]
        total = [0.0] * (len(h.buckets) + 2)
        for r in rows:
            for i, v in enumerate(r):
                total[i] += v
        return h.buckets, total

    def reset(self):
        with self.lock:
            self.counters.clear()
            self.gauges.clear()
            self.series.clear()
            self.labeled.clear()
            self.labeled_gauges.clear()
            self.histograms.clear()


_global = StatsManager()


def stats() -> StatsManager:
    """The process-wide registry (each daemon serves it at /stats)."""
    return _global


# -- deterministic work counters -------------------------------------------


class WorkCounters:
    """Per-query work counts — DETERMINISTIC for a fixed dataset/query,
    unlike wall-clock timings on a noisy VM.  Threaded through the
    engine (ExecutionContext.work), the RPC client (calls + wire
    bytes), and the device runtime (dispatches, traversed edges,
    per-hop frontier sizes); bench.py emits them as the noise-immune
    regression signal."""

    __slots__ = ("edges_traversed", "frontier_sizes", "rpc_calls",
                 "wire_bytes_sent", "wire_bytes_recv",
                 "device_dispatches", "storage_rows", "_lock")

    def __init__(self):
        self.edges_traversed = 0
        self.frontier_sizes: List[int] = []
        self.rpc_calls = 0
        self.wire_bytes_sent = 0
        self.wire_bytes_recv = 0
        self.device_dispatches = 0
        self.storage_rows = 0
        self._lock = threading.Lock()

    def add(self, field: str, n: int = 1):
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    def add_rpc(self, sent: int, recv: int):
        with self._lock:
            self.rpc_calls += 1
            self.wire_bytes_sent += sent
            self.wire_bytes_recv += recv

    def extend_frontier(self, sizes: List[int]):
        with self._lock:
            self.frontier_sizes.extend(int(x) for x in sizes)

    def merge(self, other: "WorkCounters"):
        """Fold another statement's counts into this one (the engine
        folds each statement's ExecutionContext.work into a
        caller-installed probe — see use_work)."""
        d = other.as_dict()
        with self._lock:
            self.edges_traversed += d["edges_traversed"]
            self.frontier_sizes.extend(d["frontier_sizes"])
            self.rpc_calls += d["rpc_calls"]
            self.wire_bytes_sent += d["wire_bytes_sent"]
            self.wire_bytes_recv += d["wire_bytes_recv"]
            self.device_dispatches += d["device_dispatches"]
            self.storage_rows += d["storage_rows"]

    def as_dict(self) -> Dict[str, Any]:
        """Stable-ordered plain dict (the bench JSON schema; see
        docs/OBSERVABILITY.md)."""
        with self._lock:
            return {
                "edges_traversed": self.edges_traversed,
                "frontier_sizes": list(self.frontier_sizes),
                "rpc_calls": self.rpc_calls,
                "wire_bytes_sent": self.wire_bytes_sent,
                "wire_bytes_recv": self.wire_bytes_recv,
                "device_dispatches": self.device_dispatches,
                "storage_rows": self.storage_rows,
            }


class CostRecorder:
    """Per-plan-node cost sink (ISSUE 8 tentpole): while a node's
    executor runs, this thread-local recorder accumulates the cost
    records remote services return in the RPC reply envelope
    (`remote_us`, `rows`, `wal_fsyncs`, `dedup_hits`) plus the client
    side's own call/byte counts and the device runtime's dispatch cost
    (`device_us`, `device_dispatches`, `device_compiles`).  The
    scheduler attaches the result to the node's PROFILE row and the
    flight-recorder entry — cluster-wide cost attribution per plan
    node, not graphd-local wall time."""

    __slots__ = ("data", "_lock")

    def __init__(self):
        self.data: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, field: str, n: int = 1):
        with self._lock:
            self.data[field] = self.data.get(field, 0) + int(n)

    def merge_reply(self, cost: Dict[str, Any]):
        """Fold a reply-envelope cost record in.  The remote side ships
        its handler time as a FIXED-WIDTH decimal string ("us") so
        reply byte counts stay deterministic run-to-run (the wire-byte
        work counters are a regression probe); everything else is plain
        deterministic ints."""
        with self._lock:
            for k, v in cost.items():
                key = "remote_us" if k == "us" else k
                try:
                    self.data[key] = self.data.get(key, 0) + int(float(v))
                except (TypeError, ValueError):
                    continue

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(sorted(self.data.items()))

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self.data)


_cost_tls = threading.local()


def current_cost() -> Optional[CostRecorder]:
    return getattr(_cost_tls, "cost", None)


class _CostGuard:
    __slots__ = ("_rec", "_prev")

    def __init__(self, rec: Optional[CostRecorder]):
        self._rec = rec

    def __enter__(self):
        self._prev = getattr(_cost_tls, "cost", None)
        _cost_tls.cost = self._rec
        return self._rec

    def __exit__(self, *exc):
        _cost_tls.cost = self._prev
        return False


def use_cost(rec: Optional[CostRecorder]) -> _CostGuard:
    """Install `rec` as this thread's cost-attribution target (None
    keeps attribution disabled; the guard still restores correctly).
    Mirrors use_work: fan-out pool threads re-install the submitting
    thread's recorder so per-part costs attribute to the right node."""
    return _CostGuard(rec)


_work_tls = threading.local()


def current_work() -> Optional[WorkCounters]:
    return getattr(_work_tls, "work", None)


class _WorkGuard:
    __slots__ = ("_wc", "_prev")

    def __init__(self, wc: Optional[WorkCounters]):
        self._wc = wc

    def __enter__(self):
        self._prev = getattr(_work_tls, "work", None)
        _work_tls.work = self._wc
        return self._wc

    def __exit__(self, *exc):
        _work_tls.work = self._prev
        return False


def use_work(wc: Optional[WorkCounters]) -> _WorkGuard:
    """Install `wc` as this thread's work-counter target (None keeps
    counting disabled — the guard still restores correctly)."""
    return _WorkGuard(wc)
