"""StatsManager — counters + rolling histograms.

Analog of the reference's src/common/stats StatsManager [UNVERIFIED —
empty mount, SURVEY §0]: named counters (`num_queries`), value series
with rolling windows exposing sum/count/avg/rate and p50/p95/p99
(`query_latency_us`), served by every daemon's `/stats` endpoint.  The
TPU build adds device gauges (HBM bytes pinned, per-hop all_to_all
volume, kernel step time) through the same registry.
"""
from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _Series:
    """A value series over a sliding window of seconds."""

    __slots__ = ("window_s", "points", "total_sum", "total_count", "lock")

    def __init__(self, window_s: float = 600.0):
        self.window_s = window_s
        self.points: List[Tuple[float, float]] = []   # (ts, value)
        self.total_sum = 0.0
        self.total_count = 0
        self.lock = threading.Lock()

    def add(self, v: float):
        now = time.monotonic()
        with self.lock:
            self.points.append((now, v))
            self.total_sum += v
            self.total_count += 1
            self._gc(now)

    def _gc(self, now: float):
        cutoff = now - self.window_s
        i = bisect.bisect_left(self.points, (cutoff, float("-inf")))
        if i > 0:
            del self.points[:i]

    def snapshot(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self.lock:
            self._gc(now)
            vals = sorted(v for _, v in self.points)
            n = len(vals)
            out = {
                "sum": self.total_sum,
                "count": self.total_count,
                "rate": n / self.window_s,
            }
            if n:
                out["avg"] = sum(vals) / n
                for q in (50, 95, 99):
                    out[f"p{q}"] = vals[min(n - 1, int(n * q / 100))]
            return out


class StatsManager:
    def __init__(self):
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}
        self.series: Dict[str, _Series] = {}
        self.lock = threading.Lock()

    def inc(self, name: str, delta: int = 1):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name: str, value: float):
        with self.lock:
            self.gauges[name] = value

    def add_value(self, name: str, value: float):
        s = self.series.get(name)
        if s is None:
            with self.lock:
                s = self.series.setdefault(name, _Series())
        s.add(value)

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            out: Dict[str, Any] = dict(self.counters)
            out.update(self.gauges)
            series = dict(self.series)
        for name, s in series.items():
            for k, v in s.snapshot().items():
                out[f"{name}.{k}"] = v
        return out

    def to_text(self) -> str:
        snap = self.snapshot()
        return "\n".join(f"{k}={snap[k]}" for k in sorted(snap))

    def reset(self):
        with self.lock:
            self.counters.clear()
            self.gauges.clear()
            self.series.clear()


_global = StatsManager()


def stats() -> StatsManager:
    """The process-wide registry (each daemon serves it at /stats)."""
    return _global
