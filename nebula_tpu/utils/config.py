"""Typed config registry — the gflags analog.

Reference behavior (gflags `DEFINE_*` + etc/*.conf + meta-managed config
+ live `/flags` mutation [UNVERIFIED — empty mount, SURVEY §0]) as one
layered registry:

    defaults  <  config file (`key=value` lines, `#` comments)
              <  environment (NEBULA_<UPPER_NAME>)
              <  dynamic (live /flags PUT, meta config push)

Flags are declared near their use via define_flag(); lookups are
`get_config().get("name")`.  Unknown names raise — typos surface
immediately, like gflags.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class FlagDef:
    name: str
    default: Any
    ftype: type
    help: str = ""
    mutable: bool = True          # may /flags or meta change it live?


class ConfigError(Exception):
    pass


def _parse(ftype: type, raw: str) -> Any:
    if ftype is bool:
        if raw.lower() in ("1", "true", "yes", "on"):
            return True
        if raw.lower() in ("0", "false", "no", "off"):
            return False
        raise ConfigError(f"bad bool {raw!r}")
    return ftype(raw)


class Config:
    def __init__(self):
        self.defs: Dict[str, FlagDef] = {}
        self.file_layer: Dict[str, Any] = {}
        self.dynamic_layer: Dict[str, Any] = {}
        self.lock = threading.RLock()
        self.listeners: list = []      # fn(name, value) on dynamic change

    def define(self, name: str, default: Any, help: str = "",
               ftype: Optional[type] = None, mutable: bool = True):
        with self.lock:
            if name in self.defs:
                return                 # idempotent re-import
            self.defs[name] = FlagDef(name, default,
                                      ftype or type(default), help, mutable)

    def load_file(self, path: str):
        """gflags-style `key=value` lines (also accepts `--key=value`)."""
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln or ln.startswith("#"):
                    continue
                if ln.startswith("--"):
                    ln = ln[2:]
                if "=" not in ln:
                    raise ConfigError(f"bad config line: {ln!r}")
                k, v = ln.split("=", 1)
                k, v = k.strip(), v.strip()
                d = self.defs.get(k)
                if d is None:
                    raise ConfigError(f"unknown flag `{k}' in {path}")
                with self.lock:
                    self.file_layer[k] = _parse(d.ftype, v)

    def get(self, name: str) -> Any:
        d = self.defs.get(name)
        if d is None:
            raise ConfigError(f"unknown flag `{name}'")
        with self.lock:
            if name in self.dynamic_layer:
                return self.dynamic_layer[name]
        env = os.environ.get("NEBULA_" + name.upper())
        if env is not None:
            return _parse(d.ftype, env)
        with self.lock:
            if name in self.file_layer:
                return self.file_layer[name]
        return d.default

    def check(self, name: str, value: Any) -> Any:
        """Validate name + coerce value WITHOUT applying (lets callers
        make multi-key updates atomic).  Wrong-typed values are rejected
        — a poisoned flag would break every later reader."""
        d = self.defs.get(name)
        if d is None:
            raise ConfigError(f"unknown flag `{name}'")
        if not d.mutable:
            raise ConfigError(f"flag `{name}' is not mutable at runtime")
        if isinstance(value, str) and d.ftype is not str:
            return _parse(d.ftype, value)
        if d.ftype is float and isinstance(value, int) \
                and not isinstance(value, bool):
            return float(value)
        if d.ftype is bool and not isinstance(value, bool):
            raise ConfigError(f"flag `{name}' expects bool, got "
                              f"{type(value).__name__}")
        if d.ftype is int and isinstance(value, bool):
            raise ConfigError(f"flag `{name}' expects int, got bool")
        if not isinstance(value, d.ftype):
            raise ConfigError(f"flag `{name}' expects "
                              f"{d.ftype.__name__}, got "
                              f"{type(value).__name__}")
        return value

    def set_dynamic(self, name: str, value: Any):
        self.set_dynamic_many({name: value})

    def set_dynamic_many(self, updates: Dict[str, Any]):
        """Atomic multi-key dynamic update: EVERY key is validated and
        coerced before ANY is applied — a rejected update means nothing
        changed (the PUT /flags and UPDATE CONFIGS contract; one bad
        flag in a batch must not half-apply an overload-survival
        tuning).  Listeners fire once per key, after the whole batch
        is visible, so a listener reading a sibling key (the admission
        drain kick) sees the NEW values."""
        parsed = {k: self.check(k, v) for k, v in updates.items()}
        with self.lock:
            self.dynamic_layer.update(parsed)
            listeners = list(self.listeners)
        for fn in listeners:
            for k, v in parsed.items():
                fn(k, v)

    def all_values(self) -> Dict[str, Any]:
        return {n: self.get(n) for n in sorted(self.defs)}


_global = Config()


def get_config() -> Config:
    return _global


def define_flag(name: str, default: Any, help: str = "",
                mutable: bool = True):
    _global.define(name, default, help, mutable=mutable)
    return name


# -- core flags (mirroring the reference's .conf.default tunables) ---------
define_flag("slow_query_threshold_us", 500_000,
            "queries slower than this land in the slow log")
define_flag("heartbeat_interval_secs", 1.0,
            "meta heartbeat period for graphd/storaged")
define_flag("query_timeout_secs", 300.0,
            "statement deadline budget: propagated (and decremented) "
            "across every RPC hop of the statement; exceeding it "
            "surfaces E_QUERY_TIMEOUT.  0 disables")
define_flag("session_idle_timeout_secs", 28800,
            "idle sessions are reaped after this")
define_flag("max_match_hops", 12, "safety cap for unbounded MATCH *")
define_flag("minloglevel", 0, "log severity threshold")
define_flag("v", 0, "verbose log level")
define_flag("enable_authorize", False, "require password auth in graphd")
define_flag("tpu_enable", True, "allow the device execution plane")
define_flag("tpu_init_edge_budget", 2048,
            "initial per-block edge budget (power of two)")
define_flag("scheduler_threads", 4,
            "plan-branch concurrency; 0/1 = sequential")
define_flag("max_concurrent_admin_jobs", 2,
            "admin-job worker slots; queued jobs wait (task throttling, "
            "the AdminTaskManager analog)")
define_flag("host_hb_expire_secs", 10.0,
            "heartbeat age after which a host reads as dead")
define_flag("tpu_match_device", True,
            "run MATCH Traverse expansion on the device plane")
define_flag("tpu_degree_split_threshold", 0,
            "degree above which a supernode's adjacency is split "
            "across parts at pin time (0 = off); drops the per-part "
            "expansion ceiling toward the mean on skewed graphs")
define_flag("enable_query_tracing", True,
            "record a distributed trace per statement (SHOW TRACES / "
            "GET /traces); off = no spans ride the RPC envelope, which "
            "also makes wire-byte work counters deterministic for "
            "regression probes")
define_flag("tpu_profiler_dir", "",
            "when set, wrap every device kernel run in a jax.profiler "
            "trace written under this directory (SURVEY §5 tracing)")
define_flag("storage_read_capacity_qps", 0,
            "per-storaged read admission rate (reads/s, token bucket; "
            "0 = unlimited).  Reads beyond the rate are shed with the "
            "structured E_OVERLOAD + retry-after contract (PR 8), so "
            "follower-readable clients walk to a replica with spare "
            "capacity instead of waiting.  Production use: cap a "
            "replica's read load during backfill/compaction; bench "
            "use: model per-replica capacity for the read scale-out "
            "sweep on hosts whose cores can't isolate replicas")
define_flag("graph_statement_capacity_qps", 0,
            "per-COORDINATOR data-statement admission rate "
            "(statements/s, token bucket per graphd; 0 = unlimited).  "
            "Statements beyond the rate are shed with the structured "
            "E_OVERLOAD + retry-after contract (PR 8), so a fleet "
            "client walks to a sibling coordinator with spare "
            "capacity instead of waiting.  Control statements "
            "(SHOW/KILL/DESC/USE) bypass the bucket — the diagnosis "
            "lane must survive the overload being diagnosed.  "
            "Production use: cap one coordinator during canary or "
            "drain warm-up; bench use: model per-coordinator "
            "capacity for the fleet scale-out sweep on hosts whose "
            "cores can't isolate graphds (ISSUE 20)")
define_flag("tpu_delta_max_edges", 0,
            "device delta-CSR capacity per (block, part) in edges "
            "(rounded up to a power of two; 0 = delta plane off, "
            "every epoch bump re-pins the full snapshot).  With the "
            "delta on, group-committed writes land as a small "
            "device_put into a padded delta buffer that every "
            "traversal kernel merges with the base CSR each hop")
define_flag("tpu_delta_compact_watermark", 0.75,
            "delta fill ratio (of tpu_delta_max_edges, insert or "
            "tombstone side) above which the background compaction "
            "job rebuilds the base CSR off the gate and swaps it "
            "under a short write-side hold")
define_flag("tpu_delta_vmax_slack", 64,
            "extra padded local-vertex rows reserved at snapshot "
            "build when the delta plane is on, so freshly inserted "
            "vertices fit the pinned frontier/bitmap shapes without "
            "forcing a full re-pin")
define_flag("snapshot_dir", "./nebula_snapshots",
            "where CREATE SNAPSHOT checkpoints land")
define_flag("backup_dir", "./nebula_backups",
            "where CREATE BACKUP restorable checkpoints land")
