"""Always-on flight recorder + device kernel ledger (ISSUE 8).

The production incident problem: by the time someone asks "what did the
slow/failed query actually do", the query is gone — PROFILE can only be
run on a REPRODUCTION, and reproductions of incident queries are
unreliable.  The flight recorder fixes that by keeping a bounded ring
of COMPLETED statement profiles: every statement's per-operator
breakdown (node kind, wall time, rows, remote cost from the RPC reply
envelopes, device dispatch cost) is collected always — the collection
is a handful of dict inserts per plan node — and a statement's record
is RETAINED when either

  * deterministic sampling admits it (`flight_sample_rate`, a
    counter-based accumulator — not random, so runs reproduce), or
  * capture is FORCED: the statement errored, was killed, timed out,
    tripped a chaos failpoint, or crossed the slow-query threshold.

So the PR5 chaos harness (and any production incident) yields the exact
per-operator breakdown of the offending statement after the fact, via
`GET /flight` on the webservice or `SHOW FLIGHT RECORDER` in nGQL.

The module also owns the DEVICE KERNEL LEDGER: a bounded ring of every
kernel dispatch (kernel name, shape bucket, compile-vs-cache, dispatch
µs, HBM high-water) fed by tpu/runtime.py — the telemetry substrate the
batching/multi-chip work will be tuned against.  Kept here (not in the
tpu package) so the webservice can serve `GET /kernels` without
importing jax.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .config import define_flag, get_config

define_flag("flight_recorder_capacity", 256,
            "completed-statement profiles retained in the flight "
            "recorder ring (0 disables retention; collection stays on "
            "so PROFILE is unaffected)")
define_flag("flight_sample_rate", 0.02,
            "fraction of OK statements retained by the flight recorder "
            "(deterministic counter-based sampling, not random); "
            "errored/killed/timed-out/slow statements are always "
            "retained regardless")
define_flag("kernel_ledger_capacity", 512,
            "device kernel dispatch records retained in the ledger "
            "ring (GET /kernels)")


class FlightRecorder:
    """Bounded ring of completed statement profiles, newest last."""

    def __init__(self):
        self._ring: "deque[dict]" = deque()
        self._lock = threading.Lock()
        self._seq = 0            # monotonically growing entry id
        self._acc = 0.0          # deterministic sampling accumulator

    @staticmethod
    def _capacity() -> int:
        try:
            return int(get_config().get("flight_recorder_capacity"))
        except Exception:  # noqa: BLE001 — config not initialized
            return 256

    def _admit_sample(self) -> bool:
        """Counter-based sampling: accumulate the rate per statement
        and admit when the accumulator crosses 1 — rate 0.02 admits
        exactly every 50th OK statement, reproducibly."""
        try:
            rate = float(get_config().get("flight_sample_rate"))
        except Exception:  # noqa: BLE001
            rate = 0.0
        if rate <= 0.0:
            return False
        with self._lock:
            self._acc += min(rate, 1.0)
            if self._acc >= 1.0:
                self._acc -= 1.0
                return True
        return False

    @staticmethod
    def classify(error: Optional[str], latency_us: int,
                 slow_us: int) -> Optional[str]:
        """Forced-capture reason for a finished statement, or None when
        only sampling applies.  Matches the engine's STRUCTURED error
        shapes (exact sentinel / prefix / exception-class token), not
        loose substrings — error text embeds statement fragments, and a
        statement merely CONTAINING the word "killed" must not skew the
        status triage columns."""
        if error is not None:
            if error == "ExecutionError: query was killed":
                return "killed"           # engine.py emits exactly this
            if error.startswith("E_QUERY_TIMEOUT"):
                return "timeout"
            if error.startswith("E_OVERLOAD"):
                return "shed"             # admission/inbox load shedding
            if "FailpointError:" in error:
                return "failpoint"        # exception-class token
            return "error"
        if slow_us > 0 and latency_us > slow_us:
            return "slow"
        return None

    def record(self, *, stmt: str, kind: str, latency_us: int,
               error: Optional[str], trace_id: Optional[str],
               session: Optional[int], operators,
               work: Optional[Dict[str, Any]] = None,
               slow_us: int = 0,
               force: Optional[str] = None,
               fingerprint: Optional[str] = None) -> Optional[dict]:
        """Retain one completed statement if forced or sampled.
        Returns the stored entry (or None when dropped).  `operators`
        (and `work`) may be zero-arg callables — they are only invoked
        AFTER the retain decision, so a dropped statement pays nothing
        beyond the decision itself (the ≤2% overhead budget).
        `force` retains unconditionally under that status — the stall
        watchdog records a still-RUNNING statement this way (ISSUE 9),
        which classify() cannot see from the outcome alone."""
        cap = self._capacity()
        if cap <= 0:
            return None
        forced = force or self.classify(error, latency_us, slow_us)
        if forced is None and not self._admit_sample():
            return None
        if callable(operators):
            operators = operators()
        if callable(work):
            work = work()
        entry = {
            "ts": time.time(),
            "stmt": stmt[:500],
            "kind": kind,
            "latency_us": int(latency_us),
            "status": forced or "sampled",
            "error": error,
            "trace_id": trace_id,
            "session": session,
            "operators": operators,
            # statement fingerprint (ISSUE 16): joins this point-in-time
            # capture against the aggregate SHOW STATEMENTS table
            "fingerprint": fingerprint or "",
        }
        if work:
            entry["work"] = work
        with self._lock:
            self._seq += 1
            entry["id"] = self._seq
            self._ring.append(entry)
            while len(self._ring) > cap:
                self._ring.popleft()
        from .stats import stats
        stats().inc_labeled("flight_records", {"status": entry["status"]})
        return entry

    def get(self, entry_id: int) -> Optional[dict]:
        with self._lock:
            for e in self._ring:
                if e["id"] == entry_id:
                    return e
        return None

    def list(self, limit: int = 50) -> List[dict]:
        """Newest-first summaries (no operator bodies)."""
        if limit <= 0:
            return []
        with self._lock:
            entries = list(self._ring)
        return [{"id": e["id"], "ts": e["ts"], "stmt": e["stmt"][:120],
                 "kind": e["kind"], "status": e["status"],
                 "latency_us": e["latency_us"],
                 "operators": len(e["operators"]),
                 "trace_id": e["trace_id"],
                 "fingerprint": e.get("fingerprint", "")}
                for e in reversed(entries[-limit:])]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._acc = 0.0


class KernelLedger:
    """Bounded ring of device kernel dispatch records, newest last."""

    def __init__(self):
        self._ring: "deque[dict]" = deque()
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, *, kernel: str, shape: List[int], steps: int,
               compiled: bool, dispatch_us: int, hbm_bytes: int,
               retries: int = 0, shards: int = 1,
               exchange_bytes: int = 0):
        try:
            cap = int(get_config().get("kernel_ledger_capacity"))
        except Exception:  # noqa: BLE001
            cap = 512
        if cap <= 0:
            return
        with self._lock:
            self._seq += 1
            self._ring.append({
                "id": self._seq, "ts": time.time(), "kernel": kernel,
                "shape": list(int(x) for x in shape), "steps": int(steps),
                "compiled": bool(compiled),
                "dispatch_us": int(dispatch_us),
                "hbm_bytes": int(hbm_bytes), "retries": int(retries),
                # mesh facts (PR 17): how many part-axis shards the
                # launch spanned and the bit-packed frontier exchange
                # payload it moved — the "mesh is used, not assumed"
                # proof per dispatch
                "shards": int(shards),
                "exchange_bytes": int(exchange_bytes)})
            while len(self._ring) > cap:
                self._ring.popleft()

    def list(self, limit: int = 100) -> List[dict]:
        if limit <= 0:
            return []
        with self._lock:
            entries = list(self._ring)
        return list(reversed(entries[-limit:]))

    def clear(self):
        with self._lock:
            self._ring.clear()


_recorder = FlightRecorder()
_ledger = KernelLedger()


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder (each daemon serves it at /flight)."""
    return _recorder


def kernel_ledger() -> KernelLedger:
    """The process-wide dispatch ledger (served at /kernels)."""
    return _ledger
