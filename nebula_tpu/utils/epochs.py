"""Cluster-coherent write epochs (ISSUE 20).

PR 9's result/plan cache keys carry a write epoch that is
coordinator-local: a mutating statement through graphd A bumps A's
epoch, but graphd B keeps serving its cached rows — the one documented
wrong-rows hole.  This module is the cluster half of the fix.

Every storaged already bumps a per-space store epoch on EVERY applied
mutation (leader and raft followers alike).  ClusterEpochs folds those
per-host counters into a per-space vector

    space -> { storaged_host: (boot_id, epoch) }

and derives from it a LOCAL, monotonically increasing generation
number per space.  The generation — not the raw vector — goes into the
cache key: any observed change anywhere in the vector mints new keys,
so previously cached entries become unreachable (invalidation by
unreachability, same trick as the catalog-version half of the key).

Why a (boot, epoch) pair per host rather than one max-merged scalar:
store epochs are host-local counters that reset on restart.  A plain
max() would let a long-lived host's high epoch mask a freshly
restarted host's low-but-advancing one (missed invalidations); a plain
replace would let an out-of-order heartbeat regress the vector and
resurrect retired cache keys.  Per-host-per-boot max-merge is immune
to both: same boot → monotonic guard drops stale folds; new boot →
unconditional replace (a restart is always news).

Propagation path (both legs ride existing traffic, no new RPC):
  - storaged heartbeat carries {space: [boot, epoch, bump_ts]} → metad
    merges into a leader-local table (like liveness/heat — deliberately
    NOT raft-replicated; a fresh leader rebuilds it from the next
    heartbeat wave) → every heartbeat REPLY carries the merged table →
    graphd folds it here.  Window ≈ storaged hb + graphd hb intervals,
    measured as `epoch_propagation_lag_ms` (now − bump_ts whenever a
    fold advances an entry that carries a timestamp).
  - the storaged write ack already carries the space epoch; the
    writing graphd folds it immediately (note_ack) so its OWN caches
    turn over without waiting a heartbeat — read-your-writes on the
    write coordinator is ack-latency, not heartbeat-latency.

Strict mode (`result_cache_strict_epoch`): before serving a cached
result at leader consistency, the engine pulls metad's merged table
once and folds it — a write acked through ANY coordinator that reached
metad invalidates before the read is served, closing even the
heartbeat window for reads that asked for leader semantics.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

__all__ = ["ClusterEpochs"]


class ClusterEpochs:
    """Per-space cluster write-epoch vector + derived local generation."""

    def __init__(self):
        self._mu = threading.Lock()
        # space -> host -> (boot, epoch)
        self._vec: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # space -> local generation (bumped on every observed advance)
        self._gen: Dict[str, int] = {}
        # space -> max store epoch seen on a write ack (host-anonymous:
        # acks don't say which replica served, so this is a separate
        # monotonic floor under pseudo-host "#ack")
        self._ack: Dict[str, int] = {}

    # -- reads -----------------------------------------------------------

    def gen(self, space: Optional[str]) -> int:
        """Cache-key component: local generation for `space` (0 until a
        fold lands — standalone engines never fold, keys unchanged)."""
        if not space:
            return 0
        with self._mu:
            return self._gen.get(space, 0)

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            return {sp: {"gen": self._gen.get(sp, 0),
                         "ack": self._ack.get(sp, 0),
                         "hosts": {h: [b, e]
                                   for h, (b, e) in hosts.items()}}
                    for sp, hosts in self._vec.items()}

    # -- folds -----------------------------------------------------------

    def fold(self, space: str, host: str, boot: str, epoch: int,
             ts: Optional[float] = None) -> bool:
        """Fold one host's (boot, epoch) into the vector; True when the
        vector advanced (and the space generation was bumped)."""
        epoch = int(epoch)
        advanced = False
        with self._mu:
            hosts = self._vec.setdefault(space, {})
            cur = hosts.get(host)
            if cur is None or cur[0] != boot or epoch > cur[1]:
                hosts[host] = (boot, epoch)
                self._gen[space] = self._gen.get(space, 0) + 1
                advanced = True
        if advanced and ts:
            lag_ms = max(0.0, (time.time() - float(ts)) * 1000.0)
            from .stats import stats
            stats().observe("epoch_propagation_lag_ms", lag_ms)
            stats().inc("cluster_epoch_folds")
        return advanced

    def fold_table(self, table: Optional[Dict[str, Any]]) -> int:
        """Fold a metad-merged table {space: {host: [boot, epoch, ts]}};
        returns how many entries advanced."""
        if not table:
            return 0
        n = 0
        for space, hosts in table.items():
            if not isinstance(hosts, dict):
                continue
            for host, ent in hosts.items():
                try:
                    boot, epoch = ent[0], int(ent[1])
                    ts = float(ent[2]) if len(ent) > 2 and ent[2] else None
                except (TypeError, ValueError, IndexError):
                    continue
                if self.fold(space, host, boot, epoch, ts=ts):
                    n += 1
        return n

    def note_ack(self, space: str, epoch: Any) -> bool:
        """Fold a write-ack store epoch (host unknown).  Monotonic per
        space; an advance bumps the generation, so the writing graphd's
        caches turn over at ack time, before any heartbeat."""
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return False
        if not space or epoch <= 0:
            return False
        with self._mu:
            if epoch <= self._ack.get(space, 0):
                return False
            self._ack[space] = epoch
            self._gen[space] = self._gen.get(space, 0) + 1
        return True


class EpochClock:
    """Storaged-side bump-timestamp tracker: remembers WHEN each
    space's store epoch was last seen advancing, so the heartbeat
    payload can carry a wall-clock bump ts and the folding graphd can
    measure true propagation lag (not just heartbeat cadence)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._seen: Dict[str, Tuple[int, float]] = {}  # space -> (epoch, ts)

    def note(self, space: str, epoch: int) -> None:
        with self._mu:
            cur = self._seen.get(space)
            if cur is None or epoch > cur[0]:
                self._seen[space] = (int(epoch), time.time())

    def ts_for(self, space: str, epoch: int) -> Optional[float]:
        """Bump ts if it corresponds to `epoch` (else None — an epoch
        that advanced without passing through note(), e.g. a follower
        apply, carries no ts and is folded without a lag sample)."""
        with self._mu:
            cur = self._seen.get(space)
            if cur is not None and cur[0] == int(epoch):
                return cur[1]
            return None
