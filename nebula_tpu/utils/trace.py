"""Distributed query tracing — trace/span ids carried in the RPC envelope.

The reference ships per-node PROFILE timings but nothing that crosses
the graphd process boundary; a slow cluster query's time disappears
into storaged.  This module is the cross-service half of the
observability layer (ISSUE 1 tentpole): a per-query trace id plus span
ids ride the JSON-TCP envelope (cluster.rpc), every service opens child
spans around its work, and the spans a REMOTE service produced while
handling an RPC are returned in the reply and grafted into the caller's
trace — so the coordinator (the graphd that ran the statement) ends up
holding ONE stitched tree covering graphd executors, storaged reads,
raft appends and the device put/dispatch/fetch phases.  Queryable via
`GET /traces` on the webservice and `SHOW TRACES` in nGQL.

Design constraints:
  * zero cost when no trace is active — `span()` is a no-op context;
  * thread-pool safe — the scheduler and the storage fan-out run on
    pools, so the context is snapshot/restore (`current_ctx` /
    `use_ctx`), and sinks are plain lists (append is atomic);
  * spans are plain dicts the moment they finish (JSON-safe: they ship
    in RPC replies and out of the /traces endpoint verbatim).

Span fields: tid, sid, psid (parent span id), name, svc (service
role), t0 (epoch seconds), dur_us, attrs (flat dict).  Remote spans
grafted from an RPC reply additionally carry remote=True.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_tls = threading.local()
_span_seq = itertools.count(1)
# span ids must not collide across processes (a trace stitches spans
# from graphd + storaged + metad); prefix with a per-process token
_PROC = f"{os.getpid():x}"


def _new_id(kind: str) -> str:
    return f"{kind}{_PROC}-{next(_span_seq)}"


class _Ctx:
    __slots__ = ("tid", "sid", "sink", "service")

    def __init__(self, tid: str, sid: str, sink: List[dict], service: str):
        self.tid = tid
        self.sid = sid
        self.sink = sink
        self.service = service


def _get_ctx() -> Optional[_Ctx]:
    return getattr(_tls, "ctx", None)


def current_ctx() -> Optional[_Ctx]:
    """Snapshot for cross-thread propagation (fan-out pools)."""
    return _get_ctx()


def wire_context() -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) to put on an outgoing RPC frame."""
    ctx = _get_ctx()
    if ctx is None:
        return None
    return ctx.tid, ctx.sid


class _CtxGuard:
    """Context manager installing a _Ctx (or None) on this thread."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[_Ctx]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def use_ctx(ctx: Optional[_Ctx]) -> _CtxGuard:
    """Re-establish a snapshot taken with current_ctx() on a pool
    thread (no-op guard when ctx is None).  Installs a COPY sharing the
    trace id and sink but owning its parent-span slot — span guards
    mutate `ctx.sid`, and concurrent branches of one query must not
    stomp each other's parenting (sink.append itself is atomic)."""
    if ctx is None:
        return _CtxGuard(None)
    return _CtxGuard(_Ctx(ctx.tid, ctx.sid, ctx.sink, ctx.service))


class _SpanGuard:
    """Open span: on exit, append the finished record to the sink."""

    __slots__ = ("_ctx", "_rec", "_t0", "_prev_sid")

    def __init__(self, ctx: Optional[_Ctx], name: str, attrs: Dict[str, Any]):
        self._ctx = ctx
        if ctx is None:
            return
        self._rec = {"tid": ctx.tid, "sid": _new_id("s"),
                     "psid": ctx.sid, "name": name, "svc": ctx.service,
                     "t0": time.time(), "dur_us": 0}
        if attrs:
            self._rec["attrs"] = attrs

    def __enter__(self):
        ctx = self._ctx
        if ctx is None:
            return None
        self._t0 = time.perf_counter()
        self._prev_sid = ctx.sid
        ctx.sid = self._rec["sid"]
        return self._rec

    def __exit__(self, exc_type, exc, tb):
        ctx = self._ctx
        if ctx is None:
            return False
        ctx.sid = self._prev_sid
        self._rec["dur_us"] = int(
            (time.perf_counter() - self._t0) * 1e6)
        if exc is not None:
            self._rec.setdefault("attrs", {})["error"] = \
                f"{type(exc).__name__}: {exc}"
        ctx.sink.append(self._rec)
        return False


def span(name: str, **attrs) -> _SpanGuard:
    """Child span of the active trace; no-op when none is active."""
    return _SpanGuard(_get_ctx(), name, attrs)


def record_phase(name: str, dur_s: float, **attrs):
    """Append an already-measured span (device phases: the runtime times
    put/dispatch/fetch itself; these become leaf spans of the executor
    span that drove the kernel)."""
    ctx = _get_ctx()
    if ctx is None:
        return
    rec = {"tid": ctx.tid, "sid": _new_id("s"), "psid": ctx.sid,
           "name": name, "svc": ctx.service, "t0": time.time() - dur_s,
           "dur_us": int(dur_s * 1e6)}
    if attrs:
        rec["attrs"] = attrs
    ctx.sink.append(rec)


def graft(spans: List[dict]):
    """Merge spans returned by a remote service into the active trace
    (they already carry their own parentage — the root of the remote
    subtree points at the client-side rpc span id we sent over)."""
    ctx = _get_ctx()
    if ctx is None or not spans:
        return
    for s in spans:
        s = dict(s)
        s["remote"] = True
        ctx.sink.append(s)


class _TraceGuard:
    """Root context: owns the sink; stores the finished trace."""

    __slots__ = ("_ctx", "_rec", "_t0", "_prev")

    def __init__(self, name: str, service: str, attrs: Dict[str, Any]):
        tid = _new_id("t")
        sink: List[dict] = []
        self._ctx = _Ctx(tid, "", sink, service)
        self._rec = {"tid": tid, "sid": _new_id("s"), "psid": "",
                     "name": name, "svc": service, "t0": time.time(),
                     "dur_us": 0}
        if attrs:
            self._rec["attrs"] = attrs

    @property
    def trace_id(self) -> str:
        return self._ctx.tid

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        self._ctx.sid = self._rec["sid"]
        _tls.ctx = self._ctx
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.ctx = self._prev
        self._rec["dur_us"] = int((time.perf_counter() - self._t0) * 1e6)
        if exc is not None:
            self._rec.setdefault("attrs", {})["error"] = \
                f"{type(exc).__name__}: {exc}"
        self._ctx.sink.append(self._rec)
        trace_store().add(self._ctx.tid, self._rec["name"],
                          list(self._ctx.sink))
        return False


def start_trace(name: str, service: str = "standalone",
                **attrs) -> _TraceGuard:
    """Open a new root trace on this thread.  Nested start_trace calls
    (compound `a; b` statements) each get their own trace."""
    return _TraceGuard(name, service, attrs)


class _RemoteGuard:
    """Server-side adoption of an incoming wire context: spans produced
    while handling the RPC go to a FRESH sink that the dispatcher ships
    back in the reply — they are NOT stored locally (the coordinator
    owns the trace)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, tid: str, psid: str, service: str):
        self._ctx = _Ctx(tid, psid, [], service)

    @property
    def spans(self) -> List[dict]:
        return self._ctx.sink

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


def adopt_remote(tid: str, psid: str, service: str) -> _RemoteGuard:
    return _RemoteGuard(tid, psid, service)


# -- the per-process store of finished traces -------------------------------


class TraceStore:
    """Bounded ring of recent traces, newest last."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._traces: Dict[str, dict] = {}   # insertion-ordered
        self._lock = threading.Lock()

    def add(self, tid: str, name: str, spans: List[dict]):
        root = next((s for s in spans if not s.get("psid")), None)
        entry = {"tid": tid, "name": name,
                 "t0": root["t0"] if root else time.time(),
                 "dur_us": root["dur_us"] if root else 0,
                 "spans": spans}
        with self._lock:
            self._traces[tid] = entry
            while len(self._traces) > self.capacity:
                self._traces.pop(next(iter(self._traces)))

    def get(self, tid: str) -> Optional[dict]:
        with self._lock:
            return self._traces.get(tid)

    def list(self, limit: int = 50) -> List[dict]:
        """Newest-first summaries (no span bodies)."""
        with self._lock:
            entries = list(self._traces.values())
        return [{"tid": e["tid"], "name": e["name"], "t0": e["t0"],
                 "dur_us": e["dur_us"], "spans": len(e["spans"])}
                for e in reversed(entries[-limit:])]

    def clear(self):
        with self._lock:
            self._traces.clear()


def render_tree(entry: dict) -> str:
    """Indented text rendering of one trace's span tree.  Orphan spans
    (parent not shipped — e.g. a remote subtree whose local anchor was
    dropped) attach under the root rather than vanishing."""
    spans = entry["spans"]
    by_id = {s["sid"]: s for s in spans}
    children: Dict[str, List[dict]] = {}
    root = None
    for s in spans:
        psid = s.get("psid") or ""
        if not psid:
            root = s
            continue
        children.setdefault(
            psid if psid in by_id else "__orphan__", []).append(s)
    lines: List[str] = []

    def visit(s: dict, depth: int):
        attrs = s.get("attrs") or {}
        extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items()))
        svc = s.get("svc", "")
        rem = " [remote]" if s.get("remote") else ""
        lines.append("  " * depth
                     + f"{s['name']} ({svc}{rem}) {s['dur_us']}us{extra}")
        for c in sorted(children.get(s["sid"], []), key=lambda x: x["t0"]):
            visit(c, depth + 1)

    if root is not None:
        visit(root, 0)
    for s in sorted(children.get("__orphan__", []), key=lambda x: x["t0"]):
        visit(s, 1)
    return "\n".join(lines)


_store = TraceStore()


def trace_store() -> TraceStore:
    """The process-wide store (each daemon serves it at /traces)."""
    return _store
