"""Cross-cutting utilities: metrics registry, typed config."""
from .config import Config, define_flag, get_config  # noqa: F401
from .stats import StatsManager, stats  # noqa: F401
