"""Workload insights plane (ISSUE 16): WHAT ran, aggregated over time.

Every observability layer so far answers "what is this one statement
doing" — tracing (PR 1) follows one statement's spans, the flight
recorder (PR 6) retains one statement's post-mortem, the live workload
plane (PR 7) shows one statement's in-flight progress.  Nothing
aggregates ACROSS statements, so "which query shapes dominate the
fleet", "did the optimizer's plan for this shape regress after a DDL
epoch bump" and "which partitions are hot" were unanswerable.  Three
pieces, pg_stat_statements-style:

  * **Statement fingerprints** — a literal-normalizing digest over the
    parsed AST: every `Literal` becomes `?`, homogeneous value lists
    collapse to `?*` (so ``FROM 1, 2, 3`` and ``FROM 5`` share a
    fingerprint), while structure, statement kind, identifiers, step
    counts and the session space are preserved.  Computed once per
    statement at plan-cache-key time and memoized by (text, space), so
    the steady-state cost is one bounded-LRU lookup.

  * **StatementRegistry** — a bounded per-graphd table keyed by
    fingerprint accumulating calls, error/kill/shed triage, latency +
    queue/device/host µs (latency into the shared fixed buckets so
    per-host tables merge exactly), rows, device dispatches, plan- and
    result-cache hits and multi-lane batching share.  Fed from the same
    completion hook the flight recorder uses: one locked dict update
    per statement.  Per-ENGINE (not process-wide) because a
    LocalCluster runs several graphds in one process and the cluster
    fan-out must not double count.

  * **Plan history + regression sentinel** — per fingerprint, per plan
    shape hash (the optimized plan's kind tree), its own latency
    buckets.  When the active plan flips (DDL epoch bump, optimizer
    toggle, device↔host fallback change) the pre/post stats sit side
    by side and `plan_regressed{fingerprint}` fires once the new
    plan's p50 degrades past `plan_regression_ratio`.

  * **PartHeatTable** — per-partition read/write QPS, rows, bytes and
    latency EWMAs maintained by storaged's `_read_part`/`rpc_write`
    hot paths (two unlocked counter bumps + one EWMA fold), ridden to
    metad on the existing heartbeat and ranked by `SHOW HOTSPOTS`.
    `heat_of()` is the documented read hook for the replica router and
    BALANCE (ISSUE 10/16): heat-driven placement reads it, never
    writes.

Everything is gated on `insights_enabled`: off reproduces pre-PR
behavior byte for byte (no fingerprinting, no registry writes).
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import fields, is_dataclass
from typing import Any, Dict, List, Optional, Tuple

from .config import define_flag, get_config
from .stats import LATENCY_BUCKETS_US

define_flag("insights_enabled", True,
            "maintain statement fingerprints + the per-graphd "
            "StatementRegistry behind SHOW STATEMENTS (off = no "
            "fingerprinting, no registry writes; the A/B lever for "
            "the bench overhead probe)")
define_flag("insights_max_fingerprints", 512,
            "distinct fingerprints retained per graphd registry; "
            "least-recently-seen shapes are evicted beyond this")
define_flag("plan_regression_ratio", 1.5,
            "regression sentinel: after a fingerprint's active plan "
            "changes, flag it regressed when the new plan's p50 "
            "exceeds the previous plan's p50 by this factor")
define_flag("plan_regression_min_calls", 8,
            "calls required on BOTH the old and the new plan before "
            "the regression sentinel compares their p50s")
define_flag("heat_ewma_alpha", 0.3,
            "EWMA smoothing factor for per-partition QPS/latency heat "
            "(folded at snapshot time, i.e. once per heartbeat)")


# -- statement fingerprints -------------------------------------------------


def _expr_slots(cls) -> Tuple[str, ...]:
    out: List[str] = []
    for c in reversed(cls.__mro__):
        s = c.__dict__.get("__slots__", ())
        if isinstance(s, str):
            s = (s,)
        out.extend(s)
    return tuple(out)


# per-kind slots to SKIP: pattern_pred carries its raw source text
# (which embeds literals) next to the parsed pattern — normalize the
# pattern, drop the text
_SKIP_SLOTS = {"pattern_pred": ("text",)}


def _norm(node: Any) -> str:
    """One node's literal-normalized canonical form (recursive)."""
    from ..core.expr import Expr, Literal, ListExpr, SetExpr

    if node is None:
        return "~"
    if isinstance(node, Literal):
        return "?"
    if isinstance(node, Expr):
        if isinstance(node, (ListExpr, SetExpr)) \
                and all(isinstance(i, Literal) for i in node.items):
            return "?*"
        skip = _SKIP_SLOTS.get(node.kind, ())
        inner = ",".join(_norm(getattr(node, s))
                         for s in _expr_slots(type(node)) if s not in skip)
        return f"{node.kind}({inner})"
    if is_dataclass(node):
        inner = ",".join(_norm(getattr(node, f.name))
                         for f in fields(node))
        return f"{type(node).__name__}({inner})"
    if isinstance(node, (list, tuple)):
        items = [_norm(x) for x in node]
        # homogeneous runs collapse: FROM 1,2,3 ≡ FROM 5, a 20-row
        # INSERT ≡ a 1-row INSERT of the same tag/prop shape
        out: List[str] = []
        for it in items:
            if out and out[-1] == f"{it}*":
                continue
            out.append(f"{it}*")
        return "[" + ",".join(out) + "]"
    if isinstance(node, dict):
        inner = ",".join(f"{k}:{_norm(v)}" for k, v in node.items())
        return "{" + inner + "}"
    if isinstance(node, bool) or isinstance(node, (int, float)):
        # bare numbers in dataclass fields are STRUCTURE (GO step
        # bounds, hop limits, LIMIT pushdown counts), not literals
        return repr(node)
    if isinstance(node, str):
        return node
    return f"<{type(node).__name__}>"


def normalize_statement(stmt: Any, space: str = "") -> str:
    """The fingerprint's preimage: statement kind + normalized shape +
    space.  Exposed for the golden tests."""
    return f"{space}|{_norm(stmt)}"


def fingerprint_of(stmt: Any, space: str = "") -> str:
    """12-hex-digit digest of the literal-normalized AST."""
    pre = normalize_statement(stmt, space)
    return hashlib.sha1(pre.encode("utf-8", "replace")).hexdigest()[:12]


def parse_error_fingerprint(text: str, space: str = "") -> str:
    """Unparseable text cannot be normalized — digest the raw text so
    repeated garbage still aggregates under one row."""
    pre = f"{space}|Parse({text})"
    return hashlib.sha1(pre.encode("utf-8", "replace")).hexdigest()[:12]


class _FingerprintCache:
    """Bounded (text, space) → fingerprint memo — the steady-state
    per-statement cost of the insights plane."""

    def __init__(self, capacity: int = 2048):
        self._cap = capacity
        self._map: "OrderedDict[Tuple[str, str], str]" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, text: str, space: str) -> Optional[str]:
        key = (text, space)
        with self._lock:
            fp = self._map.get(key)
            if fp is not None:
                self._map.move_to_end(key)
            return fp

    def put(self, text: str, space: str, fp: str):
        with self._lock:
            self._map[(text, space)] = fp
            while len(self._map) > self._cap:
                self._map.popitem(last=False)

    def clear(self):
        with self._lock:
            self._map.clear()


# -- latency buckets (shared fixed boundaries → exact cross-host merge) -----


_NB = len(LATENCY_BUCKETS_US) + 1      # +1 overflow bucket


def _bucket_index(us: float) -> int:
    for i, b in enumerate(LATENCY_BUCKETS_US):
        if us <= b:
            return i
    return _NB - 1


def bucket_quantile(counts: List[int], q: float) -> int:
    """Quantile estimate from fixed-bucket counts: the upper boundary
    of the bucket where the cumulative count crosses q·total (overflow
    bucket reports the last finite boundary)."""
    total = sum(counts)
    if total <= 0:
        return 0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return int(LATENCY_BUCKETS_US[min(i, _NB - 2)])
    return int(LATENCY_BUCKETS_US[-1])


# -- per-fingerprint accumulation ------------------------------------------


_SUM_FIELDS = ("calls", "errors", "kills", "sheds", "lat_sum_us",
               "queue_us", "device_us", "host_us", "rows", "dispatches",
               "plan_cache_hits", "result_cache_hits", "batched_calls",
               "lanes_sum", "plan_changed")


def _new_row(fp: str, text: str, kind: str, space: str) -> Dict[str, Any]:
    row: Dict[str, Any] = {
        "fingerprint": fp, "sample": text[:120], "kind": kind,
        "space": space, "lat_buckets": [0] * _NB,
        "plans": {},                      # plan_hash → {calls, buckets}
        "active_plan": "", "prev_plan": "", "regressed": False,
    }
    for f in _SUM_FIELDS:
        row[f] = 0
    return row


class StatementRegistry:
    """Bounded per-graphd fingerprint → aggregate table.  One locked
    dict update per completed statement; snapshots are mergeable
    across hosts because every histogram shares the fixed buckets."""

    def __init__(self):
        self._rows: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.fingerprints = _FingerprintCache()

    @staticmethod
    def enabled() -> bool:
        try:
            return bool(get_config().get("insights_enabled"))
        except Exception:  # noqa: BLE001 — config not initialized
            return True

    @staticmethod
    def _cap() -> int:
        try:
            return int(get_config().get("insights_max_fingerprints"))
        except Exception:  # noqa: BLE001
            return 512

    # -- the completion hook ---------------------------------------------

    def record(self, *, fp: str, text: str, kind: str, space: str,
               latency_us: int, error: Optional[str] = None,
               rows: int = 0, queue_us: int = 0, device_us: int = 0,
               dispatches: int = 0, plan_hash: Optional[str] = None,
               plan_cache_hit: bool = False,
               result_cache_hit: bool = False, lanes: int = 0):
        lat = int(latency_us)
        bi = _bucket_index(lat)
        host_us = max(lat - int(queue_us) - int(device_us), 0)
        with self._lock:
            row = self._rows.get(fp)
            if row is None:
                row = _new_row(fp, text, kind, space)
                self._rows[fp] = row
                while len(self._rows) > self._cap():
                    self._rows.popitem(last=False)
                    _stats().inc("insights_evictions")
                _stats().gauge("insights_fingerprints",
                               float(len(self._rows)))
            else:
                self._rows.move_to_end(fp)
            row["calls"] += 1
            row["lat_buckets"][bi] += 1
            row["lat_sum_us"] += lat
            row["queue_us"] += int(queue_us)
            row["device_us"] += int(device_us)
            row["host_us"] += host_us
            row["rows"] += int(rows)
            row["dispatches"] += int(dispatches)
            if error is not None:
                if error == "ExecutionError: query was killed":
                    row["kills"] += 1
                elif error.startswith("E_OVERLOAD"):
                    row["sheds"] += 1
                else:
                    row["errors"] += 1
            if plan_cache_hit:
                row["plan_cache_hits"] += 1
            if result_cache_hit:
                row["result_cache_hits"] += 1
            if lanes > 1:
                row["batched_calls"] += 1
                row["lanes_sum"] += int(lanes)
            if plan_hash:
                self._record_plan(row, plan_hash, lat, bi)

    def _record_plan(self, row: Dict[str, Any], plan_hash: str,
                     lat: int, bi: int):
        """Plan history + the regression sentinel (caller holds lock)."""
        plans = row["plans"]
        p = plans.get(plan_hash)
        if p is None:
            p = plans[plan_hash] = {"calls": 0, "lat_sum_us": 0,
                                    "buckets": [0] * _NB}
        p["calls"] += 1
        p["lat_sum_us"] += lat
        p["buckets"][bi] += 1
        if row["active_plan"] != plan_hash:
            if row["active_plan"]:
                row["prev_plan"] = row["active_plan"]
                row["plan_changed"] += 1
                row["regressed"] = False
            row["active_plan"] = plan_hash
        prev = plans.get(row["prev_plan"])
        if prev is None:
            return
        try:
            ratio = float(get_config().get("plan_regression_ratio"))
            min_calls = int(get_config().get("plan_regression_min_calls"))
        except Exception:  # noqa: BLE001
            ratio, min_calls = 1.5, 8
        if p["calls"] < min_calls or prev["calls"] < min_calls:
            return
        p50_new = bucket_quantile(p["buckets"], 0.5)
        p50_old = bucket_quantile(prev["buckets"], 0.5)
        regressed = p50_old > 0 and p50_new > ratio * p50_old
        if regressed and not row["regressed"]:
            _stats().inc_labeled("plan_regressed",
                                 {"fingerprint": row["fingerprint"]})
        row["regressed"] = regressed

    # -- reading ---------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Mergeable per-fingerprint dicts, most-called first."""
        with self._lock:
            rows = [dict(r, lat_buckets=list(r["lat_buckets"]),
                         plans={h: dict(p, buckets=list(p["buckets"]))
                                for h, p in r["plans"].items()})
                    for r in self._rows.values()]
        rows.sort(key=lambda r: (-r["calls"], r["fingerprint"]))
        return rows

    def get(self, fp: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            r = self._rows.get(fp)
            return dict(r) if r is not None else None

    def clear(self):
        with self._lock:
            self._rows.clear()
        self.fingerprints.clear()

    def __len__(self):
        with self._lock:
            return len(self._rows)


def merge_statement_snapshots(
        snaps: List[List[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Fold per-host registry snapshots into one cluster table: sum
    counters and bucket counts elementwise; the sample/kind and plan
    fields follow the host with the most calls for that fingerprint."""
    merged: Dict[str, Dict[str, Any]] = {}
    best_calls: Dict[str, int] = {}
    for snap in snaps:
        for r in snap or ():
            fp = r["fingerprint"]
            m = merged.get(fp)
            if m is None:
                m = merged[fp] = _new_row(fp, r.get("sample", ""),
                                          r.get("kind", ""),
                                          r.get("space", ""))
                best_calls[fp] = -1
            for f in _SUM_FIELDS:
                m[f] += int(r.get(f, 0))
            for i, c in enumerate(r.get("lat_buckets", ())[:_NB]):
                m["lat_buckets"][i] += int(c)
            for h, p in (r.get("plans") or {}).items():
                mp = m["plans"].get(h)
                if mp is None:
                    mp = m["plans"][h] = {"calls": 0, "lat_sum_us": 0,
                                          "buckets": [0] * _NB}
                mp["calls"] += int(p.get("calls", 0))
                mp["lat_sum_us"] += int(p.get("lat_sum_us", 0))
                for i, c in enumerate(p.get("buckets", ())[:_NB]):
                    mp["buckets"][i] += int(c)
            if int(r.get("calls", 0)) > best_calls[fp]:
                best_calls[fp] = int(r.get("calls", 0))
                m["sample"] = r.get("sample", m["sample"])
                m["kind"] = r.get("kind", m["kind"])
                m["space"] = r.get("space", m["space"])
                m["active_plan"] = r.get("active_plan", "")
                m["prev_plan"] = r.get("prev_plan", "")
            m["regressed"] = m["regressed"] or bool(r.get("regressed"))
    out = list(merged.values())
    out.sort(key=lambda r: (-r["calls"], r["fingerprint"]))
    return out


def statement_columns(rows: List[Dict[str, Any]]) -> List[List[Any]]:
    """The SHOW STATEMENTS column contract (docs/OBSERVABILITY.md §10):
    [Fingerprint, Sample, Calls, Errors, P50 Us, P95 Us, Rows,
     DeviceShare, PlanHash, PlanChanged, Regressed]."""
    out = []
    for r in rows:
        lat_sum = max(int(r.get("lat_sum_us", 0)), 1)
        share = round(int(r.get("device_us", 0)) / lat_sum, 3)
        out.append([
            r["fingerprint"], r.get("sample", ""), int(r.get("calls", 0)),
            int(r.get("errors", 0)) + int(r.get("kills", 0))
            + int(r.get("sheds", 0)),
            bucket_quantile(r.get("lat_buckets", []), 0.5),
            bucket_quantile(r.get("lat_buckets", []), 0.95),
            int(r.get("rows", 0)), share, r.get("active_plan", ""),
            int(r.get("plan_changed", 0)), bool(r.get("regressed"))])
    return out


def plan_shape_hash(plan) -> str:
    """12-hex-digit digest of the optimized plan's kind tree — flips
    when the optimizer changes the shape or a device operator falls
    back to its host twin (TpuTraverse ↔ ExpandAll)."""
    try:
        kinds = plan.root.kind_tree()
    except Exception:  # noqa: BLE001 — plan-less admin statements
        return ""
    return hashlib.sha1(
        ",".join(kinds).encode("utf-8", "replace")).hexdigest()[:12]


# -- per-partition heat maps ------------------------------------------------


class _Heat:
    __slots__ = ("reads", "writes", "read_rows", "write_rows",
                 "read_bytes", "write_bytes", "read_lat_us",
                 "write_lat_us", "read_qps", "write_qps",
                 "_last_reads", "_last_writes", "_last_ts")

    def __init__(self):
        self.reads = 0
        self.writes = 0
        self.read_rows = 0
        self.write_rows = 0
        self.read_bytes = 0
        self.write_bytes = 0
        self.read_lat_us = 0.0     # EWMA
        self.write_lat_us = 0.0    # EWMA
        self.read_qps = 0.0        # EWMA, folded at snapshot time
        self.write_qps = 0.0
        self._last_reads = 0
        self._last_writes = 0
        self._last_ts = time.monotonic()


class PartHeatTable:
    """Per-(space, part) load counters on one storaged.  The hot-path
    record calls are two integer bumps and one EWMA fold under a short
    lock; QPS EWMAs fold once per snapshot (i.e. per heartbeat), so
    idle parts decay toward zero without a background thread."""

    def __init__(self):
        self._parts: Dict[Tuple[str, int], _Heat] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _alpha() -> float:
        try:
            return float(get_config().get("heat_ewma_alpha"))
        except Exception:  # noqa: BLE001
            return 0.3

    def _get(self, space: str, pid: int) -> _Heat:
        key = (space, int(pid))
        h = self._parts.get(key)
        if h is None:
            h = self._parts.setdefault(key, _Heat())
        return h

    def record_read(self, space: str, pid: int, rows: int = 0,
                    latency_us: float = 0.0, nbytes: int = 0):
        a = self._alpha()
        with self._lock:
            h = self._get(space, pid)
            h.reads += 1
            h.read_rows += int(rows)
            h.read_bytes += int(nbytes)
            h.read_lat_us += a * (float(latency_us) - h.read_lat_us)

    def record_write(self, space: str, pid: int, rows: int = 0,
                     latency_us: float = 0.0, nbytes: int = 0):
        a = self._alpha()
        with self._lock:
            h = self._get(space, pid)
            h.writes += 1
            h.write_rows += int(rows)
            h.write_bytes += int(nbytes)
            h.write_lat_us += a * (float(latency_us) - h.write_lat_us)

    def heat_of(self, space: str, pid: int) -> float:
        """THE documented read hook for the replica router and BALANCE
        (ISSUE 16): one part's current load score — smoothed read+write
        QPS, writes double-weighted (they cost a quorum round).  Purely
        observational; callers must treat 0.0 (unknown part) as cold."""
        with self._lock:
            h = self._parts.get((space, int(pid)))
            if h is None:
                return 0.0
            return h.read_qps + 2.0 * h.write_qps

    def snapshot(self) -> List[Dict[str, Any]]:
        """Fold QPS EWMAs forward and emit per-part rows (the heartbeat
        payload).  Mergeable: counters sum, EWMAs max/avg at the
        consumer."""
        a = self._alpha()
        now = time.monotonic()
        out = []
        with self._lock:
            for (space, pid), h in self._parts.items():
                dt = max(now - h._last_ts, 1e-3)
                r_rate = (h.reads - h._last_reads) / dt
                w_rate = (h.writes - h._last_writes) / dt
                h.read_qps += a * (r_rate - h.read_qps)
                h.write_qps += a * (w_rate - h.write_qps)
                h._last_reads, h._last_writes = h.reads, h.writes
                h._last_ts = now
                out.append({
                    "space": space, "part": pid,
                    "reads": h.reads, "writes": h.writes,
                    "read_rows": h.read_rows, "write_rows": h.write_rows,
                    "read_bytes": h.read_bytes,
                    "write_bytes": h.write_bytes,
                    "read_lat_us": round(h.read_lat_us, 1),
                    "write_lat_us": round(h.write_lat_us, 1),
                    "read_qps": round(h.read_qps, 3),
                    "write_qps": round(h.write_qps, 3),
                    "score": round(h.read_qps + 2.0 * h.write_qps, 3)})
        out.sort(key=lambda r: -r["score"])
        return out

    def clear(self):
        with self._lock:
            self._parts.clear()


def merge_heat_snapshots(
        per_host: Dict[str, List[Dict[str, Any]]]) -> List[Dict[str, Any]]:
    """Fold per-host PartHeat rows into one cluster hotspot table:
    counters and QPS sum across a part's replicas (each replica serves
    its own traffic), latency EWMAs take the max replica, and the
    serving hosts are listed for placement context."""
    merged: Dict[Tuple[str, int], Dict[str, Any]] = {}
    for host, rows in per_host.items():
        for r in rows or ():
            key = (r["space"], int(r["part"]))
            m = merged.get(key)
            if m is None:
                m = merged[key] = {
                    "space": r["space"], "part": int(r["part"]),
                    "reads": 0, "writes": 0, "read_rows": 0,
                    "write_rows": 0, "read_bytes": 0, "write_bytes": 0,
                    "read_lat_us": 0.0, "write_lat_us": 0.0,
                    "read_qps": 0.0, "write_qps": 0.0, "score": 0.0,
                    "hosts": []}
            for f in ("reads", "writes", "read_rows", "write_rows",
                      "read_bytes", "write_bytes"):
                m[f] += int(r.get(f, 0))
            for f in ("read_qps", "write_qps", "score"):
                m[f] = round(m[f] + float(r.get(f, 0.0)), 3)
            for f in ("read_lat_us", "write_lat_us"):
                m[f] = round(max(m[f], float(r.get(f, 0.0))), 1)
            m["hosts"].append(host)
    out = list(merged.values())
    for m in out:
        m["hosts"] = sorted(m["hosts"])
    out.sort(key=lambda r: (-r["score"], r["space"], r["part"]))
    return out


def _stats():
    from .stats import stats
    return stats()
