"""Deterministic failpoint injection — the TiKV/CockroachDB fail-rs
analog (ISSUE 5 tentpole).

Every crash-shaped code path in the cluster layer carries a named
failpoint site (`fail.hit("raft:pre_fsync")`); unarmed sites cost one
dict-truthiness check.  Tests and the chaos harness arm sites with a
small action language, per-site:

    fail.arm("rpc:recv", "2*off->1*kill_conn")   # skip 2 hits, kill on 3rd
    fail.arm("wal:pre_fsync", "delay(0.25)")     # one fsync stall
    fail.arm("toss:pre_in", "-1*raise(torn)")    # every hit, forever

Actions:
    off          no-op (consumes a hit — the skip/counting primitive)
    raise[(msg)] raise FailpointError(msg)
    delay(s)     sleep s seconds (stalls, NOT failures)
    kill_conn    raise ConnectionKilled — the RPC layer translates it
                 into tearing down the live connection mid-call (the
                 at-least-once reply-lost hazard)

A spec is a `->`-chain of `[N*]action` terms; N=-1 repeats forever,
omitted N means once.  When the chain exhausts the site disarms.

Seeded schedules (`FaultSchedule`) arm sites with PSEUDO-RANDOM but
fully deterministic triggers: each rule's decisions are drawn from
`random.Random(f"{seed}:{site}")`, so the k-th hit of a site triggers
identically across runs of the same workload — a failing chaos run is
reproducible from its seed alone (tools/chaos_bench.py prints the
reproducer line).

Arming also works from the environment (CI chaos jobs):
    NEBULA_FAILPOINTS="raft:pre_fsync=delay(0.1);rpc:recv=3*off->1*kill_conn"

Observability: every FIRED action (not unarmed hits) increments the
labeled counter `failpoint_fired{name,action}`.
"""
from __future__ import annotations

import os
import random
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["FailpointError", "ConnectionKilled", "FailpointRegistry",
           "FaultSchedule", "fail", "hit"]


class FailpointError(Exception):
    """An armed `raise` action fired."""


class ConnectionKilled(FailpointError):
    """An armed `kill_conn` action fired; rpc.py translates this into
    killing the live connection (reply lost mid-call)."""


_TERM_RE = re.compile(r"^(?:(-?\d+)\*)?([a-z_]+)(?:\(([^)]*)\))?$")
_ACTIONS = frozenset({"off", "raise", "delay", "kill_conn"})


def _parse_spec(spec: str) -> List[List]:
    """'2*off->1*raise(boom)' -> [[2, 'off', None], [1, 'raise', 'boom']]
    (mutable counts — the registry decrements them in place)."""
    terms: List[List] = []
    for raw in spec.split("->"):
        m = _TERM_RE.match(raw.strip())
        if m is None:
            raise ValueError(f"bad failpoint term {raw!r}")
        count = int(m.group(1)) if m.group(1) else 1
        kind, arg = m.group(2), m.group(3)
        if kind not in _ACTIONS:
            raise ValueError(f"unknown failpoint action {kind!r}")
        if kind == "delay":
            arg = float(arg if arg else 0.05)
        terms.append([count, kind, arg])
    if not terms:
        raise ValueError(f"empty failpoint spec {spec!r}")
    return terms


class FailpointRegistry:
    """Name → armed action chain.  `hit()` is the only hot-path entry;
    it returns immediately when nothing is armed anywhere."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, object] = {}     # name → terms | callable
        self._hits: Dict[str, int] = {}         # per-site hit counter
        env = os.environ.get("NEBULA_FAILPOINTS")
        if env:
            for part in env.split(";"):
                part = part.strip()
                if not part:
                    continue
                name, spec = part.split("=", 1)
                self.arm(name.strip(), spec.strip())

    # -- arming -----------------------------------------------------------

    def arm(self, name: str, spec: str):
        """Arm `name` with an action-chain spec (see module doc)."""
        terms = _parse_spec(spec)
        with self._lock:
            self._armed[name] = terms
            self._hits.setdefault(name, 0)

    def arm_callable(self, name: str,
                     fn: Callable[[int, object],
                                  Optional[Tuple[str, object]]]):
        """Arm with a decision function: fn(hit_index, key) returns
        (action, arg) or None for no-op.  `key` is the optional context
        the site passed to hit() (e.g. the raft group name), letting a
        rule target one group/part while the site stays global.  The
        seeded-schedule hook."""
        with self._lock:
            self._armed[name] = fn
            self._hits.setdefault(name, 0)

    def disarm(self, name: str):
        with self._lock:
            self._armed.pop(name, None)

    def reset(self):
        with self._lock:
            self._armed.clear()
            self._hits.clear()

    def scoped(self) -> "_Scope":
        """Context manager that restores the pre-entry armed set on exit
        (test isolation)."""
        return _Scope(self)

    def hit_count(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)

    def armed(self) -> List[str]:
        with self._lock:
            return sorted(self._armed)

    # -- the hot-path entry ----------------------------------------------

    def hit(self, name: str, key=None):
        if not self._armed:             # fast path: nothing armed at all
            return
        with self._lock:
            arm = self._armed.get(name)
            if arm is None:
                return
            idx = self._hits.get(name, 0)
            self._hits[name] = idx + 1
            if not callable(arm):
                while arm and arm[0][0] == 0:
                    arm.pop(0)
                if not arm:
                    self._armed.pop(name, None)
                    return
                term = arm[0]
                if term[0] > 0:
                    term[0] -= 1
                kind, arg = term[1], term[2]
                # eager disarm on exhaustion: the hit AFTER the last
                # term must be a true unarmed no-op (uncounted)
                if term[0] == 0 and len(arm) == 1:
                    self._armed.pop(name, None)
        if callable(arm):
            # decision fns run OUTSIDE the registry lock: a chaos
            # harness decision may block (holding a propose open while
            # a killer thread acts) and must not freeze every other
            # site in the process — raft's own failpoint hits included
            decision = arm(idx, key)
            if decision is None:
                return
            kind, arg = decision
        self._fire(name, kind, arg)

    def _fire(self, name: str, kind: str, arg):
        if kind == "off":
            return
        from .stats import stats
        stats().inc_labeled("failpoint_fired",
                           {"name": name, "action": kind})
        if kind == "delay":
            time.sleep(float(arg))
        elif kind == "raise":
            raise FailpointError(arg or f"failpoint {name} fired")
        elif kind == "kill_conn":
            raise ConnectionKilled(f"failpoint {name} killed connection")


class _Scope:
    def __init__(self, reg: FailpointRegistry):
        self.reg = reg

    def __enter__(self):
        with self.reg._lock:
            self._saved = dict(self.reg._armed)
        return self.reg

    def __exit__(self, *exc):
        with self.reg._lock:
            self.reg._armed.clear()
            self.reg._armed.update(self._saved)
        return False


class FaultSchedule:
    """A seeded, deterministic set of probabilistic failpoint rules.

    rules: [{"fp": name, "action": "raise"|"delay"|"kill_conn"|"off",
             "arg": optional, "p": probability per hit,
             "max": max fires (default unbounded),
             "after": skip the first N hits (default 0),
             "key": only fire when the site's context key contains
                    this substring (e.g. "meta" → only the metad raft
                    group; default: any)}]

    Each rule draws its per-hit trigger decisions from
    random.Random(f"{seed}:{fp}") — the k-th hit of a site always decides
    identically for a given seed, independent of wall-clock or thread
    interleaving, so a failure reproduces from (seed, workload) alone.
    """

    def __init__(self, seed: int, rules: List[Dict]):
        self.seed = int(seed)
        self.rules = rules
        self.fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    def arm(self, reg: Optional[FailpointRegistry] = None):
        reg = reg or fail
        for rule in self.rules:
            name = rule["fp"]
            rng = random.Random(f"{self.seed}:{name}")
            action = rule.get("action", "raise")
            arg = rule.get("arg")
            if action == "delay" and arg is None:
                arg = 0.05
            p = float(rule.get("p", 1.0))
            after = int(rule.get("after", 0))
            cap = rule.get("max")
            keyf = rule.get("key")
            state = {"fired": 0}

            def decide(idx, key, _rng=rng, _p=p, _after=after, _cap=cap,
                       _state=state, _action=action, _arg=arg,
                       _name=name, _keyf=keyf):
                with self._lock:
                    # one draw per hit (under the schedule lock — hits
                    # arrive from many threads) keeps the decision
                    # stream aligned with the hit index regardless of
                    # earlier outcomes
                    r = _rng.random()
                    if _keyf is not None and _keyf not in str(key):
                        return None
                    if idx < _after:
                        return None
                    if _cap is not None and _state["fired"] >= _cap:
                        return None
                    if r >= _p:
                        return None
                    _state["fired"] += 1
                    self.fired[_name] = self.fired.get(_name, 0) + 1
                return (_action, _arg)

            reg.arm_callable(name, decide)
        return self

    def disarm(self, reg: Optional[FailpointRegistry] = None):
        reg = reg or fail
        for rule in self.rules:
            reg.disarm(rule["fp"])

    def describe(self) -> str:
        parts = [f"{r['fp']}={r.get('action', 'raise')}"
                 f"(p={r.get('p', 1.0)})" for r in self.rules]
        return f"seed={self.seed} " + " ".join(parts)


#: process-global registry — all production sites hit() this instance
fail = FailpointRegistry()
hit = fail.hit
