"""Per-query memory accounting with kill-on-exceed.

Analog of the reference's MemoryTracker (reference: src/common/memory
[UNVERIFIED — empty mount, SURVEY §0], SURVEY §2 row 5): every executor
output and every loop that can explode (variable-length MATCH, path
search) charges its allocations against the query's budget; exceeding
it raises MemoryExceeded, which the engine surfaces as a clean
ExecutionError instead of letting one runaway query OOM the process.

The device plane has its own scarce resource: TpuRuntime checks pinned
HBM bytes against `tpu_hbm_limit_bytes` before pinning a snapshot.
"""
from __future__ import annotations

from typing import Any, List, Optional

from .config import define_flag, get_config

define_flag("query_memory_limit_bytes", 1 << 30,
            "per-query intermediate-result budget; 0 disables tracking")
define_flag("tpu_hbm_limit_bytes", 12_000_000_000,
            "max bytes of CSR snapshots pinned to device HBM")


class MemoryExceeded(Exception):
    def __init__(self, used: int, limit: int):
        super().__init__(
            f"query memory exceeded: used≈{used:,} bytes, "
            f"limit {limit:,} (flag query_memory_limit_bytes)")
        self.used = used
        self.limit = limit


def approx_row_bytes(row: List[Any]) -> int:
    """Cheap per-row estimate: container overhead + per-cell cost."""
    total = 64
    for c in row:
        if isinstance(c, str):
            total += 56 + len(c)
        elif isinstance(c, (list, tuple, set)):
            total += 64 + 48 * len(c)
        else:
            total += 48
    return total


def approx_dataset_bytes(rows: List[List[Any]]) -> int:
    """Sampled estimate: first rows price the rest (rows of one node
    output are shape-homogeneous)."""
    n = len(rows)
    if n == 0:
        return 64
    k = min(n, 32)
    sampled = sum(approx_row_bytes(rows[i]) for i in range(k))
    return 64 + (sampled * n) // k


def approx_columnar_bytes(cols) -> int:
    """Charge a columnar result WITHOUT touching `.rows` (which would
    materialize per-row Python lists — the exact cost lazy columnar
    results exist to avoid).  Numeric columns price at their buffer
    size; object columns sample like approx_dataset_bytes."""
    total = 64
    for c in cols:
        dt = getattr(c, "dtype", None)
        if dt is None:
            n = len(c)
            if n:
                k = min(n, 32)
                total += (approx_row_bytes(list(c)[:k]) * n) // k
            continue
        if dt != object:
            total += int(c.nbytes)
            continue
        n = int(c.size)
        if n == 0:
            continue
        k = min(n, 32)
        sampled = approx_row_bytes([c[i] for i in range(k)])
        total += (sampled * n) // k
    return total


class MemoryTracker:
    """One per query execution.  charge() is cumulative: intermediates
    are versioned and kept for $vars/PROFILE, so releases are rare and
    conservatively ignored."""

    __slots__ = ("limit", "used", "_mu")

    def __init__(self, limit: Optional[int] = None):
        import threading
        self._mu = threading.Lock()
        if limit is None:
            limit = int(get_config().get("query_memory_limit_bytes"))
        self.limit = limit
        self.used = 0

    def charge(self, nbytes: int):
        # executors charge from scheduler pool threads concurrently — an
        # unlocked read-modify-write loses updates and under-enforces
        # the kill switch on exactly the large parallel plans it guards
        with self._mu:
            self.used += int(nbytes)
            used = self.used
        if self.limit and used > self.limit:
            raise MemoryExceeded(used, self.limit)

    def charge_rows(self, rows: List[List[Any]]):
        self.charge(approx_dataset_bytes(rows))
