"""Admission control + overload survival (ISSUE 10 tentpole).

Nothing used to stand between a thundering herd and the engine: every
statement grabbed a scheduler slot, charged memory and dispatched to
the device unconditionally, so saturation meant collapse (unbounded
queues, OOM kills, deadline blowouts) instead of bounded degradation.
This module is the graphd-side half of the overload plane:

  * `AdmissionController` — a bounded set of concurrency slots
    (`max_running_queries`; 0 = the disabled sentinel, byte-identical
    to the pre-admission engine) in front of `scheduler.run`, with a
    capped wait queue drained by DEFICIT-WEIGHTED round-robin across
    sessions (`admission_session_weights`) so no session can starve
    another, and a memory watermark
    (`admission_memory_watermark_bytes`) gating new admissions against
    the process-wide total of per-statement MemoryTracker charges.

  * a PRIORITY LANE: control-plane statements (KILL QUERY/SESSION,
    SHOW *, UPDATE CONFIGS, admin introspection) bypass the queue
    entirely — the cluster stays operable at saturation, which is the
    whole point of shedding load instead of timing out uniformly.

  * structured SHEDDING: a full queue fails the statement immediately
    with `E_OVERLOAD` carrying a `retry_after_ms` hint derived from
    the observed drain rate (`DrainEstimator`), instead of letting it
    queue toward a guaranteed deadline blowout.  Deadline-aware queue
    EVICTION: a statement whose PR5 budget expires while queued is
    failed with E_QUERY_TIMEOUT without ever taking a slot, and a
    KILL QUERY / KILL SESSION of a queued statement removes it from
    the queue immediately (slot never consumed).

The cluster-wide halves live elsewhere and share this module's
`overload_error` / `parse_retry_after` contract: the RPC server's
bounded inbox (`rpc_server_inbox_capacity`, cluster/rpc.py) rejects
overflow with E_OVERLOAD + retry-after instead of queuing unboundedly,
the RPC client honors the hint inside the PR5 deadline-budgeted
backoff (overload is breaker-neutral — the reply proves the peer
alive), and the device dispatch gate caps queue depth
(`tpu_dispatch_queue_cap`, tpu/pipeline.py) beyond which fused
pipelines degrade to their stashed host subplan — never wrong, only
slower.  Semantics matrix: docs/ROBUSTNESS.md §7.
"""
from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from . import cancel as _cancel
from .config import define_flag, get_config

define_flag("max_running_queries", 0,
            "admission-control concurrency slots per process; 0 is the "
            "DISABLED sentinel (no queueing, no shedding — byte-"
            "identical to the pre-admission engine, keeping the wire/"
            "work-counter regression probes deterministic)")
define_flag("admission_queue_capacity", 64,
            "statements allowed to WAIT for a slot before new arrivals "
            "are shed with E_OVERLOAD + retry-after")
define_flag("admission_memory_watermark_bytes", 0,
            "process-wide memory watermark: while the summed "
            "MemoryTracker charge of RUNNING statements is at or above "
            "this, new admissions wait in the queue (0 disables; one "
            "statement is always admitted when nothing runs, so the "
            "gate can never deadlock the drain)")
define_flag("admission_session_weights", "",
            "per-session DWRR weights as `sid:weight[,sid:weight...]` "
            "(unlisted sessions weigh 1); runtime-updatable via "
            "UPDATE CONFIGS so an operator can deprioritize a noisy "
            "tenant without a restart")
define_flag("admission_tenant_weights", "",
            "per-tenant (user) DWRR quotas as `user:weight[,...]` "
            "(unlisted tenants weigh 1): the OUTER rotation of the "
            "two-level drain — tenants split slots by these weights, "
            "each tenant's sessions split its share by the session "
            "weights.  Enforced at every graphd, so an aggressor "
            "tenant cannot starve others anywhere in the fleet "
            "(ISSUE 20); runtime-updatable via UPDATE CONFIGS")
define_flag("rpc_server_inbox_capacity", 0,
            "bounded RPC-server dispatch inbox: pipelined requests "
            "beyond this many in flight per server are rejected with "
            "E_OVERLOAD + retry-after instead of queuing unboundedly "
            "(0 = unbounded, today's behavior); raft, meta.* and graph "
            "control methods are exempt so cluster health never sheds")
define_flag("tpu_dispatch_queue_cap", 0,
            "device dispatch-queue depth beyond which fused MATCH "
            "pipelines degrade to their stashed host subplan instead "
            "of piling onto the device (0 = off); never wrong, only "
            "slower")

#: wire prefix of every shed/overload error — the one string clients,
#: the RPC client retry loop and the flight recorder key off
OVERLOAD_PREFIX = "E_OVERLOAD"

_RETRY_AFTER_RE = re.compile(r"retry_after_ms=(\d+)")


def overload_error(retry_after_s: float, where: str, detail: str) -> str:
    """The one E_OVERLOAD wire shape: prefix, human detail, shedding
    site, machine-parseable retry-after hint (milliseconds)."""
    ms = max(int(retry_after_s * 1000), 1)
    return (f"{OVERLOAD_PREFIX}: {detail} [{where}]; "
            f"retry_after_ms={ms}")


def is_overload(err: Optional[str]) -> bool:
    return isinstance(err, str) and err.startswith(OVERLOAD_PREFIX)


def parse_retry_after(err: Optional[str]) -> Optional[float]:
    """retry-after hint in SECONDS from an E_OVERLOAD error string, or
    None when absent/malformed (callers fall back to their backoff)."""
    if not isinstance(err, str):
        return None
    m = _RETRY_AFTER_RE.search(err)
    if m is None:
        return None
    return int(m.group(1)) / 1000.0


class OverloadError(Exception):
    """Shed at admission: the statement never took a slot.  str() is
    the full E_OVERLOAD wire error (retry_after_ms included)."""

    def __init__(self, retry_after_s: float, where: str, detail: str):
        super().__init__(overload_error(retry_after_s, where, detail))
        self.retry_after_s = retry_after_s
        self.where = where


class DrainEstimator:
    """Observed drain rate → retry-after hints.

    A sliding window of completion timestamps prices how long a queue
    of depth N will take to drain; the hint is that estimate clamped to
    [50ms, 5s] so a cold estimator can neither hammer (0) nor park a
    client forever.  With no completions observed yet the hint is a
    flat 500ms — the "come back soon, we just started" default."""

    __slots__ = ("_done", "_mu")

    def __init__(self):
        self._done: "deque[float]" = deque(maxlen=64)
        self._mu = threading.Lock()

    def note_done(self):
        with self._mu:
            self._done.append(time.monotonic())

    def rate(self) -> float:
        """Completions per second over the window (0 when unknown)."""
        with self._mu:
            if len(self._done) < 2:
                return 0.0
            span = self._done[-1] - self._done[0]
            n = len(self._done)
        if span <= 0:
            return 0.0
        return (n - 1) / span

    def retry_after_s(self, depth: int) -> float:
        r = self.rate()
        if r <= 0:
            return 0.5
        return min(max(max(depth, 1) / r, 0.05), 5.0)


# -- control-plane lane ------------------------------------------------------

#: statement kinds that bypass the admission queue: the operator's way
#: back into a saturated cluster.  SHOW/KILL/DESCRIBE are pure
#: introspection or cancellation; USE/UPDATE CONFIGS/GET CONFIGS are
#: the levers that relieve the overload (a capacity bump must not
#: itself queue behind the traffic it exists to drain).
_CONTROL_PREFIXES = ("Show", "Kill", "Desc")
_CONTROL_KINDS = frozenset({
    "Use", "UpdateConfigs", "GetConfigs", "StopJob"})


def is_control_stmt(kind: str) -> bool:
    return kind.startswith(_CONTROL_PREFIXES) or kind in _CONTROL_KINDS


# -- analytics lane (ISSUE 13) ----------------------------------------------

#: statement kinds that run BELOW interactive traffic: long-running
#: whole-graph analytics (`CALL algo.*`).  They queue in a separate
#: FIFO band that drains only when no interactive statement is
#: waiting — strict priority, so a burst of PageRank runs can never
#: add queueing delay to point reads.  Deadline eviction, KILL
#: eviction and the capacity bound apply to the band exactly as to
#: the DWRR queues (an analytic statement whose budget expires while
#: parked fails E_QUERY_TIMEOUT without ever taking a slot).
_ANALYTIC_KINDS = frozenset({"CallAlgo"})


def is_analytic_stmt(kind: str) -> bool:
    return kind in _ANALYTIC_KINDS


# -- the controller ----------------------------------------------------------


class _Waiter:
    __slots__ = ("qid", "session", "kind", "event", "admitted",
                 "cancelled", "t_enq", "tracker", "live", "analytic",
                 "user")

    def __init__(self, qid: int, session: int, kind: str, live, tracker,
                 user: str = ""):
        self.qid = qid
        self.session = session
        self.kind = kind
        self.event = threading.Event()
        self.admitted = False
        self.cancelled = False
        self.t_enq = time.monotonic()
        self.tracker = tracker
        self.live = live
        self.analytic = is_analytic_stmt(kind)
        self.user = user


class Ticket:
    """What acquire() hands back; release() exactly once (engine's
    finally).  mode: 'admitted' holds a slot, 'bypass' (control lane)
    and 'off' (admission disabled) hold nothing."""

    __slots__ = ("_ctl", "mode", "qid", "queue_wait_us", "_released")

    def __init__(self, ctl: "AdmissionController", mode: str, qid: int,
                 queue_wait_us: int = 0):
        self._ctl = ctl
        self.mode = mode
        self.qid = qid
        self.queue_wait_us = queue_wait_us
        self._released = False

    def release(self):
        if self._released:
            return
        self._released = True
        if self.mode == "admitted":
            self._ctl._release_slot(self.qid)


class AdmissionController:
    """Process-wide admission queue in front of every engine's
    scheduler (graphd and standalone share it, like the live workload
    registry — the slots bound the PROCESS, which is what the memory
    watermark and the device plane care about)."""

    #: waiter poll slice: the KILL/deadline/watermark re-check cadence
    #: while queued.  20ms keeps "KILL QUERY removes it immediately"
    #: honest without measurable idle cost.
    POLL_S = 0.02

    def __init__(self):
        self._mu = threading.Lock()
        self._running: Dict[int, _Waiter] = {}      # qid → admitted
        # two-level DWRR (ISSUE 20): the OUTER rotation is per tenant
        # (user), weighted by `admission_tenant_weights`; each tenant
        # holds its own session rotation weighted by
        # `admission_session_weights`.  Single-tenant workloads (every
        # pre-fleet test and default deployment) collapse to the old
        # per-session DWRR exactly — one tenant, inner rotation only.
        # tenant → {"queues": OrderedDict[sid, deque],
        #           "rr": deque[sid], "deficit": {sid: float}}
        self._tenants: "OrderedDict[str, dict]" = OrderedDict()
        self._trr: "deque[str]" = deque()           # tenant rotation
        self._tdeficit: Dict[str, float] = {}
        # lifetime per-tenant admissions (SHOW TENANTS + the
        # tenant_dwrr_share gauge): under sustained contention the
        # shares converge to the configured weights
        self._tenant_admits: Dict[str, int] = {}
        self._admit_total = 0
        # below-interactive band (ISSUE 13): analytics FIFO, drained
        # only when every DWRR session queue is empty
        self._analytic: "deque[_Waiter]" = deque()
        self._queued_n = 0
        self._drain_est = DrainEstimator()
        self._weights_raw = ""
        self._weights: Dict[int, int] = {}
        self._tweights_raw = ""
        self._tweights: Dict[str, int] = {}
        self._listener_installed = False
        # last multi-statement drain burst (size, monotonic ts): the
        # admission→batch-former hand-off (ISSUE 15) — a drain that
        # releases K statements at once is exactly the moment a
        # multi-lane device launch is worth forming
        self._last_burst: Tuple[int, float] = (0, 0.0)

    # -- flags ------------------------------------------------------------

    @staticmethod
    def _flag_int(name: str, dflt: int) -> int:
        try:
            return int(get_config().get(name))
        except Exception:  # noqa: BLE001 — config not initialized
            return dflt

    def slots(self) -> int:
        return self._flag_int("max_running_queries", 0)

    def enabled(self) -> bool:
        return self.slots() > 0

    def capacity(self) -> int:
        return self._flag_int("admission_queue_capacity", 64)

    def watermark(self) -> int:
        return self._flag_int("admission_memory_watermark_bytes", 0)

    def _weight(self, sid: int) -> int:
        try:
            raw = str(get_config().get("admission_session_weights"))
        except Exception:  # noqa: BLE001
            raw = ""
        if raw != self._weights_raw:
            # parse once per distinct flag value; garbage entries are
            # dropped (a half-typed UPDATE CONFIGS must not zero the
            # whole map)
            parsed: Dict[int, int] = {}
            for part in raw.split(","):
                part = part.strip()
                if not part or ":" not in part:
                    continue
                k, _, v = part.partition(":")
                try:
                    parsed[int(k)] = max(int(v), 1)
                except ValueError:
                    continue
            self._weights_raw, self._weights = raw, parsed
        return self._weights.get(sid, 1)

    def _tenant_weight(self, user: str) -> int:
        try:
            raw = str(get_config().get("admission_tenant_weights"))
        except Exception:  # noqa: BLE001
            raw = ""
        if raw != self._tweights_raw:
            parsed: Dict[str, int] = {}
            for part in raw.split(","):
                part = part.strip()
                if not part or ":" not in part:
                    continue
                k, _, v = part.partition(":")
                try:
                    parsed[k.strip()] = max(int(v), 1)
                except ValueError:
                    continue
            self._tweights_raw, self._tweights = raw, parsed
        return self._tweights.get(user, 1)

    def _ensure_listener(self):
        """A capacity/watermark/weight bump via UPDATE CONFIGS or
        PUT /flags must drain a waiting queue WITHOUT a restart — the
        config layer's listener hook is exactly that kick."""
        if self._listener_installed:
            return
        self._listener_installed = True

        def on_flag(name, _value):
            if name in ("max_running_queries", "admission_queue_capacity",
                        "admission_memory_watermark_bytes",
                        "admission_session_weights",
                        "admission_tenant_weights"):
                self.kick()
        get_config().listeners.append(on_flag)

    # -- memory gate ------------------------------------------------------

    def _mem_total_locked(self) -> int:
        return sum(int(getattr(w.tracker, "used", 0) or 0)
                   for w in self._running.values())

    def _mem_ok_locked(self, wm: int) -> bool:
        if wm <= 0:
            return True
        if not self._running:
            return True      # always admit one: the gate must not wedge
        return self._mem_total_locked() < wm

    # -- metrics ----------------------------------------------------------

    def _gauges_locked(self):
        from .stats import stats
        stats().gauge("admission_running", float(len(self._running)))
        stats().gauge("admission_queue_depth", float(self._queued_n))

    def _note_admit_locked(self, w: _Waiter):
        """Per-tenant admission accounting: the `tenant_dwrr_share`
        gauge is this tenant's lifetime share of admissions — under
        sustained contention it converges to the weight ratio (the
        fleet QoS proof reads it)."""
        u = w.user or "-"
        self._tenant_admits[u] = self._tenant_admits.get(u, 0) + 1
        self._admit_total += 1
        from .stats import stats
        stats().gauge_labeled(
            "tenant_dwrr_share", {"tenant": u},
            round(self._tenant_admits[u] / self._admit_total, 4))

    # -- acquire / release ------------------------------------------------

    def acquire(self, qid: int, session: int, kind: str, live=None,
                tracker=None, user: str = "") -> Optional[Ticket]:
        """Block until the statement may run.  Returns a Ticket (or
        None when admission is disabled — the zero-cost sentinel path).
        Raises OverloadError (shed, queue full), DeadlineExceeded
        (budget expired while queued — no slot consumed) or
        QueryKilled (killed while queued).  `user` is the tenant
        identity for the outer DWRR rotation (ISSUE 20)."""
        slots = self.slots()
        if slots <= 0:
            return None
        self._ensure_listener()
        from .stats import stats
        if is_control_stmt(kind):
            # priority lane: the cluster stays operable at saturation
            stats().inc_labeled("admission_bypass", {"kind": kind})
            return Ticket(self, "bypass", qid)
        w = _Waiter(qid, session, kind, live, tracker, user=user)
        with self._mu:
            # the fast path requires an EMPTY queue (total, both
            # bands): an analytic arrival must not jump a queued
            # interactive statement, and vice versa FIFO order holds
            if self._queued_n == 0 and len(self._running) < slots \
                    and self._mem_ok_locked(self.watermark()):
                # fast path: empty queue, free slot, memory headroom
                self._running[qid] = w
                w.admitted = True
                self._note_admit_locked(w)
                self._gauges_locked()
                return Ticket(self, "admitted", qid)
            if self._queued_n >= max(self.capacity(), 0):
                depth = self._queued_n
                retry = self._drain_est.retry_after_s(depth)
                stats().inc("admission_shed")
                raise OverloadError(
                    retry, "graphd:admission",
                    f"admission queue full (depth={depth}, "
                    f"capacity={self.capacity()}, "
                    f"running={len(self._running)})")
        # enqueue (outside the lock: the failpoint may sleep or raise —
        # `admission:enqueue` armed with delay() holds a statement at
        # the enqueue boundary, raise() rejects it)
        from .failpoints import fail
        fail.hit("admission:enqueue", key=kind)
        with self._mu:
            if self._queued_n >= max(self.capacity(), 0):
                # re-check after the unlocked failpoint window: the
                # capacity bound stays honest under concurrent arrivals
                depth = self._queued_n
                retry = self._drain_est.retry_after_s(depth)
                stats().inc("admission_shed")
                raise OverloadError(
                    retry, "graphd:admission",
                    f"admission queue full (depth={depth}, "
                    f"capacity={self.capacity()}, "
                    f"running={len(self._running)})")
            if w.analytic:
                # below-interactive band: FIFO, drained only when the
                # DWRR rotation is empty
                self._analytic.append(w)
            else:
                t = self._tenants.get(user)
                if t is None:
                    t = self._tenants[user] = {
                        "queues": OrderedDict(), "rr": deque(),
                        "deficit": {}}
                    self._trr.append(user)
                q = t["queues"].get(session)
                if q is None:
                    q = t["queues"][session] = deque()
                    t["rr"].append(session)
                q.append(w)
            self._queued_n += 1
            if live is not None:
                live.queued = True
            stats().inc("admission_enqueued")
            self._gauges_locked()
        # the enqueue raced a release: a drain may already owe us a slot
        self._drain()
        return self._wait(w)

    def _wait(self, w: _Waiter) -> Ticket:
        from .stats import stats
        while True:
            if w.event.wait(self.POLL_S):
                break
            kill = _cancel.current_kill()
            if kill is not None and kill.is_set():
                if self._evict(w):
                    stats().inc("admission_kill_evictions")
                    raise _cancel.QueryKilled(
                        "query was killed while queued for admission")
                break      # admitted in the race — scheduler kills it
            rem = _cancel.remaining()
            if rem is not None and rem <= 0:
                if self._evict(w):
                    # the ISSUE's contract: budget spent while QUEUED →
                    # E_QUERY_TIMEOUT without ever consuming a slot
                    stats().inc("admission_deadline_evictions")
                    raise _cancel.DeadlineExceeded(
                        "deadline exhausted while queued for admission")
                break
            # watermark may have dropped / flags may have changed with
            # no release to kick the drain — re-check on the poll beat
            self._drain()
        waited_us = int((time.monotonic() - w.t_enq) * 1e6)
        if w.live is not None:
            w.live.queued = False
            w.live.add("queue_us", waited_us)
        stats().observe("admission_queue_wait_us", waited_us)
        return Ticket(self, "admitted", w.qid, queue_wait_us=waited_us)

    def _evict(self, w: _Waiter) -> bool:
        """Remove a queued waiter (kill/deadline).  False when the
        waiter won admission in the race — the caller then proceeds
        with the slot and lets the scheduler's own cancel check fire."""
        with self._mu:
            if w.admitted:
                return False
            w.cancelled = True
            if w.analytic:
                q = self._analytic
            else:
                t = self._tenants.get(w.user)
                q = t["queues"].get(w.session) if t else None
            if q is not None:
                try:
                    q.remove(w)
                except ValueError:
                    pass
            self._queued_n = max(self._queued_n - 1, 0)
            self._gauges_locked()
            return True

    def _release_slot(self, qid: int):
        with self._mu:
            if self._running.pop(qid, None) is None:
                return
            self._gauges_locked()
        self._drain_est.note_done()
        self._drain()

    def kick(self):
        """Re-drain on external state changes (config listener)."""
        self._drain()

    # -- the DWRR drain ---------------------------------------------------

    def _session_next_locked(self, t: dict) -> Optional[_Waiter]:
        """Inner rotation: next waiter of ONE tenant by session-weighted
        DWRR (the pre-fleet algorithm, verbatim, scoped to the
        tenant)."""
        rr, queues, deficit = t["rr"], t["queues"], t["deficit"]
        guard = 2 * len(rr) + 2
        for _ in range(guard):
            if not rr:
                return None
            sid = rr[0]
            q = queues.get(sid)
            if not q:
                rr.popleft()
                queues.pop(sid, None)
                deficit.pop(sid, None)
                continue
            if deficit.get(sid, 0.0) >= 1.0:
                deficit[sid] -= 1.0
                return q.popleft()
            deficit[sid] = deficit.get(sid, 0.0) + self._weight(sid)
            rr.rotate(-1)
        return None

    def _drr_next_locked(self) -> Optional[_Waiter]:
        """Next waiter by TWO-LEVEL deficit-weighted round-robin
        (ISSUE 20): the outer rotation credits each backlogged tenant
        its `admission_tenant_weights` weight per visit, one admission
        costs one credit — so tenants split admissions in proportion
        to their quotas no matter how many sessions an aggressor
        opens; within a tenant the session rotation splits its share
        by the session weights.  An emptied tenant's deficit dies with
        its queues (no banked bursts)."""
        tguard = 2 * len(self._trr) + 2
        for _ in range(tguard):
            if not self._trr:
                return None
            u = self._trr[0]
            t = self._tenants.get(u)
            if t is None or not any(t["queues"].values()):
                self._trr.popleft()
                self._tenants.pop(u, None)
                self._tdeficit.pop(u, None)
                continue
            if self._tdeficit.get(u, 0.0) >= 1.0:
                w = self._session_next_locked(t)
                if w is None:
                    # queues raced empty between the check and the pick
                    self._trr.rotate(-1)
                    continue
                self._tdeficit[u] -= 1.0
                self._queued_n = max(self._queued_n - 1, 0)
                return w
            self._tdeficit[u] = self._tdeficit.get(u, 0.0) \
                + self._tenant_weight(u)
            self._trr.rotate(-1)
        return None

    def _next_locked(self) -> Optional[_Waiter]:
        """DWRR first; the analytics band drains ONLY when no
        interactive statement waits (strict below-interactive
        priority, ISSUE 13)."""
        w = self._drr_next_locked()
        if w is None and self._analytic:
            w = self._analytic.popleft()
            self._queued_n = max(self._queued_n - 1, 0)
        return w

    def _drain(self):
        admitted = []
        with self._mu:
            slots = self.slots()
            wm = self.watermark()
            while self._queued_n > 0:
                if slots > 0 and len(self._running) >= slots:
                    break
                if slots > 0 and not self._mem_ok_locked(wm):
                    break
                w = self._next_locked()
                if w is None:
                    break
                # slots<=0 → admission was disabled live: everyone goes
                self._running[w.qid] = w
                w.admitted = True
                self._note_admit_locked(w)
                admitted.append(w)
            if admitted:
                self._gauges_locked()
            if len(admitted) > 1:
                # hand the burst to the device batch former (ISSUE 15):
                # K statements released together are K candidate lanes
                self._last_burst = (len(admitted), time.monotonic())
        for w in admitted:
            w.event.set()

    def concurrency_hint(self) -> bool:
        """Is concurrent statement traffic in evidence right now?  The
        device batch former (tpu/batch.py) consults this before paying
        the forming window: queued or multiply-running statements, or a
        drain burst within the last quarter second, mean batchmates are
        plausibly en route.  Plain int reads — GIL-atomic, no lock."""
        if self._queued_n > 0 or len(self._running) > 1:
            return True
        n, ts = self._last_burst
        return n >= 2 and (time.monotonic() - ts) < 0.25

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._mu:
            return {
                "slots": self.slots(),
                "running": len(self._running),
                "queued": self._queued_n,
                "queued_by_session": {
                    sid: len(q)
                    for t in self._tenants.values()
                    for sid, q in t["queues"].items() if q},
                "queued_by_tenant": {
                    u or "-": sum(len(q) for q in t["queues"].values())
                    for u, t in self._tenants.items()
                    if any(t["queues"].values())},
                "analytic_queued": len(self._analytic),
                "memory_bytes": self._mem_total_locked(),
                "drain_rate_per_s": round(self._drain_est.rate(), 3),
            }

    def tenant_snapshot(self) -> list:
        """Per-tenant admission rows (SHOW TENANTS / GET /tenants):
        weight, live running/queued counts, lifetime admissions and
        admission share on THIS graphd — the cluster view sums rows
        across the fleet."""
        with self._mu:
            users = set(self._tenant_admits)
            users.update(u or "-" for u in self._tenants)
            users.update((w.user or "-") for w in self._running.values())
            tot = max(self._admit_total, 1)
            rows = []
            for u in sorted(users):
                t = self._tenants.get("" if u == "-" else u)
                rows.append({
                    "tenant": u,
                    "weight": self._tenant_weight("" if u == "-" else u),
                    "running": sum(1 for w in self._running.values()
                                   if (w.user or "-") == u),
                    "queued": sum(len(q) for q in t["queues"].values())
                    if t else 0,
                    "admitted": self._tenant_admits.get(u, 0),
                    "share": round(
                        self._tenant_admits.get(u, 0) / tot, 4),
                })
            return rows

    def reset(self):
        """Test isolation: wake every waiter and drop all state."""
        with self._mu:
            waiters = [w for t in self._tenants.values()
                       for q in t["queues"].values() for w in q]
            waiters.extend(self._analytic)
            self._tenants.clear()
            self._trr.clear()
            self._tdeficit.clear()
            self._tenant_admits.clear()
            self._admit_total = 0
            self._analytic.clear()
            self._queued_n = 0
            self._running.clear()
        for w in waiters:
            w.admitted = True
            w.event.set()


_controller = AdmissionController()


def admission() -> AdmissionController:
    """The process-wide controller (engines acquire around
    scheduler.run; GET /admission and the bench read snapshot())."""
    return _controller
