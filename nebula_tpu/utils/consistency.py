"""Read-consistency levels for the storage read path (ISSUE 11).

Every storage read carries a consistency level:

  * ``leader`` (default) — today's behavior: the part leader serves,
    gated by its heartbeat lease (`RaftPart.has_lease`).  Linearizable
    modulo the lease clock-skew margin.
  * ``follower`` — read-index reads: ANY replica may serve after
    obtaining a read barrier from the leader (`RaftPart.read_index`)
    and waiting for its local apply to catch up.  Observes everything
    committed before the read started; spreads read load across the
    replica set and survives a leader loss as soon as a new leader is
    elected.
  * ``bounded_stale`` — a replica serves purely locally when it heard
    from a live leader within `read_max_stale_ms` (and its applied
    index covers the caller's read-your-writes floor); otherwise it
    rejects with a structured ``E_STALE`` + lag hint and the client
    walks to a fresher replica.  Available even while the leader is
    down or unreachable — the weakest, most available level.

The effective level for a call is the thread-local override
(`use_consistency`, installed by tests and storm drivers) falling back
to the `read_consistency` flag.  Semantics matrix: docs/ROBUSTNESS.md
§8 "Read-path consistency".
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from .config import define_flag, get_config

LEADER = "leader"
FOLLOWER = "follower"
BOUNDED_STALE = "bounded_stale"
LEVELS = (LEADER, FOLLOWER, BOUNDED_STALE)

define_flag("read_consistency", "leader",
            "default consistency level for storage reads: leader "
            "(lease-gated leader reads, today's behavior), follower "
            "(read-index reads — any replica serves after a leader "
            "read barrier + local apply catch-up), or bounded_stale "
            "(replica serves locally while its staleness is within "
            "read_max_stale_ms, else rejects with E_STALE)")
define_flag("read_max_stale_ms", 5000.0,
            "bounded_stale staleness bound: a non-leader replica may "
            "serve a bounded_stale read only while it heard from a "
            "live leader within this window (and its applied index "
            "covers the caller's read-your-writes floor)")

_tls = threading.local()


def current_override() -> Optional[str]:
    return getattr(_tls, "level", None)


def effective_consistency() -> str:
    """The level this thread's storage reads run at: the TLS override
    if installed, else the `read_consistency` flag (unknown flag values
    degrade to `leader` — the safe default — rather than erroring in
    the middle of a read)."""
    lvl = current_override()
    if lvl in LEVELS:
        return lvl
    try:
        lvl = str(get_config().get("read_consistency"))
    except Exception:  # noqa: BLE001 — config not initialized
        return LEADER
    return lvl if lvl in LEVELS else LEADER


@contextmanager
def use_consistency(level: Optional[str]):
    """Scope a read-consistency override to this thread (storm drivers
    mixing levels concurrently; tests pinning one call's level).
    None = no override (the flag decides) — the pass-through form pool
    threads use to mirror their submitting thread's state."""
    if level is not None and level not in LEVELS:
        raise ValueError(f"unknown consistency level {level!r} "
                         f"(one of {LEVELS})")
    prev = getattr(_tls, "level", None)
    _tls.level = level
    try:
        yield
    finally:
        _tls.level = prev
