"""Query layer: tokenizer, parser, AST, plan, planner, optimizer."""
from .parser import ParseError, parse
from .plan import ExecutionPlan, PlanNode, transform_plan, walk_plan
from .planner import PlannerContext, QueryError, plan_statement
from .optimizer import RULES, optimize, register_rule
