"""Statement → ExecutionPlan: validation + planning.

Folds the reference's validator layer (per-sentence semantic checks +
symbol/type deduction; reference: src/graph/validator [UNVERIFIED]) and
planner layer (GoPlanner/MatchPlanner/...; reference: src/graph/planner
[UNVERIFIED]) into one pass per statement: semantic validation happens
while the plan is built, against the live catalog.

Plan shapes (golden-plan tests pin these):

  GO n STEPS FROM x OVER e WHERE f YIELD c:
      Project ← Filter? ← ExpandAll ← [Dedup ← Project ← ExpandAll]×(n-1) ← Start
  GO m TO n: Union-ALL of the per-step branches sharing the frontier chain.
  MATCH (a)-[e]->(b):
      Project ← Filter? ← AppendVertices ← Traverse×k ← <seed> ← Start
  LOOKUP:  Project ← Filter? ← IndexScan
  FETCH:   Project ← GetVertices | GetEdges
  FIND PATH / GET SUBGRAPH: one algo node.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.expr import (AggExpr, AttributeExpr, Binary, Case, DictContext,
                         Expr, FunctionCall, InputProp, LabelExpr,
                         LabelTagProp, Literal, Unary, VarExpr, VarProp,
                         EdgeProp, VertexExpr, EdgeExpr, has_aggregate,
                         join_conjuncts, rewrite, split_conjuncts, to_text,
                         walk)
from ..core.value import NULL
from ..graphstore.schema import SchemaError
from . import ast as A
from .plan import ExecutionPlan, PlanNode


class QueryError(Exception):
    pass


class PlannerContext:
    """Carries catalog access + pipe/variable column bindings."""

    def __init__(self, qctx, space: Optional[str]):
        self.qctx = qctx              # QueryContext (exec/context.py)
        self.space = space
        self.input_node: Optional[PlanNode] = None
        self.input_cols: List[str] = []
        self.var_cols: Dict[str, List[str]] = {}   # $var → col names
        self.var_nodes: Dict[str, PlanNode] = {}

    @property
    def catalog(self):
        return self.qctx.catalog

    def need_space(self) -> str:
        if not self.space:
            raise QueryError("no space selected (USE <space> first)")
        return self.space


def plan_statement(qctx, stmt: A.Sentence, space: Optional[str]) -> ExecutionPlan:
    pctx = PlannerContext(qctx, space)
    root = _plan(pctx, stmt)
    return ExecutionPlan(root, pctx.space)


# ---------------------------------------------------------------------------


def _plan(pctx: PlannerContext, stmt: A.Sentence) -> PlanNode:
    h = _DISPATCH.get(type(stmt))
    if h is None:
        raise QueryError(f"unsupported statement {type(stmt).__name__}")
    return h(pctx, stmt)


def _start(pctx) -> PlanNode:
    return PlanNode("Start")


def _col_name(col: A.YieldColumn) -> str:
    return col.alias if col.alias else to_text(col.expr)


# ---- composition ----------------------------------------------------------


def _plan_seq(pctx, s: A.SeqSentence) -> PlanNode:
    nodes = [_plan(pctx, x) for x in s.stmts]
    # sequence: each depends on the previous for ordering; result = last
    for i in range(1, len(nodes)):
        seq = PlanNode("Sequence", deps=[nodes[i - 1], nodes[i]],
                       col_names=nodes[i].col_names)
        nodes[i] = seq
    return nodes[-1]


def _plan_pipe(pctx, s: A.PipedSentence) -> PlanNode:
    left = _plan(pctx, s.left)
    saved_node, saved_cols = pctx.input_node, pctx.input_cols
    pctx.input_node, pctx.input_cols = left, list(left.col_names)
    right = _plan(pctx, s.right)
    pctx.input_node, pctx.input_cols = saved_node, saved_cols
    return right


def _plan_assign(pctx, s: A.AssignSentence) -> PlanNode:
    node = _plan(pctx, s.stmt)
    pctx.var_cols[s.var] = list(node.col_names)
    pctx.var_nodes[s.var] = node
    # register in qctx for cross-statement $var reads inside one submit
    alias = PlanNode("SetVariable", deps=[node], col_names=node.col_names,
                     args={"var": s.var, "source": node.output_var})
    return alias


def _plan_setop(pctx, s: A.SetOpSentence) -> PlanNode:
    left = _plan(pctx, s.left)
    saved_node, saved_cols = pctx.input_node, pctx.input_cols
    right = _plan(pctx, s.right)
    pctx.input_node, pctx.input_cols = saved_node, saved_cols
    if len(left.col_names) != len(right.col_names):
        raise QueryError("set operation branches have different column counts")
    kind = {"UNION": "Union", "UNION ALL": "Union", "INTERSECT": "Intersect",
            "MINUS": "Minus"}[s.op]
    node = PlanNode(kind, deps=[left, right], col_names=list(left.col_names),
                    args={"distinct": s.op == "UNION"})
    return node


def _plan_explain(pctx, s: A.ExplainSentence) -> PlanNode:
    inner = _plan(pctx, s.stmt)
    return PlanNode("Explain", deps=[inner], col_names=["plan"],
                    args={"profile": s.profile, "fmt": s.fmt})


# ---- expression rewriting -------------------------------------------------


def _rewrite_go_expr(pctx, e: Expr, edge_names: List[str]) -> Expr:
    """knows.since → EdgeProp; validate prop refs against schemas."""
    space = pctx.need_space()
    cat = pctx.catalog

    def fn(x: Expr):
        if isinstance(x, AttributeExpr) and isinstance(x.obj, LabelExpr):
            name = x.obj.name
            if name in edge_names:
                _check_edge_prop(cat, space, name, x.attr)
                return EdgeProp(name, x.attr)
        return None

    e = rewrite(e, fn)
    for x in walk(e):
        if isinstance(x, (type(e),)):
            pass
        if x.kind == "src_prop" or x.kind == "dst_prop":
            _check_tag_prop(cat, space, x.tag, x.name)
        if x.kind == "edge_prop":
            if x.edge not in edge_names and x.edge != "*":
                _check_edge_prop(cat, space, x.edge, x.name)
        if x.kind == "input_prop" and pctx.input_cols and x.name not in pctx.input_cols:
            raise QueryError(f"unknown input column `$-.{x.name}'"
                             f" (have {pctx.input_cols})")
        if x.kind == "var_prop":
            cols = pctx.var_cols.get(x.var)
            if cols is not None and x.name not in cols:
                raise QueryError(f"unknown column `${x.var}.{x.name}'")
    return e


def _check_edge_prop(cat, space, edge, prop):
    if prop in ("_src", "_dst", "_rank", "_type"):
        return
    try:
        es = cat.get_edge(space, edge)
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    if es.latest.prop(prop) is None:
        raise QueryError(f"edge `{edge}' has no property `{prop}'")


def _check_tag_prop(cat, space, tag, prop):
    try:
        ts = cat.get_tag(space, tag)
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    if ts.latest.prop(prop) is None:
        raise QueryError(f"tag `{tag}' has no property `{prop}'")


def _rewrite_match_expr(e: Expr, aliases: Dict[str, str]) -> Expr:
    """v.tag.prop → LabelTagProp for known aliases."""
    def fn(x: Expr):
        if (isinstance(x, AttributeExpr) and isinstance(x.obj, AttributeExpr)
                and isinstance(x.obj.obj, LabelExpr)
                and x.obj.obj.name in aliases):
            return LabelTagProp(x.obj.obj.name, x.obj.attr, x.attr)
        return None
    return rewrite(e, fn)


# ---- GO -------------------------------------------------------------------


def _resolve_from(pctx, fc: A.FromClause) -> Tuple[Any, Optional[str]]:
    """Returns (vid_exprs|None, input_ref_col|None)."""
    if fc.ref is not None:
        if isinstance(fc.ref, InputProp):
            if pctx.input_cols and fc.ref.name not in pctx.input_cols:
                raise QueryError(f"unknown input column `$-.{fc.ref.name}'")
            return None, fc.ref.name
        if isinstance(fc.ref, VarProp):
            cols = pctx.var_cols.get(fc.ref.var)
            if cols is not None and fc.ref.name not in cols:
                raise QueryError(f"unknown column `${fc.ref.var}.{fc.ref.name}'")
            return None, f"${fc.ref.var}.{fc.ref.name}"
        raise QueryError("FROM clause reference must be $-.col or $var.col")
    return fc.vids, None


_GO_DEFAULT_YIELD = None  # built lazily


def _go_default_yield() -> A.YieldClause:
    return A.YieldClause([A.YieldColumn(
        FunctionCall("dst", [EdgeExpr()]), "dst")])


def _plan_go(pctx, s: A.GoSentence) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    edges = s.over.edges
    if s.over.is_all:
        edges = sorted(e.name for e in cat.edges(space))
    else:
        for e in edges:
            try:
                cat.get_edge(space, e)
            except SchemaError as ex:
                raise QueryError(str(ex)) from None

    yld = s.yield_ or _go_default_yield()
    where_expr = None
    if s.where:
        where_expr = _rewrite_go_expr(pctx, s.where.filter, edges)
    ycols = [A.YieldColumn(_rewrite_go_expr(pctx, c.expr, edges), c.alias)
             for c in yld.columns]
    col_names = [_col_name(c) for c in ycols]

    vids, ref_col = _resolve_from(pctx, s.from_)
    uses_input = ref_col is not None or any(
        x.kind == "input_prop" for c in ycols for x in walk(c.expr)) or (
        where_expr is not None and any(x.kind == "input_prop" for x in walk(where_expr)))

    if ref_col is not None and ref_col.startswith("$"):
        var = ref_col[1:].split(".")[0]
        src_node = _var_input_node(pctx, var)
        input_cols = pctx.var_cols.get(var, [])
        src_col = ref_col.split(".")[1]
    elif ref_col is not None:
        src_node = pctx.input_node
        input_cols = pctx.input_cols
        src_col = ref_col
    else:
        src_node = None
        input_cols = []
        src_col = None

    start: PlanNode
    if src_node is not None:
        start = src_node
    else:
        start = PlanNode("Start", col_names=["_vid"],
                         args={"vids": vids})

    m, n = s.steps.m, s.steps.n
    if n < m or n < 0 or m < 0:
        raise QueryError(f"invalid step range {m} TO {n}")
    go_pairs = [(c.expr, nm) for c, nm in zip(ycols, col_names)]
    go_agg = _implicit_agg_split(go_pairs)
    if n == 0:
        out = PlanNode("Project", deps=[start], col_names=col_names,
                       args={"columns": [], "empty": True})
        if go_agg is not None:
            # aggregate-over-empty yields its fold identity (count → 0),
            # same as a source vertex with no edges
            out = _plan_aggregate(out, go_agg[1], None)
        return out

    carry = list(input_cols) if uses_input and src_node is not None else []

    def expand(dep: PlanNode, step_src_col: Optional[str], first: bool) -> PlanNode:
        return PlanNode("ExpandAll", deps=[dep], args={
            "space": space, "edge_types": list(edges),
            "direction": s.over.direction,
            "src_col": step_src_col,          # None → use literal vids
            "vids": vids if first and src_node is None else None,
            "edge_filter": None, "limit": None,
            "sample": None, "carry": list(carry),
        }, col_names=(carry + ["_src", "_edge", "_dst"]))

    # frontier chain: F1 = expand(start); Fk = expand(dedup(project_dst(Fk-1)))
    branches: List[PlanNode] = []
    cur = start
    cur_src_col = src_col
    for step in range(1, n + 1):
        first = step == 1
        exp = expand(cur, cur_src_col, first)
        if m <= step:
            branch = exp
            if where_expr is not None:
                branch = PlanNode("Filter", deps=[branch],
                                  col_names=list(branch.col_names),
                                  args={"condition": where_expr})
            proj = PlanNode("Project", deps=[branch], col_names=col_names,
                            args={"columns": (go_agg[0] if go_agg
                                              else go_pairs),
                                  "go_row": True})
            branches.append(proj)
        if step < n:
            if carry:
                # keep full rows: next step expands from _dst, carrying cols
                nxt_cols = carry + ["_dst"]
                nxt = PlanNode("Project", deps=[exp], col_names=nxt_cols,
                               args={"columns":
                                     [(InputProp(c), c) for c in carry]
                                     + [(InputProp("_dst"), "_dst")],
                                     "go_row": False})
                cur, cur_src_col = nxt, "_dst"
            else:
                nxt = PlanNode("Project", deps=[exp], col_names=["_vid"],
                               args={"columns": [(InputProp("_dst"), "_vid")],
                                     "go_row": False})
                ddp = PlanNode("Dedup", deps=[nxt], col_names=["_vid"])
                cur, cur_src_col = ddp, "_vid"

    out = branches[0]
    for b in branches[1:]:
        out = PlanNode("Union", deps=[out, b], col_names=col_names,
                       args={"distinct": False})
    if go_agg is not None:
        # implicit aggregation folds over ALL steps' rows (after the
        # m-to-n union), grouped by the non-aggregate yield columns
        out = _plan_aggregate(out, go_agg[1], None)
    if yld.distinct:
        out = PlanNode("Dedup", deps=[out], col_names=col_names)
    if s.truncate is not None:
        counts = s.truncate.counts
        out = PlanNode("Sample" if s.truncate.is_sample else "Limit",
                       deps=[out], col_names=col_names,
                       args={"count": counts[-1] if counts else 0, "offset": 0})
    return out


# ---- YIELD / pipe segments ------------------------------------------------


def _var_input_node(pctx, var: str) -> PlanNode:
    """A node that reads a $var result saved earlier in the session."""
    node = pctx.var_nodes.get(var)
    if node is not None:
        return node
    cols = pctx.var_cols.get(var, [])
    n = PlanNode("VarInput", col_names=list(cols), args={"var": var})
    n.output_var = f"${var}"
    return n


def _plan_yield(pctx, s: A.YieldSentence) -> PlanNode:
    dep = pctx.input_node or PlanNode("Start", col_names=[])
    cols = s.yield_.columns
    # $var.col references: bind the variable's dataset as the input
    var_refs = {x.var for c in cols for x in walk(c.expr) if x.kind == "var_prop"}
    if s.where is not None:
        var_refs |= {x.var for x in walk(s.where.filter) if x.kind == "var_prop"}
    where_filter = s.where.filter if s.where is not None else None
    from_var = bool(var_refs)
    if from_var:
        # bind the $var's dataset as the input and read cols via $-.
        if len(var_refs) > 1:
            raise QueryError("YIELD over multiple $variables is unsupported")
        var = var_refs.pop()
        dep = _var_input_node(pctx, var)

        def _v2i(x):
            if isinstance(x, VarProp) and x.var == var:
                return InputProp(x.name)
            return None
        cols = [A.YieldColumn(rewrite(c.expr, _v2i),
                              c.alias or to_text(c.expr)) for c in cols]
        if where_filter is not None:
            where_filter = rewrite(where_filter, _v2i)
    exprs = []
    for c in cols:
        e = c.expr
        for x in walk(e):
            if x.kind == "input_prop" and pctx.input_cols and not from_var \
                    and x.name not in pctx.input_cols:
                raise QueryError(f"unknown input column `$-.{x.name}'")
        exprs.append(e)
    names = [_col_name(c) for c in cols]
    out = dep
    if where_filter is not None:
        out = PlanNode("Filter", deps=[out], col_names=list(out.col_names),
                       args={"condition": where_filter})
    if any(has_aggregate(e) for e in exprs):
        out = _plan_aggregate(out, list(zip(exprs, names)), group_keys=None)
    else:
        out = PlanNode("Project", deps=[out], col_names=names,
                       args={"columns": list(zip(exprs, names))})
    if s.yield_.distinct:
        out = PlanNode("Dedup", deps=[out], col_names=names)
    return out


def _implicit_agg_split(pairs: List[Tuple[Expr, str]]):
    """Implicit aggregation in GO/LOOKUP/FETCH YIELD (reference:
    GoValidator/LookupValidator semantics — `GO ... YIELD count(*)`
    folds over ALL result rows, grouped by the non-aggregate columns).

    Returns None when no column aggregates; otherwise (inner, outer):
    the per-row projection (each aggregate column replaced by its
    argument — the fold's feed) and the Aggregate columns that fold the
    projected values.  An aggregate nested inside a larger expression
    is refused (same restriction as the reference)."""
    if not any(has_aggregate(e) for e, _ in pairs):
        return None
    inner, outer = [], []
    for e, nm in pairs:
        if isinstance(e, AggExpr):
            if e.arg is not None and has_aggregate(e.arg):
                raise QueryError(
                    "aggregate functions can not be nested")
            inner.append((e.arg if e.arg is not None else Literal(1), nm))
            outer.append((AggExpr(e.func, InputProp(nm), e.distinct), nm))
        elif has_aggregate(e):
            raise QueryError(
                "an aggregate function must be the entire YIELD column")
        else:
            inner.append((e, nm))
            outer.append((InputProp(nm), nm))
    return inner, outer


def _plan_aggregate(dep: PlanNode, cols: List[Tuple[Expr, str]],
                    group_keys: Optional[List[Expr]]) -> PlanNode:
    keys = group_keys
    if keys is None:
        keys = [e for e, _ in cols if not has_aggregate(e)]
    return PlanNode("Aggregate", deps=[dep],
                    col_names=[n for _, n in cols],
                    args={"group_keys": keys, "columns": cols})


def _plan_group_by(pctx, s: A.GroupBySentence) -> PlanNode:
    dep = pctx.input_node
    if dep is None:
        raise QueryError("GROUP BY requires piped input")
    cols = [(c.expr, _col_name(c)) for c in s.yield_.columns]
    _check_input_cols(list(s.keys) + [e for e, _ in cols], dep,
                      "GROUP BY")
    return _plan_aggregate(dep, cols, s.keys)


def _check_input_cols(exprs, dep, what: str):
    """Every `$-.name` reference must name a column of the pipe input —
    a typo'd column otherwise sorts/groups on NULL silently (reference
    raises SemanticError at validation)."""
    from ..core.expr import walk as _walk
    cols = set(dep.col_names)
    for e in exprs:
        for x in _walk(e):
            if x.kind == "input_prop" and x.name not in cols:
                raise QueryError(
                    f"`$-.{x.name}' not found in {what} input "
                    f"(columns: {sorted(cols)})")


def _plan_order_by(pctx, s: A.OrderBySentence) -> PlanNode:
    dep = pctx.input_node
    if dep is None:
        raise QueryError("ORDER BY requires piped input")
    _check_input_cols([f.expr for f in s.factors], dep, "ORDER BY")
    return PlanNode("Sort", deps=[dep], col_names=list(dep.col_names),
                    args={"factors": [(f.expr, f.ascending) for f in s.factors]})


def _plan_limit(pctx, s: A.LimitSentence) -> PlanNode:
    dep = pctx.input_node
    if dep is None:
        raise QueryError("LIMIT requires piped input")
    return PlanNode("Limit", deps=[dep], col_names=list(dep.col_names),
                    args={"offset": s.offset, "count": s.count})


def _plan_sample(pctx, s: A.SampleSentence) -> PlanNode:
    dep = pctx.input_node
    if dep is None:
        raise QueryError("SAMPLE requires piped input")
    return PlanNode("Sample", deps=[dep], col_names=list(dep.col_names),
                    args={"count": s.count})


# ---- FETCH / LOOKUP -------------------------------------------------------


def _plan_fetch_vertices(pctx, s: A.FetchVerticesSentence) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    tags = s.tags
    for t in tags:
        try:
            cat.get_tag(space, t)
        except SchemaError as ex:
            raise QueryError(str(ex)) from None
    vids, ref_col = _resolve_from(pctx, s.vids)
    dep = pctx.input_node if ref_col else PlanNode("Start")
    gv = PlanNode("GetVertices", deps=[dep] if dep else [],
                  col_names=["vertices_"],
                  args={"space": space, "tags": tags, "vids": vids,
                        "src_col": ref_col})
    yld = s.yield_
    if yld is None:
        yld = A.YieldClause([A.YieldColumn(VertexExpr("vertex"), "vertices_")])
    # `Person.name` in a FETCH yield is a tag-prop access on the fetched
    # vertex (reference: TagPropertyExpression), not a variable lookup
    tag_names = {t.name for t in cat.tags(space)}

    def _tagprop(x: Expr):
        if (isinstance(x, AttributeExpr) and isinstance(x.obj, LabelExpr)
                and x.obj.name in tag_names):
            return LabelTagProp("vertices_", x.obj.name, x.attr)
        return None

    ycols = [(rewrite(c.expr, _tagprop), _col_name(c)) for c in yld.columns]
    names = [n for _, n in ycols]
    agg_split = _implicit_agg_split(ycols)
    out = PlanNode("Project", deps=[gv], col_names=names,
                   args={"columns": agg_split[0] if agg_split else ycols,
                         "fetch_row": True})
    if agg_split is not None:
        out = _plan_aggregate(out, agg_split[1], None)
    if yld.distinct:
        out = PlanNode("Dedup", deps=[out], col_names=names)
    return out


def _plan_fetch_edges(pctx, s: A.FetchEdgesSentence) -> PlanNode:
    space = pctx.need_space()
    try:
        pctx.catalog.get_edge(space, s.etype)
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    keys = [(_const_eval(k.src), _const_eval(k.dst), k.rank) for k in s.keys]
    ge = PlanNode("GetEdges", deps=[], col_names=["edges_"],
                  args={"space": space, "etype": s.etype, "keys": keys})
    yld = s.yield_
    if yld is None:
        yld = A.YieldClause([A.YieldColumn(EdgeExpr(), "edges_")])
    ycols = [(_rewrite_go_expr(pctx, c.expr, [s.etype]), _col_name(c))
             for c in yld.columns]
    names = [n for _, n in ycols]
    agg_split = _implicit_agg_split(ycols)
    out = PlanNode("Project", deps=[ge], col_names=names,
                   args={"columns": agg_split[0] if agg_split else ycols,
                         "fetch_row": True})
    if agg_split is not None:
        out = _plan_aggregate(out, agg_split[1], None)
    if yld.distinct:
        out = PlanNode("Dedup", deps=[out], col_names=names)
    return out


_REV_OP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


def _lookup_field_cond(c: Expr, schema: str, is_edge: bool):
    """Conjunct of shape <schema>.<field> OP <const> (either order) →
    (field, op, value); else None."""
    if not isinstance(c, Binary) or c.op not in ("==", "<", "<=", ">", ">="):
        return None

    def field_of(x):
        if is_edge and isinstance(x, EdgeProp) and x.edge == schema \
                and not x.name.startswith("_"):
            return x.name
        if not is_edge and isinstance(x, AttributeExpr) \
                and isinstance(x.obj, LabelExpr) and x.obj.name == schema:
            return x.attr
        return None

    for lhs, rhs, op in ((c.lhs, c.rhs, c.op),
                         (c.rhs, c.lhs, _REV_OP.get(c.op, c.op))):
        f = field_of(lhs)
        if f is None:
            continue
        try:
            v = _const_eval(rhs)
        except Exception:  # noqa: BLE001 — non-constant operand
            return None
        from ..core.value import is_null
        if is_null(v) and not isinstance(v, bool):
            return None
        return (f, op, v)
    return None


_GEO_REGION_FNS = ("st_intersects", "st_covers", "st_coveredby")


def _geo_field_of(x: Expr, schema: str, is_edge: bool,
                  alias: Optional[str] = None):
    """<schema>.<field> in LOOKUP spelling, or <alias>.<schema>.<field>
    (LabelTagProp) in MATCH spelling when `alias` is given."""
    if is_edge and isinstance(x, EdgeProp) and x.edge == schema \
            and not x.name.startswith("_"):
        return x.name
    if not is_edge and isinstance(x, AttributeExpr) \
            and isinstance(x.obj, LabelExpr) and x.obj.name == schema:
        return x.attr
    if not is_edge and isinstance(x, LabelTagProp) and x.tag == schema \
            and (alias is None or x.var == alias):
        return x.prop
    return None


def _const_geography(e: Expr):
    """Constant-fold to a Geography (WKT strings coerce); raise else."""
    from ..core.geo import Geography, from_wkt
    v = _const_eval(e)
    if isinstance(v, str):
        v = from_wkt(v)
    if not isinstance(v, Geography):
        raise QueryError("not a geography constant")
    return v


def _lookup_geo_cond(c: Expr, schema: str, is_edge: bool,
                     alias: Optional[str] = None):
    """Conjunct a geo index can serve (reference: the storage geo index's
    predicate→cover extraction [UNVERIFIED — empty mount, SURVEY §0 row
    15]) → (field, covering token ranges); else None.  Shapes:

      ST_Intersects|ST_Covers|ST_CoveredBy(<schema>.<f>, <const geo>)
        (either argument order)
      ST_DWithin(<schema>.<f>, <const geo>, <const meters>)
      ST_Distance(<schema>.<f>, <const geo>) < r  (<=; either side)

    The cover is a bbox superset, so the caller must keep the ORIGINAL
    predicate as a residual filter — the index only prunes."""
    from ..core.geo import covering_ranges

    def dist_parts(fc):
        """st_distance(field, const) in either arg order → (field, geog)."""
        if not (isinstance(fc, FunctionCall) and fc.name == "st_distance"
                and len(fc.args) == 2):
            return None
        for a, b in ((fc.args[0], fc.args[1]), (fc.args[1], fc.args[0])):
            f = _geo_field_of(a, schema, is_edge, alias)
            if f is not None:
                try:
                    return f, _const_geography(b)
                except Exception:  # noqa: BLE001 — non-constant operand
                    return None
        return None

    if isinstance(c, FunctionCall) and c.name in _GEO_REGION_FNS \
            and len(c.args) == 2:
        for a, b in ((c.args[0], c.args[1]), (c.args[1], c.args[0])):
            f = _geo_field_of(a, schema, is_edge, alias)
            if f is not None:
                try:
                    g = _const_geography(b)
                except Exception:  # noqa: BLE001 — non-constant operand
                    return None
                return f, covering_ranges(g)
        return None
    if isinstance(c, FunctionCall) and c.name == "st_dwithin" \
            and len(c.args) == 3:
        m = dist_parts(FunctionCall("st_distance", c.args[:2]))
        if m is None:
            return None
        try:
            r = _const_eval(c.args[2])
        except Exception:  # noqa: BLE001 — non-constant radius
            return None
        if not isinstance(r, (int, float)) or isinstance(r, bool) or r < 0:
            return None
        return m[0], covering_ranges(m[1], pad_m=float(r))
    if isinstance(c, Binary) and c.op in ("<", "<=", ">", ">="):
        # normalize to st_distance(...) <-upper-bound- r
        for lhs, rhs, op in ((c.lhs, c.rhs, c.op),
                             (c.rhs, c.lhs, _REV_OP.get(c.op, c.op))):
            if op not in ("<", "<="):
                continue
            m = dist_parts(lhs)
            if m is None:
                continue
            try:
                r = _const_eval(rhs)
            except Exception:  # noqa: BLE001 — non-constant bound
                return None
            if not isinstance(r, (int, float)) or isinstance(r, bool) \
                    or r < 0:
                return None
            return m[0], covering_ranges(m[1], pad_m=float(r))
    return None


def _geo_index_for(pctx, space: str, schema: str, is_edge: bool,
                   field: str):
    """The geo (cell-token-keyed) index over `schema.field`, if any."""
    from ..graphstore.schema import PropType
    try:
        sv = (pctx.catalog.get_edge(space, schema).latest if is_edge
              else pctx.catalog.get_tag(space, schema).latest)
        p = sv.prop(field)
    except SchemaError:
        return None
    if p is None or p.ptype != PropType.GEOGRAPHY:
        return None
    for d in pctx.catalog.indexes_for(space, schema, is_edge):
        if d.fields == [field]:
            return d
    return None


_TEXT_OPS = ("PREFIX", "WILDCARD", "REGEXP", "FUZZY")


def _lookup_text_cond(c: Expr, schema: str, is_edge: bool):
    """Conjunct of shape PREFIX|WILDCARD|REGEXP|FUZZY(<schema>.<field>,
    <string const>) → (op, field, pattern); else None.  (The reference's
    ES-backed LOOKUP text predicates.)"""
    if not isinstance(c, FunctionCall) or c.name.upper() not in _TEXT_OPS \
            or len(c.args) != 2:
        return None
    a0, a1 = c.args
    field = None
    if is_edge and isinstance(a0, EdgeProp) and a0.edge == schema \
            and not a0.name.startswith("_"):
        field = a0.name
    elif not is_edge and isinstance(a0, AttributeExpr) \
            and isinstance(a0.obj, LabelExpr) and a0.obj.name == schema:
        field = a0.attr
    if field is None:
        return None
    try:
        pat = _const_eval(a1)
    except Exception:  # noqa: BLE001 — non-constant pattern
        return None
    if not isinstance(pat, str):
        return None
    return (c.name.upper(), field, pat)


def score_index_hints(indexes, conds: Dict[str, list]):
    """Shared predicate→IndexColumnHint scoring (reference analog:
    OptimizerUtils; SURVEY §2 rows 15/22).

    conds: {field: [(op, value, conjunct_idx), ...]}.  For each index,
    bind an equality prefix over its fields, then a range on the next
    field; score = (#eq, has_range).  Returns the best
    (score, index_name, eq_values, range_hint, used_conjunct_idxs) —
    used by both the LOOKUP planner and the optimizer's MATCH
    scan→index exploration rule.
    """
    from ..graphstore.index import MAX, MIN, norm
    best = None
    for d in indexes:
        used: set = set()
        eq = []
        for f in d.fields:
            hit = next(((v, i) for (op, v, i) in conds.get(f, [])
                        if op == "=="), None)
            if hit is None:
                break
            eq.append(hit[0])
            used.add(hit[1])
        rng = None
        if len(eq) < len(d.fields):
            nf = d.fields[len(eq)]
            lo, hi, lo_inc, hi_inc = MIN, MAX, True, True
            found = False
            for (op, v, i) in conds.get(nf, []):
                if op in (">", ">="):
                    inc = op == ">="
                    # keep the TIGHTEST lower bound (ties: exclusive wins)
                    if isinstance(lo, type(MIN)) or norm(v) > norm(lo) or \
                            (norm(v) == norm(lo) and not inc):
                        lo, lo_inc = v, inc
                    used.add(i)
                    found = True
                elif op in ("<", "<="):
                    inc = op == "<="
                    if isinstance(hi, type(MAX)) or norm(v) < norm(hi) or \
                            (norm(v) == norm(hi) and not inc):
                        hi, hi_inc = v, inc
                    used.add(i)
                    found = True
            if found:
                rng = (lo, hi, lo_inc, hi_inc)
        score = (len(eq), 1 if rng else 0)
        if best is None or score > best[0]:
            best = (score, d.name, eq, rng, used)
    return best


def _choose_index(pctx, space: str, schema: str, is_edge: bool,
                  filt: Optional[Expr]):
    """Pick the best index + column hints for a LOOKUP predicate.
    Returns (index_name, eq_values, range_hint, residual_filter)."""
    indexes = pctx.catalog.indexes_for(space, schema, is_edge)
    if not indexes:
        kind = "edge" if is_edge else "tag"
        raise QueryError(
            f"no valid index found on {kind} `{schema}' "
            f"(LOOKUP requires one; CREATE {kind.upper()} INDEX first)")
    if filt is None:
        return indexes[0].name, [], None, None
    conjs = split_conjuncts(filt)
    conds: Dict[str, list] = {}
    for i, c in enumerate(conjs):
        m = _lookup_field_cond(c, schema, is_edge)
        if m is not None:
            conds.setdefault(m[0], []).append((m[1], m[2], i))
    _, name, eq, rng, used = score_index_hints(indexes, conds)
    d = next(x for x in indexes if x.name == name)
    lens = list(getattr(d, "field_lens", None) or [])
    if any(lens):
        # string prefix index (name(10)): stored keys are truncated, so
        # probe values truncate the same way, bounds widen to inclusive
        # (a cut bound excludes keys whose full values qualify), and the
        # WHOLE predicate stays as residual — prefix hits over-match
        eq = [v[:lens[i]] if i < len(lens) and lens[i]
              and isinstance(v, str) else v for i, v in enumerate(eq)]
        if rng is not None:
            lo, hi, lo_inc, hi_inc = rng
            nf = len(eq)
            ln = lens[nf] if nf < len(lens) else 0
            if ln:
                # an exclusive lo of length >= ln collides with keys
                # truncated TO lo (value "alexander" > lo "alex" stores
                # key "alex") — widen to inclusive; hi only over-matches
                # when actually cut
                if isinstance(lo, str) and len(lo) >= ln:
                    lo, lo_inc = lo[:ln], True
                if isinstance(hi, str) and len(hi) > ln:
                    hi, hi_inc = hi[:ln], True
            rng = (lo, hi, lo_inc, hi_inc)
        return name, eq, rng, filt
    residual = join_conjuncts(
        [c for i, c in enumerate(conjs) if i not in used])
    return name, eq, rng, residual


def _plan_lookup(pctx, s: A.LookupSentence) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    is_edge = False
    try:
        cat.get_tag(space, s.schema_name)
    except SchemaError:
        try:
            cat.get_edge(space, s.schema_name)
            is_edge = True
        except SchemaError:
            raise QueryError(
                f"`{s.schema_name}' is neither tag nor edge in `{space}'") from None
    filt = None
    if s.where is not None:
        aliases = {s.schema_name: s.schema_name}
        filt = _rewrite_match_expr(s.where.filter, aliases)
        filt = _rewrite_go_expr(pctx, filt, [s.schema_name]) if is_edge else filt
    # text-search predicate → fulltext scan (reference: ES-backed LOOKUP)
    text = ft_pick = first_unindexed = None
    if filt is not None:
        conjs = split_conjuncts(filt)
        ft_descs = pctx.catalog.fulltext_indexes_for(
            space, s.schema_name, is_edge)
        for i, c in enumerate(conjs):
            m = _lookup_text_cond(c, s.schema_name, is_edge)
            if m is None:
                continue
            op, field, pat = m
            if op == "REGEXP":
                # validate const patterns at plan time so scan-planned
                # and residual placements fail identically
                import re as _re
                try:
                    _re.compile(pat)
                except _re.error as ex:
                    raise QueryError(
                        f"bad REGEXP pattern {pat!r}: {ex}") from None
            d = next((d for d in ft_descs if d.fields[0] == field), None)
            if d is None:
                # another conjunct may still be indexed; the host text
                # evaluators cover this one as a residual
                if first_unindexed is None:
                    first_unindexed = (op, field)
                continue
            if text is None:
                text = m
                ft_pick = d
                residual_t = join_conjuncts(
                    [x for j, x in enumerate(conjs) if j != i])
        if text is None and first_unindexed is not None:
            op, field = first_unindexed
            raise QueryError(
                f"no fulltext index on `{s.schema_name}.{field}' "
                f"({op} requires one; CREATE FULLTEXT INDEX first)")
    geo = None
    if filt is not None and text is None:
        # ST_ predicate over a cell-token geo index: scan the covering
        # ranges, keep the WHOLE predicate as residual (cover ⊇ region).
        # An equality/range binding on a B-tree index beats the bbox
        # cover (code-review: the geo branch must not preempt a more
        # selective probe), so the geo path only runs when the generic
        # hint extraction binds nothing.
        generic_binds = False
        try:
            _nm, eq_h, rng_h, _res = _choose_index(
                pctx, space, s.schema_name, is_edge, filt)
            generic_binds = bool(eq_h) or rng_h is not None
        except QueryError:
            pass                      # no B-tree index at all
        if not generic_binds:
            for c in split_conjuncts(filt):
                m = _lookup_geo_cond(c, s.schema_name, is_edge)
                if m is not None:
                    d = _geo_index_for(pctx, space, s.schema_name,
                                       is_edge, m[0])
                    if d is not None:
                        geo = (d.name, m[1])
                        break
    if text is not None:
        op, field, pat = text
        scan = PlanNode("FulltextIndexScan", deps=[],
                        col_names=["_matched"],
                        args={"space": space, "schema": s.schema_name,
                              "is_edge": is_edge, "filter": residual_t,
                              "index": ft_pick.name, "op": op,
                              "pattern": pat})
    elif geo is not None:
        scan = PlanNode("IndexScan", deps=[],
                        col_names=["_matched"],
                        args={"space": space, "schema": s.schema_name,
                              "is_edge": is_edge, "filter": filt,
                              "index": geo[0], "geo_ranges": geo[1]})
    else:
        index_name, eq, rng, residual = _choose_index(
            pctx, space, s.schema_name, is_edge, filt)
        scan = PlanNode("IndexScan", deps=[],
                        col_names=["_matched"],
                        args={"space": space, "schema": s.schema_name,
                              "is_edge": is_edge, "filter": residual,
                              "index": index_name, "eq": eq, "range": rng})
    yld = s.yield_
    if yld is None:
        default = (FunctionCall("id", [VertexExpr("vertex")]) if not is_edge
                   else EdgeExpr())
        yld = A.YieldClause([A.YieldColumn(default, "_matched")])
    ycols = []
    for c in yld.columns:
        e = _rewrite_match_expr(c.expr, {s.schema_name: s.schema_name})
        if is_edge:
            e = _rewrite_go_expr(pctx, e, [s.schema_name])
        ycols.append((e, _col_name(c)))
    names = [n for _, n in ycols]
    agg_split = _implicit_agg_split(ycols)
    out = PlanNode("Project", deps=[scan], col_names=names,
                   args={"columns": agg_split[0] if agg_split else ycols,
                         "lookup_row": True,
                         "schema": s.schema_name, "is_edge": is_edge})
    if agg_split is not None:
        out = _plan_aggregate(out, agg_split[1], None)
    if yld.distinct:
        out = PlanNode("Dedup", deps=[out], col_names=names)
    return out


# ---- MATCH ----------------------------------------------------------------


def _plan_match(pctx, s: A.MatchSentence) -> PlanNode:
    # a pure UNWIND/WITH/RETURN pipeline touches no graph data — like
    # YIELD, it must work before any USE (openCypher expression-only
    # queries); the first MATCH clause still demands a space
    if any(isinstance(c, A.MatchClauseAst) for c in s.clauses):
        pctx.need_space()
    current: Optional[PlanNode] = pctx.input_node
    aliases: Dict[str, str] = {}
    if current is not None:
        for c in current.col_names:
            aliases[c] = "input"

    for clause in s.clauses:
        if isinstance(clause, A.MatchClauseAst):
            if clause.optional and current is None:
                # leading OPTIONAL MATCH: one implicit input row, so a
                # miss null-extends to a single all-NULL row instead of
                # an empty result (openCypher).  A zero-column one-row
                # Project is the unit for the empty-key left join.
                current = PlanNode("Project", deps=[PlanNode("Start")],
                                  col_names=[],
                                  args={"columns": [],
                                        "match_row": True})
            current = _plan_match_clause(pctx, clause, current, aliases)
        elif isinstance(clause, A.UnwindClauseAst):
            e = _rewrite_match_expr(clause.expr, aliases)
            cols = (list(current.col_names) if current else []) + [clause.alias]
            current = PlanNode("Unwind", deps=[current] if current else [],
                               col_names=cols,
                               args={"expr": e, "alias": clause.alias})
            aliases[clause.alias] = "value"
        elif isinstance(clause, A.WithClauseAst):
            wcols = clause.columns
            if wcols is None:      # WITH *: carry every visible alias
                wcols = [A.YieldColumn(LabelExpr(a), a) for a in aliases
                         if not a.startswith("_")]
                if not wcols:
                    raise QueryError("WITH * with nothing in scope")
            current = _plan_projection(pctx, current, wcols,
                                       clause.distinct, clause.where,
                                       clause.order_by, clause.skip,
                                       clause.limit, aliases)
            # a bare alias carried through WITH keeps its kind: a later
            # clause can then Argument-seed a pattern from a projected
            # vertex instead of scanning every vertex and joining
            # (IC5-shaped multi-clause MATCH was scan-bound without this)
            carried = {}
            for c in wcols:
                if isinstance(c.expr, LabelExpr):
                    k = aliases.get(c.expr.name)
                    if k is not None:
                        carried[_col_name(c)] = k
            aliases = {c: carried.get(c, "value")
                       for c in current.col_names}
        else:
            raise QueryError(f"unsupported MATCH clause {type(clause).__name__}")

    ret = s.return_
    cols = ret.columns
    if cols is None:
        cols = [A.YieldColumn(LabelExpr(a), a) for a in aliases
                if not a.startswith("_")]
        if not cols:
            raise QueryError("RETURN * with nothing to return")
    return _plan_projection(pctx, current, cols, ret.distinct, None,
                            ret.order_by, ret.skip, ret.limit, aliases)


def _plan_projection(pctx, dep: Optional[PlanNode], cols: List[A.YieldColumn],
                     distinct: bool, where: Optional[Expr],
                     order_by, skip: int, limit: int,
                     aliases: Dict[str, str]) -> PlanNode:
    if dep is None:
        dep = PlanNode("Start")
    out = dep
    ycols = [(_rewrite_match_expr(c.expr, aliases), _col_name(c)) for c in cols]
    names = [n for _, n in ycols]
    if any(has_aggregate(e) for e, _ in ycols):
        out = _plan_aggregate(out, ycols, None)
        out.args["match_row"] = True
    else:
        out = PlanNode("Project", deps=[out], col_names=names,
                       args={"columns": ycols, "match_row": True})
    if where is not None:
        # WITH ... WHERE filters the PROJECTED columns (openCypher)
        w = _rewrite_match_expr(where, {n: "value" for n in names})
        out = PlanNode("Filter", deps=[out], col_names=names,
                       args={"condition": w, "match_row": True})
    if distinct:
        out = PlanNode("Dedup", deps=[out], col_names=names)
    if order_by:
        # ORDER BY items resolve against the PROJECTED columns
        # (openCypher scope after RETURN/WITH): a bare alias stays a
        # column lookup, an expression that textually matches an output
        # column (e.g. `ORDER BY id(a)` after `RETURN id(a), k`) is
        # re-homed to that column, and anything else is an error —
        # evaluating it against projected rows would silently sort on
        # NULL (the pattern variables are out of scope here).
        src_text = {to_text(e): nm for e, nm in ycols}
        factors = []
        for f in order_by:
            e2 = f.expr
            txt = to_text(e2)
            txt_m = to_text(_rewrite_match_expr(
                e2, aliases)) if aliases else txt
            if isinstance(e2, LabelExpr) and e2.name in names:
                pass                       # alias lookup — resolves as-is
            elif txt in names:
                e2 = LabelExpr(txt)        # same column, spelled as expr
            elif txt_m in src_text:
                # ORDER BY repeats a projected column's SOURCE expr
                # (`RETURN a.p.x AS x ORDER BY a.p.x`) — same column
                e2 = LabelExpr(src_text[txt_m])
            else:
                e2 = _rewrite_match_expr(
                    e2, {n: "value" for n in names})
                refs = {x.name for x in walk(e2)
                        if x.kind in ("label", "input_prop")}
                if refs and not refs <= set(names):
                    raise QueryError(
                        f"ORDER BY item `{txt}' must be a column of "
                        f"the RETURN/WITH list (have {names})")
            factors.append((e2, f.ascending))
        out = PlanNode("Sort", deps=[out], col_names=names,
                       args={"factors": factors, "match_row": True})
    if skip or (limit is not None and limit >= 0):
        out = PlanNode("Limit", deps=[out], col_names=names,
                       args={"offset": skip, "count": limit if limit >= 0 else -1})
    return out


def _plan_match_clause(pctx, mc: A.MatchClauseAst, current: Optional[PlanNode],
                       aliases: Dict[str, str]) -> PlanNode:
    pat_nodes = []
    for pat in mc.patterns:
        pat_nodes.append(_plan_pattern(pctx, pat, mc.where, aliases, current))
    node = pat_nodes[0]
    for other in pat_nodes[1:]:
        shared = [c for c in node.col_names if c in other.col_names]
        if shared:
            node = PlanNode("HashInnerJoin", deps=[node, other],
                            col_names=node.col_names + [c for c in other.col_names
                                                        if c not in node.col_names],
                            args={"keys": shared})
        else:
            node = PlanNode("CrossJoin", deps=[node, other],
                            col_names=node.col_names + other.col_names)
    clause_edges = [ep.alias for pat in mc.patterns for ep in pat.edges]
    if len(clause_edges) >= 2:
        # Cypher relationship isomorphism scopes to the WHOLE MATCH
        # clause: no edge binds two relationship variables across any of
        # its comma patterns — including cycles through the dup-alias
        # branch in _plan_pattern ((a)-[e1]-(b)-[e2]-(a) walking one
        # edge out and back).
        cond = FunctionCall("_edges_distinct",
                            [LabelExpr(al) for al in clause_edges])
        node = PlanNode("Filter", deps=[node],
                        col_names=list(node.col_names),
                        args={"condition": cond, "match_row": True})
    where = mc.where
    if where is not None and mc.optional and current is not None:
        # OPTIONAL MATCH ... WHERE filters DURING matching (openCypher):
        # a row failing the predicate is a non-match that null-extends,
        # not a dropped output row — so conjuncts whose references
        # (including any pattern predicate's node aliases) live entirely
        # in the pattern branch filter it BEFORE the left join.
        # Conjuncts reaching outer aliases fall through to the normal
        # above-join path, where their pattern predicates resolve
        # against the JOINED columns (legacy drop placement — they
        # cannot be evaluated inside the branch).
        w = _rewrite_match_expr(where, aliases)
        right_cols = set(node.col_names)
        pre, post = [], []
        for c in split_conjuncts(w):
            refs = {x.name for x in walk(c) if isinstance(x, LabelExpr)} \
                | {x.var for x in walk(c) if isinstance(x, LabelTagProp)}
            for x in walk(c):
                if x.kind == "pattern_pred":
                    refs |= {np_.alias for np_ in x.pattern.nodes
                             if np_.alias is not None}
            (pre if refs <= right_cols else post).append(c)
        if pre:
            wpre = join_conjuncts(pre)
            node, wpre, hidden_o = _apply_pattern_preds(pctx, node, wpre)
            node = PlanNode("Filter", deps=[node],
                            col_names=list(node.col_names),
                            args={"condition": wpre, "match_row": True})
            if hidden_o:
                keep = [c for c in node.col_names if c not in hidden_o]
                node = PlanNode("Project", deps=[node], col_names=keep,
                                args={"columns": [(LabelExpr(c), c)
                                                  for c in keep],
                                      "match_row": True})
        where = join_conjuncts(post) if post else None
    if current is not None:
        shared = [c for c in current.col_names if c in node.col_names]
        join_kind = "HashLeftJoin" if mc.optional else "HashInnerJoin"
        if shared:
            node = PlanNode(join_kind, deps=[current, node],
                            col_names=current.col_names
                            + [c for c in node.col_names if c not in current.col_names],
                            args={"keys": shared})
        elif mc.optional:
            # no shared aliases: openCypher semantics are a cartesian
            # product, degrading to one all-NULL row for the pattern's
            # columns when it matched nothing — exactly a hash left
            # join on the EMPTY key (every row shares the () key)
            node = PlanNode("HashLeftJoin", deps=[current, node],
                            col_names=current.col_names
                            + [c for c in node.col_names
                               if c not in current.col_names],
                            args={"keys": []})
        else:
            node = PlanNode("CrossJoin", deps=[current, node],
                            col_names=current.col_names + node.col_names)
    if where is not None:
        w = _rewrite_match_expr(where, aliases)
        node, w, hidden = _apply_pattern_preds(pctx, node, w)
        node = PlanNode("Filter", deps=[node], col_names=list(node.col_names),
                        args={"condition": w, "match_row": True})
        if hidden:
            keep = [c for c in node.col_names if c not in hidden]
            node = PlanNode("Project", deps=[node], col_names=keep,
                            args={"columns": [(LabelExpr(c), c)
                                              for c in keep],
                                  "match_row": True})
    return node


def _apply_pattern_preds(pctx, node: PlanNode, w: Expr):
    """WHERE (a)-[:e]->() — exists-semantics pattern predicates
    (reference: MatchValidator's PatternExpression planned as a
    RollUpApply semi-join [UNVERIFIED — empty mount, SURVEY §0]).

    Each distinct pattern becomes a deduplicated semi-join branch: plan
    the pattern seeded from a bound alias (Argument over the current
    rows), project the bound alias columns plus a TRUE marker, left-join
    back on the bound aliases, and rewrite the predicate occurrence into
    `CASE WHEN <any bound alias IS NULL> THEN NULL ELSE marker IS NOT
    NULL END` — NULL bound variables (OPTIONAL MATCH misses) make the
    predicate NULL per openCypher 3VL; otherwise it is a two-valued
    boolean so NOT/AND/OR compose correctly.
    Returns (node, rewritten_where, hidden_cols)."""
    import copy

    markers: Dict[str, str] = {}
    pats = []
    for x in walk(w):
        if x.kind == "pattern_pred" and x.text not in markers:
            markers[x.text] = ""
            pats.append(x)
    if not pats:
        return node, w, []
    hidden: List[str] = []
    for pe in pats:
        n = getattr(pctx, "_pe_counter", 0)
        pctx._pe_counter = n + 1
        marker = f"__pe_{n}"
        pat = copy.deepcopy(pe.pattern)
        named = [np.alias for np in pat.nodes if np.alias is not None]
        # bound = present in the incoming rows, whatever the alias kind:
        # a vertex carried through WITH/UNWIND is typed "value" but its
        # runtime column holds the vertex, which is all the semi-join
        # seed needs (code-review: rejecting those as "new variables"
        # refused valid openCypher)
        bound = [a for a in dict.fromkeys(named) if a in node.col_names]
        fresh = sorted(set(named) - set(bound))
        if fresh:
            raise QueryError(
                "pattern predicate must not introduce new variables: "
                + ", ".join(fresh))
        if not bound:
            raise QueryError(
                "pattern predicate must use at least one bound variable")
        for ep in pat.edges:
            if ep.alias is not None:
                raise QueryError(
                    f"pattern predicate must not name its edges "
                    f"(`{ep.alias}')")
        scratch = {a: "vertex" for a in bound}
        sub = _plan_pattern(pctx, pat, None, scratch, node)
        cols = [(LabelExpr(a), a) for a in bound] + [(Literal(True), marker)]
        sub = PlanNode("Project", deps=[sub], col_names=bound + [marker],
                       args={"columns": cols, "match_row": True})
        sub = PlanNode("Dedup", deps=[sub], col_names=bound + [marker])
        node = PlanNode("HashLeftJoin", deps=[node, sub],
                        col_names=list(node.col_names) + [marker],
                        args={"keys": bound})
        markers[pe.text] = (marker, bound)
        hidden.append(marker)

    def fn(x: Expr):
        if x.kind == "pattern_pred":
            marker, bound = markers[x.text]
            found = Unary("IS_NOT_NULL", LabelExpr(marker))
            null_check = None
            for a in bound:
                c = Unary("IS_NULL", LabelExpr(a))
                null_check = c if null_check is None \
                    else Binary("OR", null_check, c)
            return Case([(null_check, Literal(NULL))], found, None)
        return None
    return node, rewrite(w, fn), hidden


def _anon_names(pctx):
    """Anonymous aliases must be unique across the WHOLE query: two
    patterns in one MATCH each having an anonymous edge must not share
    a column name, or the join between them keys on unrelated edges
    (anonymous elements are never join keys in Cypher)."""
    while True:
        n = getattr(pctx, "_anon_counter", 0)
        pctx._anon_counter = n + 1
        yield f"__anon_{n}"


def _plan_pattern(pctx, pat: A.PathPattern, where: Optional[Expr],
                  aliases: Dict[str, str], current: Optional[PlanNode]) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    anon = _anon_names(pctx)
    for np in pat.nodes:
        if np.alias is None:
            np.alias = next(anon)
    for ep in pat.edges:
        if ep.alias is None:
            ep.alias = next(anon)
        for t in ep.types:
            try:
                cat.get_edge(space, t)
            except SchemaError as ex:
                raise QueryError(str(ex)) from None
    for np in pat.nodes:
        for lbl, _ in np.labels:
            try:
                cat.get_tag(space, lbl)
            except SchemaError as ex:
                raise QueryError(str(ex)) from None

    # ---- choose seed node: id(x)==lit / id(x) IN [...] in WHERE, bound
    # alias from a previous clause, else labeled node, else first node.
    seed_idx, seed_vids = _choose_seed(pat, where, aliases, current)

    if seed_idx == len(pat.nodes) - 1 and len(pat.nodes) > 1:
        _reverse_pattern(pat)
        seed_idx = 0
    elif seed_idx != 0 and seed_idx != len(pat.nodes) - 1:
        seed_idx = 0
        seed_vids = None

    seed = pat.nodes[seed_idx]
    bound = seed.alias in aliases and aliases[seed.alias] == "vertex"
    if bound and current is not None:
        dep = PlanNode("Argument", deps=[], col_names=[seed.alias],
                       args={"from_var": current.output_var, "col": seed.alias})
    elif seed_vids is not None:
        dep = PlanNode("GetVertices", deps=[], col_names=[seed.alias],
                       args={"space": space, "tags": [], "vids": seed_vids,
                             "src_col": None, "as_col": seed.alias})
    else:
        tag = seed.labels[0][0] if seed.labels else None
        dep = PlanNode("ScanVertices", deps=[], col_names=[seed.alias],
                       args={"space": space, "tag": tag, "as_col": seed.alias})
    node_filter = _node_pred(seed)
    if node_filter is not None:
        dep = PlanNode("Filter", deps=[dep], col_names=list(dep.col_names),
                       args={"condition": node_filter, "match_row": True})
    aliases[seed.alias] = "vertex"

    cur = dep
    for i, ep in enumerate(pat.edges):
        dst = pat.nodes[i + 1]
        etypes = ep.types or sorted(e.name for e in cat.edges(space))
        edge_filter = _edge_pred(ep)
        # A repeated node alias within the pattern — (a)-[e]->(a), cycles
        # like (a)-->(b)-->(a) — is an EQUALITY constraint, not a second
        # column: traverse into a fresh alias, filter id(fresh)==id(orig),
        # then drop the fresh column.
        dup = dst.alias in cur.col_names
        use_alias = (next(anon) + "d") if dup else dst.alias
        cols = list(cur.col_names) + [ep.alias, use_alias]
        cur = PlanNode("Traverse", deps=[cur], col_names=cols, args={
            "space": space, "src_col": pat.nodes[i].alias,
            "edge_alias": ep.alias, "dst_alias": use_alias,
            "edge_types": etypes, "direction": ep.direction,
            "min_hop": ep.min_hop, "max_hop": ep.max_hop,
            "edge_filter": edge_filter,
        })
        aliases[ep.alias] = "edge_list" if ep.max_hop != 1 or ep.min_hop != 1 else "edge"
        aliases[dst.alias] = "vertex"
        dst_filter = _node_pred(dst)
        av_labels = [l for l, _ in dst.labels]
        cur = PlanNode("AppendVertices", deps=[cur], col_names=list(cur.col_names),
                       args={"space": space, "col": use_alias,
                             "labels": av_labels, "filter": dst_filter})
        if dup:
            eq = Binary("==", FunctionCall("id", [LabelExpr(use_alias)]),
                        FunctionCall("id", [LabelExpr(dst.alias)]))
            cur = PlanNode("Filter", deps=[cur],
                           col_names=list(cur.col_names),
                           args={"condition": eq, "match_row": True})
            keep = [c for c in cur.col_names if c != use_alias]
            cur = PlanNode("Project", deps=[cur], col_names=keep,
                           args={"columns": [(LabelExpr(c), c)
                                             for c in keep],
                                 "match_row": True})
    if not pat.edges:
        # single-node pattern: ensure label presence already filtered
        if seed.labels and seed_vids is not None:
            lbl_conds = [FunctionCall("_hastag",
                                      [LabelExpr(seed.alias), Literal(l)])
                         for l, _ in seed.labels]
            cond = lbl_conds[0]
            for c in lbl_conds[1:]:
                cond = Binary("AND", cond, c)
            cur = PlanNode("Filter", deps=[cur], col_names=list(cur.col_names),
                           args={"condition": cond, "match_row": True})
    if pat.alias is not None:
        # named path column
        cols = list(cur.col_names) + [pat.alias]
        cur = PlanNode("BuildPath", deps=[cur], col_names=cols, args={
            "alias": pat.alias,
            "nodes": [n.alias for n in pat.nodes],
            "edges": [e.alias for e in pat.edges],
        })
        aliases[pat.alias] = "path"
    return cur


def _node_pred(np: A.NodePattern) -> Optional[Expr]:
    conds: List[Expr] = []
    for lbl, lprops in np.labels:
        conds.append(FunctionCall("_hastag", [LabelExpr(np.alias), Literal(lbl)]))
        if lprops:
            for k, v in lprops.items():
                conds.append(Binary("==", LabelTagProp(np.alias, lbl, k), v))
    if np.props:
        for k, v in np.props.items():
            conds.append(Binary("==",
                                AttributeExpr(LabelExpr(np.alias), k), v))
    if not conds:
        return None
    out = conds[0]
    for c in conds[1:]:
        out = Binary("AND", out, c)
    return out


def _edge_pred(ep: A.EdgePattern) -> Optional[Expr]:
    if not ep.props:
        return None
    conds = [Binary("==", AttributeExpr(LabelExpr("__edge__"), k), v)
             for k, v in ep.props.items()]
    out = conds[0]
    for c in conds[1:]:
        out = Binary("AND", out, c)
    return out


def _choose_seed(pat, where, aliases, current):
    """Find id(x)==lit / id(x) IN [..] for a pattern node, or a bound alias."""
    node_aliases = [n.alias for n in pat.nodes]
    if current is not None:
        for i, a in enumerate(node_aliases):
            if a in aliases and aliases[a] == "vertex":
                return i, None
    if where is not None:
        for conj in split_conjuncts(where):
            if isinstance(conj, Binary) and conj.op in ("==", "IN"):
                for lhs, rhs in ((conj.lhs, conj.rhs), (conj.rhs, conj.lhs)):
                    if (isinstance(lhs, FunctionCall) and lhs.name == "id"
                            and len(lhs.args) == 1
                            and isinstance(lhs.args[0], LabelExpr)
                            and lhs.args[0].name in node_aliases
                            and _is_const(rhs)):
                        idx = node_aliases.index(lhs.args[0].name)
                        v = rhs.eval(DictContext())
                        vids = v if isinstance(v, list) else [v]
                        return idx, [Literal(x) for x in vids]
    for i, n in enumerate(pat.nodes):
        if n.labels or n.props:
            return i, None
    return 0, None


def _is_const(e: Expr) -> bool:
    return all(x.kind in ("literal", "list", "map", "set") for x in walk(e))


def _reverse_pattern(pat: A.PathPattern):
    pat.nodes.reverse()
    pat.edges.reverse()
    for ep in pat.edges:
        if ep.direction == "out":
            ep.direction = "in"
        elif ep.direction == "in":
            ep.direction = "out"


# ---- FIND PATH / SUBGRAPH -------------------------------------------------


def _plan_find_path(pctx, s: A.FindPathSentence) -> PlanNode:
    space = pctx.need_space()
    edges = s.over.edges
    if s.over.is_all:
        edges = sorted(e.name for e in pctx.catalog.edges(space))
    src_vids, src_ref = _resolve_from(pctx, s.from_)
    dst_vids, dst_ref = _resolve_from(pctx, s.to)
    deps = [pctx.input_node] if (src_ref or dst_ref) and pctx.input_node else []
    where_expr = None
    if s.where is not None:
        where_expr = _rewrite_go_expr(pctx, s.where.filter, edges)
    col = "path"
    if s.yield_ is not None and s.yield_.columns:
        col = _col_name(s.yield_.columns[0])
    return PlanNode("FindPath", deps=deps, col_names=[col], args={
        "space": space, "kind": s.kind, "edge_types": edges,
        "direction": s.over.direction,
        "src_vids": src_vids, "src_ref": src_ref,
        "dst_vids": dst_vids, "dst_ref": dst_ref,
        "upto": s.upto, "with_prop": s.with_prop, "filter": where_expr,
    })


def _plan_call_algo(pctx, s: A.CallAlgoSentence) -> PlanNode:
    """CALL algo.<func>(...) → one CallAlgo node (ISSUE 13).  The
    validator vetted names/required/yields; parameter values are
    constant expressions evaluated HERE so the executor sees plain
    python values (the plan is the wire/cache form)."""
    from ..algo import ALGORITHMS
    space = pctx.need_space()
    params = {k: _const_eval(v) for k, v in s.params.items()}
    spec = ALGORITHMS[s.func]
    if s.yield_ is not None and s.yield_.columns:
        ycols = [(c.expr.name, _col_name(c)) for c in s.yield_.columns]
    else:
        ycols = [(c, c) for c in spec.yield_cols]
    return PlanNode("CallAlgo", col_names=[al for _, al in ycols],
                    args={"space": space, "algo": s.func,
                          "params": params, "yield": ycols})


def _plan_subgraph(pctx, s: A.SubgraphSentence) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    all_names = sorted(e.name for e in cat.edges(space))
    in_e, out_e, both_e = s.in_edges, s.out_edges, s.both_edges
    if s.all_edges or not (in_e or out_e or both_e):
        both_e = all_names
    vids, ref = _resolve_from(pctx, s.from_)
    names = ["_vertices", "_edges"]
    if s.yield_ is not None:
        names = [_col_name(c) for c in s.yield_.columns]
    where_expr = None
    if s.where is not None:
        where_expr = _rewrite_go_expr(pctx, s.where.filter, all_names)
    deps = [pctx.input_node] if ref and pctx.input_node else []
    yield_spec = []
    if s.yield_ is not None:
        for c in s.yield_.columns:
            t = to_text(c.expr).lower()
            yield_spec.append("vertices" if "vertices" in t else "edges")
    else:
        yield_spec = ["vertices", "edges"]
    return PlanNode("Subgraph", deps=deps, col_names=names, args={
        "space": space, "steps": s.steps, "vids": vids, "src_ref": ref,
        "in_edges": in_e, "out_edges": out_e, "both_edges": both_e,
        "with_prop": s.with_prop, "filter": where_expr, "yield": yield_spec,
    })


# ---- DML ------------------------------------------------------------------


def _const_eval(e: Expr) -> Any:
    return e.eval(DictContext())


def _plan_insert_vertices(pctx, s: A.InsertVerticesSentence) -> PlanNode:
    space = pctx.need_space()
    for tag, names in s.tags:
        try:
            ts = pctx.catalog.get_tag(space, tag)
        except SchemaError as ex:
            raise QueryError(str(ex)) from None
        for n in names:
            if ts.latest.prop(n) is None:
                raise QueryError(f"tag `{tag}' has no property `{n}'")
    total = len(s.prop_names)
    rows = []
    for r in s.rows:
        if len(r.values) != total:
            raise QueryError("value count does not match prop count")
        vals = [_const_eval(v) for v in r.values]
        per_tag, off = [], 0
        for _tag, names in s.tags:
            per_tag.append(dict(zip(names, vals[off:off + len(names)])))
            off += len(names)
        rows.append((_const_eval(r.vid), per_tag))
    return PlanNode("InsertVertices", col_names=[], args={
        "space": space, "tags": list(s.tags), "rows": rows,
        "if_not_exists": s.if_not_exists})


def _plan_insert_edges(pctx, s: A.InsertEdgesSentence) -> PlanNode:
    space = pctx.need_space()
    try:
        es = pctx.catalog.get_edge(space, s.etype)
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    for n in s.prop_names:
        if es.latest.prop(n) is None:
            raise QueryError(f"edge `{s.etype}' has no property `{n}'")
    rows = []
    for r in s.rows:
        if len(r.values) != len(s.prop_names):
            raise QueryError("value count does not match prop count")
        rows.append((_const_eval(r.src), _const_eval(r.dst), r.rank,
                     {n: _const_eval(v) for n, v in zip(s.prop_names, r.values)}))
    return PlanNode("InsertEdges", col_names=[], args={
        "space": space, "etype": s.etype, "rows": rows,
        "prop_names": s.prop_names, "if_not_exists": s.if_not_exists})


def _plan_delete_vertices(pctx, s: A.DeleteVerticesSentence) -> PlanNode:
    space = pctx.need_space()
    vids, ref = _resolve_from(pctx, s.vids)
    deps = [pctx.input_node] if ref and pctx.input_node else []
    return PlanNode("DeleteVertices", deps=deps, col_names=[], args={
        "space": space, "vids": vids, "src_ref": ref, "with_edge": s.with_edge})


def _plan_delete_edges(pctx, s: A.DeleteEdgesSentence) -> PlanNode:
    space = pctx.need_space()
    keys = [(_const_eval(k.src), _const_eval(k.dst), k.rank) for k in s.keys]
    deps = []
    ref = None
    if s.ref is not None:
        deps = [pctx.input_node] if pctx.input_node else []
        ref = tuple(s.ref)
    return PlanNode("DeleteEdges", deps=deps, col_names=[], args={
        "space": space, "etype": s.etype, "keys": keys, "ref": ref})


def _plan_delete_tags(pctx, s: A.DeleteTagsSentence) -> PlanNode:
    space = pctx.need_space()
    vids, ref = _resolve_from(pctx, s.vids)
    return PlanNode("DeleteTags", col_names=[], args={
        "space": space, "tags": s.tags, "vids": vids, "src_ref": ref})


def _plan_update(pctx, s: A.UpdateSentence) -> PlanNode:
    space = pctx.need_space()
    cat = pctx.catalog
    try:
        schema = (cat.get_edge(space, s.schema_name) if s.is_edge
                  else cat.get_tag(space, s.schema_name))
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    for name, _ in s.sets:
        if schema.latest.prop(name) is None:
            raise QueryError(f"no property `{name}' on `{s.schema_name}'")
    args: Dict[str, Any] = {
        "space": space, "is_edge": s.is_edge, "schema": s.schema_name,
        "sets": s.sets, "when": s.when, "insertable": s.insertable,
        "yield": [(c.expr, _col_name(c)) for c in (s.yield_.columns if s.yield_ else [])],
    }
    if s.is_edge:
        k = s.edge_key
        args["edge_key"] = (_const_eval(k.src), _const_eval(k.dst), k.rank)
    else:
        args["vid"] = _const_eval(s.vid)
    cols = [n for _, n in args["yield"]]
    return PlanNode("Update", col_names=cols, args=args)


# ---- DDL / admin ----------------------------------------------------------


def _admin(node_kind: str, cols: List[str] = None, **args) -> PlanNode:
    return PlanNode(node_kind, col_names=cols or [], args=args)


def _plan_use(pctx, s: A.UseSentence) -> PlanNode:
    try:
        pctx.catalog.get_space(s.space)
    except SchemaError as ex:
        raise QueryError(str(ex)) from None
    pctx.space = s.space
    return _admin("SwitchSpace", space=s.space)


_DISPATCH = {}


def _register_dispatch():
    _DISPATCH.update({
        A.SeqSentence: _plan_seq,
        A.PipedSentence: _plan_pipe,
        A.AssignSentence: _plan_assign,
        A.SetOpSentence: _plan_setop,
        A.ExplainSentence: _plan_explain,
        A.GoSentence: _plan_go,
        A.YieldSentence: _plan_yield,
        A.GroupBySentence: _plan_group_by,
        A.OrderBySentence: _plan_order_by,
        A.LimitSentence: _plan_limit,
        A.SampleSentence: _plan_sample,
        A.FetchVerticesSentence: _plan_fetch_vertices,
        A.FetchEdgesSentence: _plan_fetch_edges,
        A.LookupSentence: _plan_lookup,
        A.MatchSentence: _plan_match,
        A.FindPathSentence: _plan_find_path,
        A.SubgraphSentence: _plan_subgraph,
        A.CallAlgoSentence: _plan_call_algo,
        A.InsertVerticesSentence: _plan_insert_vertices,
        A.InsertEdgesSentence: _plan_insert_edges,
        A.DeleteVerticesSentence: _plan_delete_vertices,
        A.DeleteEdgesSentence: _plan_delete_edges,
        A.DeleteTagsSentence: _plan_delete_tags,
        A.UpdateSentence: _plan_update,
        A.UseSentence: _plan_use,
        A.CreateSpaceSentence: lambda p, s: _admin(
            "CreateSpace", name=s.name, if_not_exists=s.if_not_exists,
            partition_num=s.partition_num, replica_factor=s.replica_factor,
            vid_type=s.vid_type),
        A.DropSpaceSentence: lambda p, s: _admin(
            "DropSpace", name=s.name, if_exists=s.if_exists),
        A.CreateSpaceAsSentence: lambda p, s: _admin(
            "CreateSpaceAs", name=s.name, source=s.source,
            if_not_exists=s.if_not_exists),
        A.CreateSchemaSentence: lambda p, s: _admin(
            "CreateSchema", is_edge=s.is_edge, name=s.name,
            props=s.props, if_not_exists=s.if_not_exists,
            ttl_duration=s.ttl_duration, ttl_col=s.ttl_col,
            space=p.need_space()),
        A.AlterSchemaSentence: lambda p, s: _admin(
            "AlterSchema", is_edge=s.is_edge, name=s.name, adds=s.adds,
            drops=s.drops, changes=s.changes, ttl_duration=s.ttl_duration,
            ttl_col=s.ttl_col, space=p.need_space()),
        A.DropSchemaSentence: lambda p, s: _admin(
            "DropSchema", is_edge=s.is_edge, name=s.name,
            if_exists=s.if_exists, space=p.need_space()),
        A.DescribeSentence: lambda p, s: _admin(
            "Describe", cols=["Field", "Type", "Null", "Default"],
            kind=s.kind, name=s.name,
            space=p.space if s.kind != "space" else None),
        A.ShowSentence: lambda p, s: _admin(
            "Show", cols=["Name"], kind=s.kind, extra=s.extra, space=p.space),
        A.CreateIndexSentence: lambda p, s: _admin(
            "CreateIndex", is_edge=s.is_edge, index_name=s.index_name,
            schema_name=s.schema_name, fields=s.fields,
            field_lens=getattr(s, "field_lens", None) or None,
            if_not_exists=s.if_not_exists, space=p.need_space()),
        A.DropIndexSentence: lambda p, s: _admin(
            "DropIndex", is_edge=s.is_edge, index_name=s.index_name,
            if_exists=s.if_exists, space=p.need_space()),
        A.RebuildIndexSentence: lambda p, s: _admin(
            "RebuildIndex", is_edge=s.is_edge, index_name=s.index_name,
            space=p.need_space()),
        A.CreateFulltextIndexSentence: lambda p, s: _admin(
            "CreateFulltextIndex", is_edge=s.is_edge,
            index_name=s.index_name, schema_name=s.schema_name,
            field=s.field, if_not_exists=s.if_not_exists,
            space=p.need_space()),
        A.DropFulltextIndexSentence: lambda p, s: _admin(
            "DropFulltextIndex", index_name=s.index_name,
            if_exists=s.if_exists, space=p.need_space()),
        A.RebuildFulltextIndexSentence: lambda p, s: _admin(
            "RebuildFulltextIndex", index_name=s.index_name,
            space=p.need_space()),
        A.AddListenerSentence: lambda p, s: _admin(
            "AddListener", ltype=s.ltype, endpoints=s.endpoints,
            space=p.need_space()),
        A.RemoveListenerSentence: lambda p, s: _admin(
            "RemoveListener", ltype=s.ltype, space=p.need_space()),
        A.SubmitJobSentence: lambda p, s: _admin(
            "SubmitJob", cols=["New Job Id"], job=s.job, space=p.space),
        A.ShowJobsSentence: lambda p, s: _admin(
            "ShowJobs", cols=["Job Id", "Command", "Status"], job_id=s.job_id),
        A.CreateSnapshotSentence: lambda p, s: _admin("CreateSnapshot"),
        A.DropSnapshotSentence: lambda p, s: _admin("DropSnapshot", name=s.name),
        A.CreateBackupSentence: lambda p, s: _admin(
            "CreateBackup", cols=["Name"], name=s.name),
        A.DropBackupSentence: lambda p, s: _admin("DropBackup", name=s.name),
        A.RestoreBackupSentence: lambda p, s: _admin(
            "RestoreBackup", cols=["Restored Spaces"], name=s.name),
        A.KillQuerySentence: lambda p, s: _admin(
            "KillQuery", session_id=s.session_id, plan_id=s.plan_id),
        A.KillSessionSentence: lambda p, s: _admin(
            "KillSession", session_id=s.session_id),
        A.UpdateConfigsSentence: lambda p, s: _admin(
            "UpdateConfigs", updates=s.updates),
        A.GetConfigsSentence: lambda p, s: _admin(
            "GetConfigs", cols=["Module", "Name", "Type", "Mode", "Value"],
            name=s.name),
        A.AddHostsSentence: lambda p, s: _admin(
            "AddHosts", hosts=s.hosts, zone=s.zone),
        A.DropHostsSentence: lambda p, s: _admin(
            "DropHosts", hosts=s.hosts),
        A.DropZoneSentence: lambda p, s: _admin(
            "DropZone", zone=s.zone),
        A.MergeZoneSentence: lambda p, s: _admin(
            "MergeZone", zones=s.zones, into=s.into),
        A.RenameZoneSentence: lambda p, s: _admin(
            "RenameZone", old=s.old, new=s.new),
        A.DivideZoneSentence: lambda p, s: _admin(
            "DivideZone", zone=s.zone, parts=s.parts),
        A.DescZoneSentence: lambda p, s: _admin(
            "DescZone", cols=["Hosts"], zone=s.zone),
        A.ClearSpaceSentence: lambda p, s: _admin(
            "ClearSpace", name=s.name, if_exists=s.if_exists),
        A.StopJobSentence: lambda p, s: _admin(
            "StopJob", cols=["Result"], job_id=s.job_id),
        A.RecoverJobSentence: lambda p, s: _admin(
            "RecoverJob", cols=["Recovered job num"], job_id=s.job_id),
        A.SignInTextServiceSentence: lambda p, s: _admin(
            "SignInTextService", endpoints=s.endpoints, user=s.user,
            password=s.password),
        A.SignOutTextServiceSentence: lambda p, s: _admin(
            "SignOutTextService"),
        A.DescribeUserSentence: lambda p, s: _admin(
            "DescribeUser", cols=["role", "space"], name=s.name),
        A.AlterSpaceSentence: lambda p, s: _admin(
            "AlterSpace", name=s.name, op=s.op, zone=s.zone),
        A.DownloadSentence: lambda p, s: _admin(
            "Download", url=s.url),
        A.IngestSentence: lambda p, s: _admin(
            "SubmitJob", cols=["New Job Id"], job="ingest", space=p.space),
        A.CreateUserSentence: lambda p, s: _admin(
            "CreateUser", name=s.name, password=s.password,
            if_not_exists=s.if_not_exists),
        A.DropUserSentence: lambda p, s: _admin(
            "DropUser", name=s.name, if_exists=s.if_exists),
        A.AlterUserSentence: lambda p, s: _admin(
            "AlterUser", name=s.name, password=s.password),
        A.ChangePasswordSentence: lambda p, s: _admin(
            "ChangePassword", name=s.name, old=s.old, new=s.new),
        A.GrantRoleSentence: lambda p, s: _admin(
            "GrantRole", role=s.role, space=s.space, user=s.user),
        A.RevokeRoleSentence: lambda p, s: _admin(
            "RevokeRole", role=s.role, space=s.space, user=s.user),
    })


_register_dispatch()
