"""nGQL parser: hand-written recursive descent + Pratt expressions.

Replaces the reference's bison grammar (reference: src/parser/parser.yy
[UNVERIFIED — empty mount, SURVEY §0]).  The grammar below is the supported
subset: GO / FETCH / LOOKUP / MATCH / FIND PATH / GET SUBGRAPH / YIELD,
DDL (space/tag/edge/index), DML (insert/update/upsert/delete), admin
(SHOW/DESCRIBE/EXPLAIN/PROFILE/jobs/snapshot), composition (`;`, `|`,
assignment, UNION/INTERSECT/MINUS).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core.expr import (AggExpr, AttributeExpr, Binary, Case, EdgeExpr,
                         Expr, FunctionCall, InputProp, LabelExpr,
                         ListComprehension, ListExpr, Literal, MapExpr,
                         PatternPredExpr, PredicateExpr, Reduce, SetExpr,
                         Slice, SrcProp, Subscript, Unary, VarExpr, VarProp,
                         VertexExpr, DstProp)
from ..core.expr import AGG_NAMES
from ..core.value import NULL
from . import ast as A
from .tokenizer import LexError, Token, tokenize


class ParseError(Exception):
    pass


PIPE_STARTERS = {"GO", "YIELD", "GROUP", "ORDER", "LIMIT", "SAMPLE", "FETCH",
                 "LOOKUP", "DELETE"}


def parse(text: str) -> A.Sentence:
    return Parser(text).parse_program()


def parse_expression(text: str) -> Expr:
    """Parse ONE expression (the wire format for pushed-down storage
    filters — predicates ship as canonical nGQL text, never code)."""
    p = Parser(text)
    e = p.parse_expr()
    if not p.at("EOF"):
        t = p.peek()
        raise ParseError(f"trailing input after expression at pos {t.pos}")
    return e


class Parser:
    def __init__(self, text: str):
        self.text = text
        try:
            self.toks = tokenize(text)
        except LexError as e:
            raise ParseError(str(e)) from None
        self.i = 0
        # >0 while parsing inside a bracketed expression context —
        # gates the bit-or operator (see p_bitor)
        self.bracket = 0

    # ---- token helpers ----
    def peek(self, off=0) -> Token:
        return self.toks[min(self.i + off, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def at(self, kind: str, value=None) -> bool:
        t = self.peek()
        if t.kind != kind:
            return False
        return value is None or t.value == value

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.value in kws

    def accept(self, kind: str, value=None) -> Optional[Token]:
        if self.at(kind, value):
            return self.next()
        return None

    def accept_kw(self, *kws) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.next()
        return None

    def expect(self, kind: str, value=None) -> Token:
        t = self.peek()
        if not self.at(kind, value):
            raise ParseError(f"expected {value or kind}, got {t.kind}"
                             f"({t.value!r}) at pos {t.pos}")
        return self.next()

    def expect_kw(self, *kws) -> Token:
        if not self.at_kw(*kws):
            t = self.peek()
            raise ParseError(f"expected {'/'.join(kws)}, got {t.value!r} at pos {t.pos}")
        return self.next()

    def ident(self, allow_keywords=True) -> str:
        t = self.peek()
        if t.kind == "IDENT":
            return self.next().value
        if allow_keywords and t.kind == "KEYWORD":
            t = self.next()
            return t.raw or t.value.lower()
        raise ParseError(f"expected identifier, got {t.kind}({t.value!r}) at pos {t.pos}")

    # ---- program / composition ----
    def parse_program(self) -> A.Sentence:
        stmts = []
        while not self.at("EOF"):
            if self.accept(";"):
                continue
            stmts.append(self.parse_statement())
            if not self.at("EOF"):
                self.expect(";")
        if not stmts:
            raise ParseError("empty statement")
        return stmts[0] if len(stmts) == 1 else A.SeqSentence(stmts)

    def parse_statement(self) -> A.Sentence:
        if self.at_kw("EXPLAIN", "PROFILE"):
            kw = self.next().value
            fmt = "row"
            if self.accept_kw("FORMAT"):
                self.expect("=")
                fmt = self.expect("STRING").value
            inner = self.parse_statement()
            return A.ExplainSentence(inner, profile=(kw == "PROFILE"), fmt=fmt)
        if self.at("VAR") and self.peek(1).kind == "=":
            var = self.next().value
            self.next()
            return A.AssignSentence(var, self.parse_set_op())
        return self.parse_set_op()

    def parse_set_op(self) -> A.Sentence:
        left = self.parse_pipeline()
        while self.at_kw("UNION", "INTERSECT", "MINUS"):
            op = self.next().value
            if op == "UNION":
                if self.accept_kw("ALL"):
                    op = "UNION ALL"
                elif self.accept_kw("DISTINCT"):
                    pass
            right = self.parse_pipeline()
            left = A.SetOpSentence(op, left, right)
        return left

    def parse_pipeline(self) -> A.Sentence:
        left = self.parse_basic()
        while self.accept("|"):
            right = self.parse_basic()
            left = A.PipedSentence(left, right)
        return left

    # ---- statement dispatch ----
    def parse_basic(self) -> A.Sentence:
        t = self.peek()
        if t.kind == "(":
            # parenthesized compound statement: set-op operands and
            # pipe sources may be grouped, `(A UNION B) | C`
            self.next()
            inner = self.parse_set_op()
            self.expect(")")
            return inner
        if t.kind != "KEYWORD":
            raise ParseError(f"unexpected {t.kind}({t.value!r}) at pos {t.pos}")
        kw = t.value
        fn = {
            "GO": self.p_go, "USE": self.p_use, "CREATE": self.p_create,
            "DROP": self.p_drop, "ALTER": self.p_alter, "SHOW": self.p_show,
            "DESCRIBE": self.p_describe, "DESC": self.p_describe,
            "INSERT": self.p_insert, "DELETE": self.p_delete,
            "UPDATE": self.p_update, "UPSERT": self.p_update,
            "FETCH": self.p_fetch, "LOOKUP": self.p_lookup,
            "MATCH": self.p_match, "OPTIONAL": self.p_match,
            "FIND": self.p_find_path, "GET": self.p_get,
            "YIELD": self.p_yield_stmt, "GROUP": self.p_group_by,
            "ORDER": self.p_order_by, "LIMIT": self.p_limit,
            "SAMPLE": self.p_sample, "REBUILD": self.p_rebuild,
            "SUBMIT": self.p_submit, "KILL": self.p_kill,
            "UNWIND": self.p_match, "GRANT": self.p_grant, "ADD": self.p_add,
            "REVOKE": self.p_revoke, "CHANGE": self.p_change_password,
            "REMOVE": self.p_remove, "CLEAR": self.p_clear,
            "STOP": self.p_stop_job, "RECOVER": self.p_recover_job,
            "RESTORE": self.p_restore_backup,
            "SIGN": self.p_sign, "MERGE": self.p_merge_zone,
            "RENAME": self.p_rename_zone, "DIVIDE": self.p_divide_zone,
            "BALANCE": self.p_balance,
            "DOWNLOAD": self.p_download, "INGEST": self.p_ingest,
            "RETURN": self.p_match, "WITH": self.p_match,
            "CALL": self.p_call_algo,
        }.get(kw)
        if fn is None:
            raise ParseError(f"unsupported statement `{kw}' at pos {t.pos}")
        return fn()

    def p_host_literal(self) -> str:
        """A host endpoint in either spelling: `"h":9779` (the reference
        grammar's STRING ':' port) or `"h:9779"` (one string) —
        normalized to "host:port"."""
        h = self.expect("STRING").value
        if self.accept(":"):
            h = f"{h}:{self.expect('INT').value}"
        return h

    def zone_name(self) -> str:
        """Zone names are quoted strings in the reference grammar but
        bare identifiers are accepted too (our TCK's original spelling)."""
        if self.at("STRING"):
            return self.next().value
        return self.ident()

    def p_add(self) -> A.Sentence:
        """ADD HOSTS "h":port [, ...] [INTO [NEW] ZONE zname] — host
        registration + optional placement zone (no zone → "default");
        ADD LISTENER ELASTICSEARCH "h:p" [, ...] — full-text sink."""
        self.expect_kw("ADD")
        if self.accept_kw("LISTENER"):
            ltype = self.expect_kw("ELASTICSEARCH").value
            eps = [self.expect("STRING").value]
            while self.accept(","):
                eps.append(self.expect("STRING").value)
            return A.AddListenerSentence(ltype, eps)
        self.expect_kw("HOSTS")
        hosts = [self.p_host_literal()]
        while self.accept(","):
            hosts.append(self.p_host_literal())
        zone = "default"
        if self.accept_kw("INTO"):
            self.accept_kw("NEW")
            self.expect_kw("ZONE")
            zone = self.zone_name()
        return A.AddHostsSentence(hosts, zone)

    def p_remove(self) -> A.RemoveListenerSentence:
        self.expect_kw("REMOVE")
        self.expect_kw("LISTENER")
        return A.RemoveListenerSentence(self.expect_kw("ELASTICSEARCH").value)

    def p_get(self) -> A.Sentence:
        """GET SUBGRAPH ... | GET CONFIGS [name]."""
        if self.peek(1).kind == "KEYWORD" and self.peek(1).value == "CONFIGS":
            self.expect_kw("GET")
            self.expect_kw("CONFIGS")
            name = None
            if self.peek().kind in ("IDENT", "KEYWORD") \
                    and not self.at(";"):
                name = self.ident()
                if self.accept(":"):    # module prefix (one process)
                    name = self.ident()
            return A.GetConfigsSentence(name)
        return self.p_subgraph()

    def p_clear(self) -> A.ClearSpaceSentence:
        """CLEAR SPACE [IF EXISTS] name — wipe data, keep schema."""
        self.expect_kw("CLEAR")
        self.expect_kw("SPACE")
        return A.ClearSpaceSentence(if_exists=self.p_if_exists(),
                                    name=self.ident())

    def p_stop_job(self) -> A.StopJobSentence:
        self.expect_kw("STOP")
        self.expect_kw("JOB")
        return A.StopJobSentence(self.expect("INT").value)

    def p_restore_backup(self) -> A.RestoreBackupSentence:
        """RESTORE BACKUP <name> — swap in a CREATE BACKUP checkpoint
        (the statement surface of the reference's br restore)."""
        self.expect_kw("RESTORE")
        self.expect_kw("BACKUP")
        return A.RestoreBackupSentence(self.ident())

    def p_recover_job(self) -> A.RecoverJobSentence:
        self.expect_kw("RECOVER")
        self.expect_kw("JOB")
        jid = None
        if self.at("INT"):
            jid = self.next().value
        return A.RecoverJobSentence(jid)

    def p_sign(self) -> A.Sentence:
        """SIGN IN TEXT SERVICE (host[, user, pw])[, ...] / SIGN OUT
        TEXT SERVICE — external full-text endpoint registration."""
        self.expect_kw("SIGN")
        if self.accept_kw("OUT"):
            self.expect_kw("TEXT")
            self.expect_kw("SERVICE")
            return A.SignOutTextServiceSentence()
        self.expect_kw("IN")
        self.expect_kw("TEXT")
        self.expect_kw("SERVICE")
        eps, user, pw = [], None, None
        while self.accept("("):
            eps.append(self.expect("STRING").value)
            if self.accept(","):
                user = self.expect("STRING").value
                self.expect(",")
                pw = self.expect("STRING").value
            self.expect(")")
            if not self.accept(","):
                break
        if not eps:
            raise ParseError("SIGN IN TEXT SERVICE needs (host) endpoints")
        return A.SignInTextServiceSentence(eps, user, pw)

    def p_merge_zone(self) -> A.MergeZoneSentence:
        self.expect_kw("MERGE")
        self.expect_kw("ZONE")
        zones = [self.zone_name()]
        while self.accept(","):
            zones.append(self.zone_name())
        self.expect_kw("INTO")
        return A.MergeZoneSentence(zones, self.zone_name())

    def p_rename_zone(self) -> A.RenameZoneSentence:
        self.expect_kw("RENAME")
        self.expect_kw("ZONE")
        old = self.zone_name()
        self.expect_kw("TO")
        return A.RenameZoneSentence(old, self.zone_name())

    def p_divide_zone(self) -> A.DivideZoneSentence:
        """DIVIDE ZONE z INTO z1 ("h":p [, ...]) z2 (...) [...] — split a
        placement zone's hosts into new zones; the host lists must
        partition the source zone exactly (meta validates)."""
        self.expect_kw("DIVIDE")
        self.expect_kw("ZONE")
        zone = self.zone_name()
        self.expect_kw("INTO")
        parts = []
        while True:
            name = self.zone_name()
            self.expect("(")
            hosts = [self.p_host_literal()]
            while self.accept(","):
                hosts.append(self.p_host_literal())
            self.expect(")")
            parts.append((name, hosts))
            self.accept(",")
            if self.at(";") or self.at("EOF"):
                break
            # zone_name() also accepts keywords-as-identifiers (e.g.
            # `default`) — continue on any of the three token kinds
            if not (self.at("STRING")
                    or self.peek().kind in ("IDENT", "KEYWORD")):
                break
        if len(parts) < 2:
            raise ParseError("DIVIDE ZONE needs at least two target zones")
        return A.DivideZoneSentence(zone, parts)

    def p_download(self) -> A.DownloadSentence:
        self.expect_kw("DOWNLOAD")
        self.expect_kw("HDFS")
        return A.DownloadSentence(self.expect("STRING").value)

    def p_ingest(self) -> A.IngestSentence:
        self.expect_kw("INGEST")
        return A.IngestSentence()

    def p_balance(self) -> A.SubmitJobSentence:
        """BALANCE DATA [REMOVE "host" [, ...]] / BALANCE LEADER — the
        2.x spelling; canonicalizes to the SUBMIT JOB form the job
        manager executes."""
        self.expect_kw("BALANCE")
        which = self.expect_kw("DATA", "LEADER").value.lower()
        job = f"balance {which}"
        if which == "data" and self.accept_kw("REMOVE"):
            hosts = [self.expect("STRING").value]
            while self.accept(","):
                hosts.append(self.expect("STRING").value)
            job += " remove " + ",".join(hosts)
        return A.SubmitJobSentence(job)

    # ---- user management (reference: GRANT/REVOKE ROLE, CHANGE PASSWORD) --
    def p_grant(self) -> A.GrantRoleSentence:
        self.expect_kw("GRANT")
        self.accept_kw("ROLE")
        role = self.ident()
        self.expect_kw("ON")
        space = self.ident()
        self.expect_kw("TO")
        return A.GrantRoleSentence(role, space, self.ident())

    def p_revoke(self) -> A.RevokeRoleSentence:
        self.expect_kw("REVOKE")
        self.accept_kw("ROLE")
        role = self.ident()
        self.expect_kw("ON")
        space = self.ident()
        self.expect_kw("FROM")
        return A.RevokeRoleSentence(role, space, self.ident())

    def p_change_password(self) -> A.ChangePasswordSentence:
        self.expect_kw("CHANGE")
        self.expect_kw("PASSWORD")
        name = self.ident()
        self.expect_kw("FROM")
        old = self.expect("STRING").value
        self.expect_kw("TO")
        return A.ChangePasswordSentence(name, old, self.expect("STRING").value)

    # ---- GO ----
    def p_go(self) -> A.GoSentence:
        self.expect_kw("GO")
        steps = A.StepClause(1, 1)
        if self.at("INT"):
            m = self.next().value
            if self.accept_kw("TO"):
                n = self.expect("INT").value
                steps = A.StepClause(m, n)
            else:
                steps = A.StepClause(m, m)
            self.expect_kw("STEPS", "STEP")
        from_ = self.p_from()
        over = self.p_over()
        where = self.p_opt_where()
        yld = self.p_opt_yield()
        trunc = None
        if self.at_kw("SAMPLE"):
            self.next()
            trunc = A.TruncateClause(self.p_int_list(), is_sample=True)
        elif self.at_kw("LIMIT"):
            self.next()
            trunc = A.TruncateClause(self.p_int_list(), is_sample=False)
        return A.GoSentence(steps, from_, over, where, yld, trunc)

    def p_from(self) -> A.FromClause:
        self.expect_kw("FROM")
        return self.p_vid_list()

    def p_vid_list(self) -> A.FromClause:
        if self.at("$-") or self.at("VAR"):
            ref = self.parse_expr()
            return A.FromClause(ref=ref)
        vids = [self.parse_expr()]
        while self.accept(","):
            vids.append(self.parse_expr())
        return A.FromClause(vids=vids)

    def p_over(self) -> A.OverClause:
        self.expect_kw("OVER")
        edges: List[str] = []
        if self.accept("*"):
            pass
        else:
            edges.append(self.ident())
            while self.accept(","):
                edges.append(self.ident())
        direction = "out"
        if self.accept_kw("REVERSELY"):
            direction = "in"
        elif self.accept_kw("BIDIRECT"):
            direction = "both"
        return A.OverClause(edges, direction)

    def p_opt_where(self) -> Optional[A.WhereClause]:
        if self.accept_kw("WHERE"):
            return A.WhereClause(self.parse_expr())
        return None

    def p_opt_yield(self) -> Optional[A.YieldClause]:
        if self.at_kw("YIELD"):
            return self.p_yield()
        return None

    def p_yield(self) -> A.YieldClause:
        self.expect_kw("YIELD")
        distinct = bool(self.accept_kw("DISTINCT"))
        cols = [self.p_yield_col()]
        while self.accept(","):
            cols.append(self.p_yield_col())
        return A.YieldClause(cols, distinct)

    def p_yield_col(self) -> A.YieldColumn:
        e = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        return A.YieldColumn(e, alias)

    def p_int_list(self) -> List[int]:
        """Per-step counts — the reference spells them bracketed
        (`LIMIT [10, 100]`); the bare form stays accepted."""
        bracketed = self.accept("[") is not None
        out = [self.expect("INT").value]
        while self.accept(","):
            out.append(self.expect("INT").value)
        if bracketed:
            self.expect("]")
        return out

    # ---- YIELD / pipe segments ----
    def p_yield_stmt(self) -> A.YieldSentence:
        yld = self.p_yield()
        where = self.p_opt_where()
        return A.YieldSentence(yld, where)

    def p_group_by(self) -> A.GroupBySentence:
        self.expect_kw("GROUP")
        self.expect_kw("BY")
        keys = [self.parse_expr()]
        while self.accept(","):
            keys.append(self.parse_expr())
        yld = self.p_yield()
        return A.GroupBySentence(keys, yld)

    def p_order_by(self) -> A.OrderBySentence:
        self.expect_kw("ORDER")
        self.expect_kw("BY")
        factors = [self.p_order_factor()]
        while self.accept(","):
            factors.append(self.p_order_factor())
        return A.OrderBySentence(factors)

    def p_order_factor(self) -> A.OrderFactor:
        e = self.parse_expr()
        asc = True
        if self.accept_kw("ASC", "ASCENDING"):
            asc = True
        elif self.accept_kw("DESC", "DESCENDING"):
            asc = False
        return A.OrderFactor(e, asc)

    def p_limit(self) -> A.LimitSentence:
        self.expect_kw("LIMIT")
        a = self.expect("INT").value
        if self.accept(","):
            b = self.expect("INT").value
            return A.LimitSentence(a, b)
        if self.accept_kw("OFFSET"):
            off = self.expect("INT").value
            return A.LimitSentence(off, a)
        return A.LimitSentence(0, a)

    def p_sample(self) -> A.SampleSentence:
        self.expect_kw("SAMPLE")
        return A.SampleSentence(self.expect("INT").value)

    # ---- USE / DDL ----
    def p_use(self) -> A.UseSentence:
        self.expect_kw("USE")
        return A.UseSentence(self.ident())

    def p_create(self) -> A.Sentence:
        self.expect_kw("CREATE")
        if self.accept_kw("FULLTEXT"):
            # CREATE FULLTEXT {TAG|EDGE} INDEX name ON schema(field)
            is_edge = self.expect_kw("TAG", "EDGE").value == "EDGE"
            self.expect_kw("INDEX")
            ine = self.p_if_not_exists()
            iname = self.ident()
            self.expect_kw("ON")
            sname = self.ident()
            self.expect("(")
            field = self.ident()
            self.expect(")")
            return A.CreateFulltextIndexSentence(is_edge, iname, sname,
                                                 field, ine)
        if self.accept_kw("SPACE"):
            ine = self.p_if_not_exists()
            name = self.ident()
            if self.accept_kw("AS"):
                return A.CreateSpaceAsSentence(name, self.ident(), ine)
            kw = {"partition_num": 8, "replica_factor": 1,
                  "vid_type": "FIXED_STRING(32)"}
            if self.accept("("):
                while not self.accept(")"):
                    opt = self.ident().lower()
                    self.expect("=")
                    if opt == "vid_type":
                        kw["vid_type"] = self.p_type_name()
                    elif opt in ("partition_num", "replica_factor"):
                        kw[opt] = self.expect("INT").value
                    else:
                        raise ParseError(f"unknown space option `{opt}'")
                    self.accept(",")
            cmt = self.p_opt_comment()
            return A.CreateSpaceSentence(name, ine, kw["partition_num"],
                                         kw["replica_factor"], kw["vid_type"], cmt)
        if self.at_kw("TAG", "EDGE"):
            is_edge = self.next().value == "EDGE"
            if self.accept_kw("INDEX"):
                ine = self.p_if_not_exists()
                iname = self.ident()
                self.expect_kw("ON")
                sname = self.ident()
                self.expect("(")
                fields = []
                field_lens = []
                while not self.accept(")"):
                    fields.append(self.ident())
                    ln = 0
                    if self.accept("("):
                        # string columns index a fixed prefix in the
                        # reference: CREATE TAG INDEX i ON t(name(10))
                        ln = self.expect("INT").value
                        if ln <= 0:
                            raise ParseError(
                                "index prefix length must be positive")
                        self.expect(")")
                    field_lens.append(ln)
                    self.accept(",")
                return A.CreateIndexSentence(is_edge, iname, sname, fields,
                                             ine, field_lens)
            ine = self.p_if_not_exists()
            name = self.ident()
            props: List[A.PropDefAst] = []
            if self.accept("("):
                while not self.accept(")"):
                    props.append(self.p_prop_def())
                    self.accept(",")
            ttl_d, ttl_c = 0, ""
            while self.at_kw("TTL_DURATION", "TTL_COL"):
                w = self.next().value
                self.expect("=")
                if w == "TTL_DURATION":
                    ttl_d = self.expect("INT").value
                else:
                    ttl_c = self.expect("STRING").value
                self.accept(",")
            cmt = self.p_opt_comment()
            return A.CreateSchemaSentence(is_edge, name, props, ine, ttl_d, ttl_c, cmt)
        if self.accept_kw("SNAPSHOT"):
            return A.CreateSnapshotSentence()
        if self.accept_kw("BACKUP"):
            name = self.ident() if self.accept_kw("AS") else None
            return A.CreateBackupSentence(name)
        if self.accept_kw("USER"):
            ine = self.p_if_not_exists()
            name = self.ident()
            self.expect_kw("WITH")
            self.expect_kw("PASSWORD")
            pw = self.expect("STRING").value
            return A.CreateUserSentence(name, pw, ine)
        raise ParseError("expected SPACE/TAG/EDGE/SNAPSHOT/USER after CREATE")

    def p_if_not_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def p_if_exists(self) -> bool:
        if self.accept_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def p_opt_comment(self) -> str:
        if self.accept_kw("COMMENT"):
            self.expect("=")
            return self.expect("STRING").value
        return ""

    def p_type_name(self) -> str:
        t = self.peek()
        if t.kind == "KEYWORD" and t.value == "FIXED_STRING":
            self.next()
            self.expect("(")
            n = self.expect("INT").value
            self.expect(")")
            return f"FIXED_STRING({n})"
        if t.kind == "KEYWORD" and t.value == "GEOGRAPHY":
            self.next()
            # GEOGRAPHY(POINT|LINESTRING|POLYGON): the shape constraint
            # is accepted reference-compatibly (stored as geography)
            if self.accept("("):
                self.ident()
                self.expect(")")
            return "GEOGRAPHY"
        if t.kind in ("KEYWORD", "IDENT"):
            return self.next().value
        raise ParseError(f"expected type name at pos {t.pos}")

    def p_prop_def(self) -> A.PropDefAst:
        name = self.ident()
        tname = self.p_type_name()
        fixed = 0
        if tname.upper().startswith("FIXED_STRING("):
            fixed = int(tname[13:-1])
            tname = "FIXED_STRING"
        nullable = True
        default: Optional[Expr] = None
        while True:
            if self.at_kw("NOT") and self.peek(1).value == "NULL":
                self.next(); self.next()
                nullable = False
            elif self.at_kw("NULL"):
                self.next()
                nullable = True
            elif self.accept_kw("DEFAULT"):
                default = self.parse_expr()
            elif self.at_kw("COMMENT"):
                self.next()
                self.expect("=")
                self.expect("STRING")
            else:
                break
        return A.PropDefAst(name, tname, fixed, nullable, default)

    def p_drop(self) -> A.Sentence:
        self.expect_kw("DROP")
        if self.accept_kw("FULLTEXT"):
            self.expect_kw("INDEX")
            ife = self.p_if_exists()
            return A.DropFulltextIndexSentence(self.ident(), ife)
        if self.accept_kw("SPACE"):
            ife = self.p_if_exists()
            return A.DropSpaceSentence(self.ident(), ife)
        if self.at_kw("TAG", "EDGE"):
            is_edge = self.next().value == "EDGE"
            if self.accept_kw("INDEX"):
                ife = self.p_if_exists()
                return A.DropIndexSentence(is_edge, self.ident(), ife)
            ife = self.p_if_exists()
            return A.DropSchemaSentence(is_edge, self.ident(), ife)
        if self.accept_kw("SNAPSHOT"):
            return A.DropSnapshotSentence(self.ident())
        if self.accept_kw("BACKUP"):
            return A.DropBackupSentence(self.ident())
        if self.accept_kw("USER"):
            ife = self.p_if_exists()
            return A.DropUserSentence(self.ident(), ife)
        if self.accept_kw("ZONE"):
            return A.DropZoneSentence(self.zone_name())
        if self.accept_kw("HOSTS"):
            hosts = [self.p_host_literal()]
            while self.accept(","):
                hosts.append(self.p_host_literal())
            return A.DropHostsSentence(hosts)
        raise ParseError(
            "expected SPACE/TAG/EDGE/SNAPSHOT/USER/ZONE/HOSTS after DROP")

    def p_alter(self) -> A.Sentence:
        self.expect_kw("ALTER")
        if self.accept_kw("USER"):
            name = self.ident()
            self.expect_kw("WITH")
            self.expect_kw("PASSWORD")
            return A.AlterUserSentence(name, self.expect("STRING").value)
        if self.accept_kw("SPACE"):
            name = self.ident()
            self.expect_kw("ADD")
            self.expect_kw("ZONE")
            return A.AlterSpaceSentence(name, "add_zone", self.ident())
        is_edge = self.expect_kw("TAG", "EDGE").value == "EDGE"
        name = self.ident()
        out = A.AlterSchemaSentence(is_edge, name)
        while True:
            if self.accept_kw("ADD"):
                self.expect("(")
                while not self.accept(")"):
                    out.adds.append(self.p_prop_def())
                    self.accept(",")
            elif self.accept_kw("DROP"):
                self.expect("(")
                while not self.accept(")"):
                    out.drops.append(self.ident())
                    self.accept(",")
            elif self.accept_kw("CHANGE"):
                self.expect("(")
                while not self.accept(")"):
                    out.changes.append(self.p_prop_def())
                    self.accept(",")
            elif self.at_kw("TTL_DURATION", "TTL_COL"):
                w = self.next().value
                self.expect("=")
                if w == "TTL_DURATION":
                    out.ttl_duration = self.expect("INT").value
                else:
                    out.ttl_col = self.expect("STRING").value
            else:
                break
            if not self.accept(","):
                break
        return out

    def p_show(self) -> A.Sentence:
        self.expect_kw("SHOW")
        t = self.peek()
        if t.kind == "KEYWORD":
            kw = t.value
            if kw == "HOSTS":
                self.next()
                role = self.accept_kw("GRAPH", "STORAGE", "META")
                return A.ShowSentence(
                    "hosts", role.value.lower() if role else None)
            if kw in ("LOCAL", "ALL") \
                    and self.peek(1).kind == "KEYWORD" \
                    and self.peek(1).value in ("SESSIONS", "QUERIES",
                                               "STATEMENTS", "TENANTS"):
                # SHOW LOCAL SESSIONS/QUERIES/STATEMENTS: this graphd
                # only; SHOW ALL ...: cluster-wide (the default)
                scope = self.next().value.lower()
                which = self.next().value.lower()
                return A.ShowSentence(which,
                                      scope if scope == "local" else None)
            if kw in ("SPACES", "PARTS", "STATS", "JOBS", "SESSIONS",
                      "SNAPSHOTS", "BACKUPS", "QUERIES", "CONFIGS",
                      "TRACES", "STALLS", "REPAIRS", "STATEMENTS",
                      "HOTSPOTS", "TENANTS"):
                self.next()
                if kw == "JOBS":
                    return A.ShowJobsSentence()
                return A.ShowSentence(kw.lower())
            if kw == "FLIGHT":
                # SHOW FLIGHT RECORDER (ISSUE 8): the always-on ring of
                # sampled/slow/failed statement profiles
                self.next()
                self.expect_kw("RECORDER")
                return A.ShowSentence("flight_recorder")
            if kw == "SLO":
                self.next()
                return A.ShowSentence("slo")
            if kw == "TEXT":
                self.next()
                self.expect_kw("SEARCH")
                self.expect_kw("CLIENTS")
                return A.ShowSentence("text_search_clients")
            if kw == "META":
                self.next()
                self.expect_kw("LEADER")
                return A.ShowSentence("meta_leader")
            if kw in ("TAGS", "EDGES", "USERS", "ZONES"):
                self.next()
                return A.ShowSentence(kw.lower())
            if kw == "FULLTEXT":
                self.next()
                self.expect_kw("INDEXES")
                return A.ShowSentence("fulltext_indexes")
            if kw in ("CHARSET", "COLLATION"):
                self.next()
                return A.ShowSentence(kw.lower())
            if kw == "LISTENER":
                self.next()
                return A.ShowSentence("listener")
            if kw == "ROLES":
                self.next()
                self.expect_kw("IN")
                return A.ShowSentence("roles", self.ident())
            if kw in ("TAG", "EDGE"):
                self.next()
                if self.accept_kw("INDEXES"):
                    which = "tag_indexes" if kw == "TAG" else "edge_indexes"
                    if self.accept_kw("STATUS"):
                        return A.ShowSentence(which + "_status")
                    return A.ShowSentence(which)
                if self.accept_kw("INDEX"):
                    self.expect_kw("STATUS")
                    return A.ShowSentence(
                        ("tag_indexes" if kw == "TAG" else "edge_indexes")
                        + "_status")
                raise ParseError("expected INDEXES after SHOW TAG/EDGE")
            if kw == "CREATE":
                self.next()
                which = self.expect_kw("TAG", "EDGE", "SPACE").value
                return A.ShowSentence("create", (which.lower(), self.ident()))
            if kw == "JOB":
                self.next()
                return A.ShowJobsSentence(self.expect("INT").value)
        raise ParseError(f"unsupported SHOW target at pos {t.pos}")

    def p_describe(self) -> A.Sentence:
        self.expect_kw("DESCRIBE", "DESC")
        if self.accept_kw("USER"):
            return A.DescribeUserSentence(self.ident())
        if self.accept_kw("ZONE"):
            return A.DescZoneSentence(self.ident())
        kind = self.expect_kw("SPACE", "TAG", "EDGE", "INDEX").value.lower()
        if kind in ("tag", "edge") and self.accept_kw("INDEX"):
            kind = "index"       # reference spelling: DESC TAG/EDGE INDEX i
        return A.DescribeSentence(kind, self.ident())

    def p_rebuild(self) -> A.Sentence:
        self.expect_kw("REBUILD")
        if self.accept_kw("FULLTEXT"):
            self.expect_kw("INDEX")
            name = None
            if self.peek().kind in ("IDENT", "KEYWORD"):
                name = self.ident()
            return A.RebuildFulltextIndexSentence(name)
        is_edge = self.expect_kw("TAG", "EDGE").value == "EDGE"
        self.expect_kw("INDEX")
        return A.RebuildIndexSentence(is_edge, self.ident())

    def p_submit(self) -> A.SubmitJobSentence:
        self.expect_kw("SUBMIT")
        self.expect_kw("JOB")
        parts = [self.ident().lower()]
        while self.peek().kind in ("KEYWORD", "IDENT", "INT"):
            if self.at("INT"):
                parts.append(str(self.next().value))
            else:
                parts.append(self.ident().lower())
        return A.SubmitJobSentence(" ".join(parts))

    def p_kill(self) -> A.Sentence:
        self.expect_kw("KILL")
        if self.accept_kw("SESSION", "SESSIONS"):
            return A.KillSessionSentence(self.expect("INT").value)
        self.expect_kw("QUERY")
        out = A.KillQuerySentence()
        self.expect("(")
        while not self.accept(")"):
            which = self.ident().lower()
            self.expect("=")
            v = self.expect("INT").value
            if which == "session":
                out.session_id = v
            else:
                out.plan_id = v
            self.accept(",")
        return out

    # ---- DML ----
    def p_insert(self) -> A.Sentence:
        self.expect_kw("INSERT")
        if self.accept_kw("VERTEX"):
            ine = self.p_if_not_exists()
            groups = []
            while True:
                tag = self.ident()
                groups.append((tag, self.p_name_list_paren()))
                if not self.accept(","):
                    break
            self.expect_kw("VALUES")
            rows = []
            while True:
                vid = self.parse_expr()
                self.expect(":")
                self.expect("(")
                vals = []
                while not self.accept(")"):
                    vals.append(self.parse_expr())
                    self.accept(",")
                rows.append(A.VertexRowAst(vid, vals))
                if not self.accept(","):
                    break
            return A.InsertVerticesSentence(groups, rows, ine)
        self.expect_kw("EDGE")
        ine = self.p_if_not_exists()
        etype = self.ident()
        names = self.p_name_list_paren()
        self.expect_kw("VALUES")
        rows = []
        while True:
            src = self.parse_expr()
            self.expect("->")
            dst = self.parse_expr()
            rank = 0
            if self.accept("@"):
                rank = self.expect("INT").value
            self.expect(":")
            self.expect("(")
            vals = []
            while not self.accept(")"):
                vals.append(self.parse_expr())
                self.accept(",")
            rows.append(A.EdgeRowAst(src, dst, rank, vals))
            if not self.accept(","):
                break
        return A.InsertEdgesSentence(etype, names, rows, ine)

    def p_name_list_paren(self) -> List[str]:
        self.expect("(")
        names = []
        while not self.accept(")"):
            names.append(self.ident())
            self.accept(",")
        return names

    def p_delete(self) -> A.Sentence:
        self.expect_kw("DELETE")
        if self.accept_kw("VERTEX"):
            vids = self.p_vid_list()
            we = False
            if self.accept_kw("WITH"):
                self.expect_kw("EDGE")
                we = True
            return A.DeleteVerticesSentence(vids, we)
        if self.accept_kw("TAG"):
            tags = []
            if not self.accept("*"):
                tags.append(self.ident())
                while self.accept(","):
                    tags.append(self.ident())
            self.expect_kw("FROM")
            return A.DeleteTagsSentence(tags, self.p_vid_list())
        self.expect_kw("EDGE")
        etype = self.ident()
        if self.at("$-") or self.at("VAR"):
            src = self.parse_expr()
            self.expect("->")
            dst = self.parse_expr()
            rank = None
            if self.accept("@"):
                rank = self.parse_expr()
            return A.DeleteEdgesSentence(etype, [], ref=(src, dst, rank))
        keys = []
        while True:
            src = self.parse_expr()
            self.expect("->")
            dst = self.parse_expr()
            rank = 0
            if self.accept("@"):
                rank = self.expect("INT").value
            keys.append(A.EdgeKeyAst(src, dst, rank))
            if not self.accept(","):
                break
        return A.DeleteEdgesSentence(etype, keys)

    def p_update(self) -> A.Sentence:
        kw = self.expect_kw("UPDATE", "UPSERT").value
        insertable = kw == "UPSERT"
        if not insertable and self.accept_kw("CONFIGS"):
            # UPDATE CONFIGS [module:]name = value [, name = value ...]
            # (gflags live mutation; multi-key batches apply atomically
            # — all keys validate or nothing changes)
            updates = []
            while True:
                name = self.ident()
                if self.accept(":"):
                    name = self.ident()  # module prefix ignored (one proc)
                self.expect("=")
                updates.append((name, self.parse_expr()))
                if not self.accept(","):
                    break
            return A.UpdateConfigsSentence(updates)
        is_edge = self.expect_kw("VERTEX", "EDGE").value == "EDGE"
        self.expect_kw("ON")
        schema = self.ident()
        out = A.UpdateSentence(is_edge, schema, insertable=insertable)
        if is_edge:
            src = self.parse_expr()
            self.expect("->")
            dst = self.parse_expr()
            rank = 0
            if self.accept("@"):
                rank = self.expect("INT").value
            out.edge_key = A.EdgeKeyAst(src, dst, rank)
        else:
            out.vid = self.parse_expr()
        self.expect_kw("SET")
        while True:
            name = self.ident()
            self.expect("=")
            out.sets.append((name, self.parse_expr()))
            if not self.accept(","):
                break
        if self.accept_kw("WHEN"):
            out.when = self.parse_expr()
        out.yield_ = self.p_opt_yield()
        return out

    # ---- FETCH / LOOKUP ----
    def p_fetch(self) -> A.Sentence:
        self.expect_kw("FETCH")
        self.expect_kw("PROP")
        self.expect_kw("ON")
        if self.accept("*"):
            vids = self.p_vid_list()
            return A.FetchVerticesSentence([], vids, self.p_opt_yield())
        names = [self.ident()]
        while self.accept(","):
            names.append(self.ident())
        # edge fetch: src -> dst follows
        save = self.i
        first = self.parse_expr()
        if self.at("->"):
            self.next()
            if len(names) != 1:
                raise ParseError("FETCH PROP ON edge takes one edge type")
            dst = self.parse_expr()
            rank = 0
            if self.accept("@"):
                rank = self.expect("INT").value
            keys = [A.EdgeKeyAst(first, dst, rank)]
            while self.accept(","):
                s = self.parse_expr()
                self.expect("->")
                d = self.parse_expr()
                r = 0
                if self.accept("@"):
                    r = self.expect("INT").value
                keys.append(A.EdgeKeyAst(s, d, r))
            return A.FetchEdgesSentence(names[0], keys, None, self.p_opt_yield())
        # vertex fetch
        self.i = save
        vids = self.p_vid_list()
        return A.FetchVerticesSentence(names, vids, self.p_opt_yield())

    def p_lookup(self) -> A.LookupSentence:
        self.expect_kw("LOOKUP")
        self.expect_kw("ON")
        name = self.ident()
        where = self.p_opt_where()
        return A.LookupSentence(name, where, self.p_opt_yield())

    # ---- CALL algo.* (graph-analytics plane, ISSUE 13) ----
    def p_call_algo(self) -> A.CallAlgoSentence:
        """CALL algo.<func>(name=value, ...) [YIELD col [AS a], ...].

        Parameters are NAMED (never positional) and their values are
        constant expressions — `CALL algo.sssp(src=42, weight="w")`.
        The yield columns are the algorithm's output column names."""
        self.expect_kw("CALL")
        module = self.ident()
        self.expect(".")
        func = self.ident()
        self.expect("(")
        params: Dict[str, Any] = {}
        if not self.at(")"):
            while True:
                t = self.peek()
                name = self.ident()
                if name in params:
                    raise ParseError(
                        f"duplicate parameter `{name}' at pos {t.pos}")
                self.expect("=")
                params[name] = self.parse_expr()
                if not self.accept(","):
                    break
        self.expect(")")
        return A.CallAlgoSentence(module, func, params,
                                  self.p_opt_yield())

    # ---- FIND PATH / SUBGRAPH ----
    def p_find_path(self) -> A.FindPathSentence:
        self.expect_kw("FIND")
        kind = self.expect_kw("SHORTEST", "ALL", "NOLOOP").value.lower()
        self.expect_kw("PATH")
        with_prop = False
        if self.accept_kw("WITH"):
            self.expect_kw("PROP")
            with_prop = True
        from_ = self.p_from()
        self.expect_kw("TO")
        to = self.p_vid_list()
        over = self.p_over()
        where = self.p_opt_where()
        upto = 5
        if self.accept_kw("UPTO"):
            upto = self.expect("INT").value
            self.expect_kw("STEPS", "STEP")
        yld = self.p_opt_yield()
        return A.FindPathSentence(kind, from_, to, over, where, upto, with_prop, yld)

    def p_subgraph(self) -> A.SubgraphSentence:
        self.expect_kw("GET")
        self.expect_kw("SUBGRAPH")
        with_prop = False
        if self.accept_kw("WITH"):
            self.expect_kw("PROP")
            with_prop = True
        steps = 1
        if self.at("INT"):
            steps = self.next().value
            self.expect_kw("STEPS", "STEP")
        from_ = self.p_from()
        out = A.SubgraphSentence(steps, from_, with_prop=with_prop)
        while self.at_kw("IN", "OUT", "BOTH"):
            d = self.next().value
            names = []
            if self.accept("*"):
                out.all_edges = True
            else:
                names.append(self.ident())
                while self.accept(","):
                    names.append(self.ident())
            if d == "IN":
                out.in_edges = names
            elif d == "OUT":
                out.out_edges = names
            else:
                out.both_edges = names
        out.where = self.p_opt_where()
        out.yield_ = self.p_opt_yield()
        return out

    # ---- MATCH ----
    def p_match(self) -> A.MatchSentence:
        clauses: List[Any] = []
        while True:
            if self.at_kw("OPTIONAL"):
                self.next()
                self.expect_kw("MATCH")
                clauses.append(self.p_match_clause(optional=True))
            elif self.at_kw("MATCH"):
                self.next()
                clauses.append(self.p_match_clause(optional=False))
            elif self.at_kw("UNWIND"):
                self.next()
                e = self.parse_expr()
                self.expect_kw("AS")
                clauses.append(A.UnwindClauseAst(e, self.ident()))
            elif self.at_kw("WITH"):
                self.next()
                clauses.append(self.p_with_clause())
            else:
                break
        if self.accept_kw("RETURN"):
            ret = self.p_return_clause()
            return A.MatchSentence(clauses, ret)
        raise ParseError("query must end with RETURN")

    def p_match_clause(self, optional: bool) -> A.MatchClauseAst:
        pats = [self.p_path_pattern()]
        while self.accept(","):
            pats.append(self.p_path_pattern())
        where = None
        if self.accept_kw("WHERE"):
            where = self.parse_expr()
        return A.MatchClauseAst(pats, where, optional)

    def p_path_pattern(self) -> A.PathPattern:
        alias = None
        if self.peek().kind in ("IDENT", "KEYWORD") \
                and self.peek(1).kind == "=":
            alias = self.ident()
            self.next()
        pat = A.PathPattern(alias=alias)
        pat.nodes.append(self.p_node_pattern())
        while self.at("-") or self.at("<-") or self.at("<"):
            pat.edges.append(self.p_edge_pattern())
            pat.nodes.append(self.p_node_pattern())
        return pat

    def p_node_pattern(self) -> A.NodePattern:
        self.expect("(")
        np = A.NodePattern()
        if self.at("IDENT") or (self.at("KEYWORD") and self.peek(1).kind in (":", ")", "{")):
            if not self.at(")"):
                np.alias = self.ident()
        while self.accept(":"):
            label = self.ident()
            lprops = None
            if self.at("{"):
                lprops = self.p_prop_map()
            np.labels.append((label, lprops))
        if self.at("{"):
            np.props = self.p_prop_map()
        self.expect(")")
        return np

    def p_prop_map(self) -> Dict[str, Expr]:
        self.expect("{")
        out: Dict[str, Expr] = {}
        while not self.accept("}"):
            k = self.ident()
            self.expect(":")
            out[k] = self.parse_expr()
            self.accept(",")
        return out

    def p_edge_pattern(self) -> A.EdgePattern:
        ep = A.EdgePattern()
        back = False
        if self.accept("<-"):
            back = True
        else:
            self.expect("-")
        if self.accept("["):
            if self.peek().kind in ("IDENT", "KEYWORD") \
                    and self.peek(1).kind in (":", "]", "*", "{"):
                ep.alias = self.ident()
            while self.accept(":"):
                ep.types.append(self.ident())
                while self.accept("|"):
                    self.accept(":")     # both `|t` and `|:t` spellings
                    ep.types.append(self.ident())
            if self.accept("*"):
                ep.min_hop, ep.max_hop = 1, -1
                if self.at("INT"):
                    ep.min_hop = self.next().value
                    ep.max_hop = ep.min_hop
                    if self.accept(".."):
                        ep.max_hop = self.expect("INT").value if self.at("INT") else -1
                elif self.accept(".."):
                    ep.max_hop = self.expect("INT").value if self.at("INT") else -1
            if self.at("{"):
                ep.props = self.p_prop_map()
            self.expect("]")
        if self.accept("->"):
            ep.direction = "both" if back else "out"
            if back:
                raise ParseError("<-...-> pattern not supported")
        elif self.accept("-"):
            ep.direction = "in" if back else "both"
        else:
            raise ParseError(f"bad edge pattern at pos {self.peek().pos}")
        return ep

    def p_with_clause(self) -> A.WithClauseAst:
        distinct = bool(self.accept_kw("DISTINCT"))
        cols: Optional[List[A.YieldColumn]] = None
        if not self.accept("*"):
            cols = [self.p_yield_col()]
            while self.accept(","):
                cols.append(self.p_yield_col())
        wc = A.WithClauseAst(cols, distinct)
        wc.order_by, wc.skip, wc.limit = self.p_order_skip_limit()
        if self.accept_kw("WHERE"):
            wc.where = self.parse_expr()
        return wc

    def p_return_clause(self) -> A.ReturnClauseAst:
        distinct = bool(self.accept_kw("DISTINCT"))
        cols: Optional[List[A.YieldColumn]] = None
        if self.accept("*"):
            cols = None
        else:
            cols = [self.p_yield_col()]
            while self.accept(","):
                cols.append(self.p_yield_col())
        rc = A.ReturnClauseAst(cols, distinct)
        rc.order_by, rc.skip, rc.limit = self.p_order_skip_limit()
        return rc

    def p_order_skip_limit(self):
        order: List[A.OrderFactor] = []
        skip, limit = 0, -1
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order.append(self.p_order_factor())
            while self.accept(","):
                order.append(self.p_order_factor())
        if self.accept_kw("SKIP"):
            skip = self.expect("INT").value
        if self.accept_kw("LIMIT"):
            limit = self.expect("INT").value
        return order, skip, limit

    # ======================================================================
    # Expressions (Pratt)
    # ======================================================================

    def parse_expr_br(self) -> Expr:
        """parse_expr inside a bracketed context (enables bit-or)."""
        self.bracket += 1
        try:
            return self.parse_expr()
        finally:
            self.bracket -= 1

    def parse_expr_nopipe(self) -> Expr:
        """parse_expr with the bit-or gate OFF — comprehension and
        reduce collections are followed by a STRUCTURAL `|` that the
        operator must not consume even inside parens."""
        saved, self.bracket = self.bracket, 0
        try:
            return self.parse_expr()
        finally:
            self.bracket = saved

    def parse_expr(self) -> Expr:
        return self.p_or()

    def p_or(self) -> Expr:
        left = self.p_and()
        while self.at_kw("OR", "XOR"):
            op = self.next().value
            left = Binary(op, left, self.p_and())
        return left

    def p_and(self) -> Expr:
        left = self.p_not()
        while self.at_kw("AND"):
            self.next()
            left = Binary("AND", left, self.p_not())
        return left

    def p_not(self) -> Expr:
        if self.accept_kw("NOT"):
            return Unary("NOT", self.p_not())
        if self.accept("!"):
            return Unary("NOT", self.p_not())
        return self.p_relational()

    def p_relational(self) -> Expr:
        left = self.p_bitor()
        while True:
            t = self.peek()
            if t.kind in ("==", "!=", "<=", ">=", "=~") or t.kind in ("<", ">"):
                op = self.next().kind
                left = Binary(op, left, self.p_bitor())
            elif self.at_kw("IN"):
                self.next()
                left = Binary("IN", left, self.p_bitor())
            elif self.at_kw("CONTAINS"):
                self.next()
                left = Binary("CONTAINS", left, self.p_bitor())
            elif self.at_kw("STARTS"):
                self.next()
                self.expect_kw("WITH")
                left = Binary("STARTS WITH", left, self.p_bitor())
            elif self.at_kw("ENDS"):
                self.next()
                self.expect_kw("WITH")
                left = Binary("ENDS WITH", left, self.p_bitor())
            elif self.at_kw("NOT"):
                nxt = self.peek(1)
                if nxt.kind == "KEYWORD" and nxt.value in ("IN", "CONTAINS", "STARTS", "ENDS"):
                    self.next()
                    w = self.next().value
                    if w in ("STARTS", "ENDS"):
                        self.expect_kw("WITH")
                        left = Binary(f"NOT {w} WITH", left, self.p_bitor())
                    else:
                        left = Binary(f"NOT {w}", left, self.p_bitor())
                else:
                    break
            elif self.at_kw("IS"):
                self.next()
                neg = bool(self.accept_kw("NOT"))
                which = self.expect_kw("NULL", "EMPTY").value
                op = ("IS_NOT_" if neg else "IS_") + which
                left = Unary(op, left)
            else:
                break
        return left

    def p_bitor(self) -> Expr:
        """Bitwise OR — reference/MySQL precedence (below comparisons,
        above &).  `|` doubles as the statement pipe and the pattern
        type separator, so the operator form only binds inside a
        bracketed context (parens, call args, subscripts, map values) —
        the reference disambiguates the same way in practice."""
        left = self.p_bitand()
        while self.bracket > 0 and self.at("|"):
            self.next()
            left = Binary("|", left, self.p_bitand())
        return left

    def p_bitand(self) -> Expr:
        left = self.p_additive()
        while self.at("&"):
            self.next()
            left = Binary("&", left, self.p_additive())
        return left

    def p_additive(self) -> Expr:
        left = self.p_multiplicative()
        while self.at("+") or self.at("-"):
            op = self.next().kind
            left = Binary(op, left, self.p_multiplicative())
        return left

    def p_multiplicative(self) -> Expr:
        left = self.p_xor()
        while self.at("*") or self.at("/") or self.at("%"):
            op = self.next().kind
            left = Binary(op, left, self.p_xor())
        return left

    def p_xor(self) -> Expr:
        # ^ binds tighter than * (reference/MySQL precedence)
        left = self.p_unary()
        while self.at("^"):
            self.next()
            left = Binary("^", left, self.p_unary())
        return left

    def p_unary(self) -> Expr:
        if self.at("-"):
            self.next()
            return Unary("-", self.p_unary())
        if self.at("+"):
            self.next()
            return Unary("+", self.p_unary())
        return self.p_postfix()

    def p_postfix(self) -> Expr:
        e = self.p_primary()
        while True:
            if self.at("["):
                self.next()
                if self.accept(".."):
                    hi = None if self.at("]") else self.parse_expr_br()
                    self.expect("]")
                    e = Slice(e, None, hi)
                    continue
                idx = self.parse_expr_br()
                if self.accept(".."):
                    hi = None if self.at("]") else self.parse_expr_br()
                    self.expect("]")
                    e = Slice(e, idx, hi)
                else:
                    self.expect("]")
                    e = Subscript(e, idx)
            elif self.at(".") and self.peek(1).kind in ("IDENT", "KEYWORD"):
                self.next()
                e = AttributeExpr(e, self.ident())
            else:
                break
        return e

    def p_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "INT" or t.kind == "FLOAT":
            self.next()
            return Literal(t.value)
        if t.kind == "STRING":
            self.next()
            return Literal(t.value)
        if t.kind == "KEYWORD":
            if t.value == "TRUE":
                self.next()
                return Literal(True)
            if t.value == "FALSE":
                self.next()
                return Literal(False)
            if t.value == "NULL":
                self.next()
                return Literal(NULL)
            if t.value == "CASE":
                return self.p_case()
            if t.value in ("VERTEX", "EDGE") and self.peek(1).kind != "(":
                self.next()
                return VertexExpr("vertex") if t.value == "VERTEX" else EdgeExpr()
            # keyword used as function name or bare identifier — keep the
            # source spelling (a tag named `User`, prop named `role`)
            if self.peek(1).kind == "(":
                return self.p_call(self.next().value.lower())
            self.next()
            return LabelExpr(t.raw or t.value.lower())
        if t.kind == "$-":
            self.next()
            self.expect(".")
            return InputProp(self.ident())
        if t.kind == "$^":
            self.next()
            if self.accept("."):
                tag = self.ident()
                self.expect(".")
                return SrcProp(tag, self.ident())
            return VertexExpr("$^")
        if t.kind == "$$":
            self.next()
            if self.accept("."):
                tag = self.ident()
                self.expect(".")
                return DstProp(tag, self.ident())
            return VertexExpr("$$")
        if t.kind == "VAR":
            self.next()
            if self.at(".") and self.peek(1).kind in ("IDENT", "KEYWORD"):
                self.next()
                return VarProp(t.value, self.ident())
            return VarExpr(t.value)
        if t.kind == "IDENT":
            name = self.next().value
            if self.at("("):
                return self.p_call(name)
            return LabelExpr(name)
        if t.kind == "(":
            # `(a)-[:knows]->(b)` in expression position is a boolean
            # pattern predicate (reference: MatchValidator's
            # PatternExpression [UNVERIFIED — empty mount, SURVEY §0]).
            # Speculative: a parenthesized arithmetic operand like
            # `(a) - [1,2][0]` fails the pattern parse at its first
            # non-pattern token and falls back to the expression read.
            pe = self.try_pattern_pred()
            if pe is not None:
                return pe
            self.next()
            e = self.parse_expr_br()
            self.expect(")")
            return e
        if t.kind == "[":
            return self.p_list_or_comprehension()
        if t.kind == "{":
            self.next()
            items: List[Tuple[str, Expr]] = []
            while not self.accept("}"):
                k = self.ident() if not self.at("STRING") else self.next().value
                self.expect(":")
                items.append((k, self.parse_expr_br()))
                self.accept(",")
            return MapExpr(items)
        if t.kind == "*":
            # COUNT(*) handled in p_call; bare * invalid here
            raise ParseError(f"unexpected `*' at pos {t.pos}")
        raise ParseError(f"unexpected {t.kind}({t.value!r}) at pos {t.pos}")

    def try_pattern_pred(self) -> Optional[Expr]:
        """Attempt `(node)(edge node)+` at the cursor; backtrack and
        return None if it is not a pattern.  A bare `(a)` stays a
        parenthesized expression — a pattern predicate needs >=1 edge."""
        save = self.i
        try:
            pat = A.PathPattern(alias=None)
            pat.nodes.append(self.p_node_pattern())
            if not (self.at("-") or self.at("<-") or self.at("<")):
                raise ParseError("not a pattern")
            while self.at("-") or self.at("<-") or self.at("<"):
                pat.edges.append(self.p_edge_pattern())
                pat.nodes.append(self.p_node_pattern())
        except ParseError:
            self.i = save
            return None
        return PatternPredExpr(pat, A.pattern_text(pat))

    def p_call(self, name: str) -> Expr:
        lname = name.lower()
        self.expect("(")
        if lname in AGG_NAMES:
            if self.accept("*"):
                self.expect(")")
                return AggExpr(lname, None)
            distinct = bool(self.accept_kw("DISTINCT"))
            if self.at(")") and lname == "count":
                self.next()
                return AggExpr("count", None)
            arg = self.parse_expr()
            self.expect(")")
            return AggExpr(lname, arg, distinct)
        if lname in ("all", "any", "single", "none"):
            var = self.ident()
            self.expect_kw("IN")
            coll = self.parse_expr()
            self.expect_kw("WHERE")
            pred = self.parse_expr()
            self.expect(")")
            return PredicateExpr(lname, var, coll, pred)
        if lname == "reduce":
            acc = self.ident()
            self.expect("=")
            init = self.parse_expr()
            self.expect(",")
            var = self.ident()
            self.expect_kw("IN")
            coll = self.parse_expr_nopipe()
            self.expect("|")
            mapping = self.parse_expr()
            self.expect(")")
            return Reduce(acc, init, var, coll, mapping)
        if lname == "exists":
            arg = self.parse_expr()
            self.expect(")")
            if isinstance(arg, PatternPredExpr):
                return arg               # exists((a)-->(b)) ≡ (a)-->(b)
            return FunctionCall("_exists", [arg])
        args: List[Expr] = []
        while not self.accept(")"):
            args.append(self.parse_expr_br())
            self.accept(",")
        return FunctionCall(lname, args)

    def p_case(self) -> Expr:
        self.expect_kw("CASE")
        condition = None
        if not self.at_kw("WHEN"):
            condition = self.parse_expr()
        whens: List[Tuple[Expr, Expr]] = []
        while self.accept_kw("WHEN"):
            w = self.parse_expr()
            self.expect_kw("THEN")
            whens.append((w, self.parse_expr()))
        default = None
        if self.accept_kw("ELSE"):
            default = self.parse_expr()
        self.expect_kw("END")
        return Case(whens, default, condition)

    def p_list_or_comprehension(self) -> Expr:
        self.expect("[")
        if self.accept("]"):
            return ListExpr([])
        # lookahead: IDENT IN → comprehension (the variable may be an
        # unreserved keyword like `user`)
        if (self.peek().kind in ("IDENT", "KEYWORD")
                and self.peek().value not in ("TRUE", "FALSE", "NULL", "CASE")
                and self.peek(1).kind == "KEYWORD"
                and self.peek(1).value == "IN"):
            var = self.ident()
            self.next()  # IN
            coll = self.parse_expr_nopipe()
            where = None
            mapping = None
            if self.accept_kw("WHERE"):
                where = self.parse_expr_nopipe()
            if self.accept("|"):
                mapping = self.parse_expr()
            self.expect("]")
            return ListComprehension(var, coll, where, mapping)
        items = [self.parse_expr()]
        while self.accept(","):
            items.append(self.parse_expr())
        self.expect("]")
        return ListExpr(items)
