"""Validator layer: semantic checks + static type deduction.

The reference validates every sentence BEFORE planning — a Validator
subclass per sentence resolves schema references and runs type deduction
over expressions (DeduceTypeVisitor), so `YIELD 1 + "x"` is a
SemanticError at validation, not a per-row BAD_TYPE at execution
(reference: src/graph/validator + DeduceTypeVisitor [UNVERIFIED — empty
mount, SURVEY §2 row 19]).  Same split here: the engine runs
`validate(stmt, pctx)` between parse and plan; the planner's inline
checks remain as defense in depth.

Deduction is CONSERVATIVE over a small lattice: a type is reported only
when provable from literals, schema property types, and function
signatures; anything data-dependent deduces to UNKNOWN and is admitted
(runtime three-valued semantics take over, exactly like the reference's
Value::Type::__EMPTY__ escape).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ..core import expr as E
from ..graphstore.schema import PropType, SchemaError

UNKNOWN = "unknown"
NUMERIC = {"int", "float"}

# conservative return types for builtins whose result type is fixed
_FN_RETURNS = {
    "abs": UNKNOWN, "floor": "float", "ceil": "float", "sqrt": "float",
    "exp": "float", "log": "float", "log2": "float", "log10": "float",
    "sin": "float", "cos": "float", "tan": "float", "round": "float",
    "radians": "float", "degrees": "float",
    "size": "int", "length": "int", "rank": "int", "typeid": "int",
    "hash": "int", "tointeger": "int", "toint": "int",
    "tofloat": "float", "toboolean": "bool", "tostring": "string",
    "lower": "string", "upper": "string", "tolower": "string",
    "toupper": "string", "trim": "string", "ltrim": "string",
    "rtrim": "string", "substr": "string", "substring": "string",
    "left": "string", "right": "string", "replace": "string",
    "concat": "string", "type": "string", "md5": "string",
    "sha1": "string", "sha256": "string",
    "split": "list", "keys": "list", "labels": "list", "tags": "list",
    "nodes": "list", "relationships": "list", "range": "list",
    "st_distance": "float", "st_x": "float", "st_y": "float",
    "st_astext": "string", "st_dwithin": "bool", "st_intersects": "bool",
    "st_covers": "bool", "st_coveredby": "bool", "st_isvalid": "bool",
}

_PT_KIND = {
    PropType.BOOL: "bool", PropType.FLOAT: "float",
    PropType.DOUBLE: "float", PropType.STRING: "string",
    PropType.FIXED_STRING: "string", PropType.DATE: "date",
    PropType.TIME: "time", PropType.DATETIME: "datetime",
    PropType.DURATION: "duration", PropType.GEOGRAPHY: "geography",
}


class ValidationError(Exception):
    pass


class Scope:
    """What names mean inside the statement being validated."""

    def __init__(self, pctx, edge_types=None, match_aliases=None):
        self.pctx = pctx
        self.edge_types = set(edge_types or ())
        self.match_aliases = dict(match_aliases or {})


def _lit_type(v: Any) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float"
    if isinstance(v, str):
        return "string"
    if v is None:
        return UNKNOWN
    return UNKNOWN


def deduce(e: E.Expr, scope: Scope) -> str:
    """Static type of `e`, or UNKNOWN when not provable."""
    k = e.kind
    if k == "literal":
        return _lit_type(e.value)
    if k in ("list", "set"):
        for item in e.items:
            deduce(item, scope)
        return "list" if k == "list" else "set"
    if k == "map":
        for _, item in e.items:
            deduce(item, scope)
        return "map"
    if k == "edge_prop":
        return _edge_prop_type(e.edge, e.name, scope)
    if k == "attribute":
        # raw parse of `etype.prop` in a GO WHERE: attribute-of-label
        # (the planner canonicalizes later; deduce from schema now)
        if isinstance(e.obj, E.LabelExpr) and e.obj.name in scope.edge_types:
            return _edge_prop_type(e.obj.name, e.attr, scope)
        if isinstance(e.obj, E.Expr):
            deduce(e.obj, scope)
        return UNKNOWN
    if k in ("src_prop", "dst_prop"):
        return _tag_prop_type(e.tag, e.name, scope)
    if k == "unary":
        t = deduce(e.operand, scope)
        if e.op in ("IS_NULL", "IS_NOT_NULL", "IS_EMPTY", "IS_NOT_EMPTY"):
            return "bool"
        if e.op == "NOT":
            if t not in (UNKNOWN, "bool"):
                raise ValidationError(f"NOT over {t}")
            return "bool"
        if e.op in ("-", "+"):
            if t not in (UNKNOWN, "int", "float"):
                raise ValidationError(f"unary {e.op} over {t}")
            return t
        return UNKNOWN
    if k == "binary":
        return _binary_type(e, scope)
    if k == "function":
        for a in e.args:
            deduce(a, scope)
        if e.name.lower() in ("coalesce", "head", "last"):
            return UNKNOWN
        return _FN_RETURNS.get(e.name.lower(), UNKNOWN)
    if k == "aggregate":
        if e.arg is not None:
            deduce(e.arg, scope)
        if e.func in ("count",):
            return "int"
        if e.func in ("avg", "std"):
            return "float"
        if e.func in ("collect", "collect_set"):
            return "list"
        return UNKNOWN
    if k == "case":
        if e.condition is not None:
            deduce(e.condition, scope)
        outs = set()
        for w, t in e.whens:
            wt = deduce(w, scope)
            if e.condition is None and wt not in (UNKNOWN, "bool"):
                raise ValidationError(f"CASE WHEN condition is {wt}")
            outs.add(deduce(t, scope))
        if e.default is not None:
            outs.add(deduce(e.default, scope))
        return outs.pop() if len(outs) == 1 else UNKNOWN
    if k in ("subscript", "slice"):
        deduce(e.obj, scope)
        return UNKNOWN
    if k in ("list_comprehension", "predicate", "reduce"):
        return ("list" if k == "list_comprehension"
                else "bool" if k == "predicate" else UNKNOWN)
    return UNKNOWN


def _binary_type(e, scope: Scope) -> str:
    lt, rt = deduce(e.lhs, scope), deduce(e.rhs, scope)
    op = e.op
    if op in ("AND", "OR", "XOR"):
        for t in (lt, rt):
            if t not in (UNKNOWN, "bool"):
                raise ValidationError(f"{op} over {t}")
        return "bool"
    if op in ("==", "!=", "IS", "IS NOT"):
        return "bool"
    if op in ("<", "<=", ">", ">="):
        if UNKNOWN not in (lt, rt) and lt != rt \
                and not (lt in NUMERIC and rt in NUMERIC):
            raise ValidationError(f"comparison {lt} {op} {rt}")
        return "bool"
    if op in ("IN", "NOT IN", "CONTAINS", "NOT CONTAINS",
              "STARTS WITH", "ENDS WITH", "NOT STARTS WITH",
              "NOT ENDS WITH", "=~"):
        return "bool"
    if op in ("+",):
        if UNKNOWN in (lt, rt):
            return UNKNOWN
        if lt == "string" and rt == "string":
            return "string"
        if lt in NUMERIC and rt in NUMERIC:
            return "float" if "float" in (lt, rt) else "int"
        if lt == "list" or rt == "list":
            return "list"
        if {lt, rt} & {"date", "time", "datetime", "duration"}:
            return UNKNOWN          # temporal arithmetic: runtime rules
        raise ValidationError(f"`+' over {lt} and {rt}")
    if op in ("-", "*", "/", "%"):
        for t in (lt, rt):
            if t not in (UNKNOWN, "int", "float", "duration", "date",
                         "time", "datetime"):
                raise ValidationError(f"`{op}' over {t}")
        if lt in NUMERIC and rt in NUMERIC:
            return "float" if "float" in (lt, rt) else "int"
        return UNKNOWN
    return UNKNOWN


def _edge_prop_type(edge: Optional[str], name: str, scope: Scope) -> str:
    if name.startswith("_"):
        return {"_rank": "int", "_type": "string"}.get(name, UNKNOWN)
    pctx = scope.pctx
    if not pctx.space or edge in (None, "__edge__"):
        return UNKNOWN
    try:
        sv = pctx.catalog.get_edge(pctx.space, edge).latest
    except SchemaError:
        return UNKNOWN          # planner raises the schema error itself
    pd = sv.prop(name)
    if pd is None:
        raise ValidationError(f"edge `{edge}' has no property `{name}'")
    return _PT_KIND.get(pd.ptype, "int")


def _tag_prop_type(tag: str, name: str, scope: Scope) -> str:
    pctx = scope.pctx
    if not pctx.space:
        return UNKNOWN
    try:
        sv = pctx.catalog.get_tag(pctx.space, tag).latest
    except SchemaError:
        return UNKNOWN
    pd = sv.prop(name)
    if pd is None:
        raise ValidationError(f"tag `{tag}' has no property `{name}'")
    return _PT_KIND.get(pd.ptype, "int")


# ---------------------------------------------------------------------------
# per-statement validators (reference: one Validator subclass per
# sentence — GoValidator, MatchValidator, ... [UNVERIFIED — empty mount,
# SURVEY §2 row 19]).  Each entry checks the STRUCTURAL semantics of its
# sentence (step ranges, schema references, prop-name conformance)
# before the generic expression type deduction runs.  Registered by
# sentence class; statements without an entry only get type deduction.
# ---------------------------------------------------------------------------

_SENTENCE_VALIDATORS: Dict[type, Any] = {}


def _svalidator(cls):
    def deco(fn):
        _SENTENCE_VALIDATORS[cls] = fn
        return fn
    return deco


def _has_edge(pctx, name: str) -> bool:
    try:
        pctx.catalog.get_edge(pctx.space, name)
        return True
    except SchemaError:
        return False


def _has_tag(pctx, name: str) -> bool:
    try:
        pctx.catalog.get_tag(pctx.space, name)
        return True
    except SchemaError:
        return False


def _check_steps(m, n):
    if m is not None and m < 0:
        raise ValidationError(f"step number {m} is negative")
    if m is not None and n is not None and n < m:
        raise ValidationError(
            f"upper bound steps {n} must be greater than or equal to "
            f"lower bound {m}")


def _register_sentence_validators():
    from . import ast as A

    @_svalidator(A.GoSentence)
    def v_go(stmt, pctx):
        if stmt.steps is not None:
            _check_steps(stmt.steps.m, stmt.steps.n)
        if pctx.space and stmt.over is not None and not stmt.over.is_all:
            for et in stmt.over.edges or ():
                if not _has_edge(pctx, et):
                    raise ValidationError(f"edge `{et}' not found")

    @_svalidator(A.FetchVerticesSentence)
    def v_fetch_v(stmt, pctx):
        if not pctx.space:
            return
        for t in stmt.tags:
            if t != "*" and not _has_tag(pctx, t):
                raise ValidationError(f"tag `{t}' not found")

    @_svalidator(A.FetchEdgesSentence)
    def v_fetch_e(stmt, pctx):
        if pctx.space and not _has_edge(pctx, stmt.etype):
            raise ValidationError(f"edge `{stmt.etype}' not found")

    @_svalidator(A.LookupSentence)
    def v_lookup(stmt, pctx):
        if pctx.space and not (_has_tag(pctx, stmt.schema_name)
                               or _has_edge(pctx, stmt.schema_name)):
            raise ValidationError(
                f"schema `{stmt.schema_name}' not found")

    @_svalidator(A.FindPathSentence)
    def v_find_path(stmt, pctx):
        if stmt.upto is not None and stmt.upto < 0:
            raise ValidationError(
                f"UPTO {stmt.upto} STEPS is negative")
        if pctx.space and stmt.over is not None and not stmt.over.is_all:
            for et in stmt.over.edges or ():
                if not _has_edge(pctx, et):
                    raise ValidationError(f"edge `{et}' not found")

    @_svalidator(A.CallAlgoSentence)
    def v_call_algo(stmt, pctx):
        # the registry is import-light on purpose (no jax): the
        # validator statically vets module/func/params/yields before
        # any engine machinery is touched
        from ..algo import validate_call
        if stmt.module != "algo":
            raise ValidationError(
                f"unknown procedure module `{stmt.module}' "
                f"(only `algo' is served)")
        ynames = []
        if stmt.yield_ is not None:
            for c in stmt.yield_.columns:
                if c.expr.kind != "label":
                    raise ValidationError(
                        "CALL ... YIELD takes bare output column "
                        "names (optionally aliased with AS)")
                ynames.append(c.expr.name)
        try:
            validate_call(stmt.func, list(stmt.params), ynames)
        except ValueError as ex:
            raise ValidationError(str(ex)) from None
        for name, e in stmt.params.items():
            try:
                e.eval(E.DictContext())
            except Exception:  # noqa: BLE001 — non-constant param
                raise ValidationError(
                    f"parameter `{name}' must be a constant "
                    f"expression") from None
        et = stmt.params.get("edge_types")
        if et is not None and pctx.space:
            try:
                v = et.eval(E.DictContext())
            except Exception:  # noqa: BLE001 — reported above
                v = None
            names = [v] if isinstance(v, str) else \
                (v if isinstance(v, list) else [])
            for n in names:
                if isinstance(n, str) and not _has_edge(pctx, n):
                    raise ValidationError(f"edge `{n}' not found")

    @_svalidator(A.SubgraphSentence)
    def v_subgraph(stmt, pctx):
        if stmt.steps is not None and stmt.steps < 0:
            raise ValidationError(f"step number {stmt.steps} is negative")
        if pctx.space:
            for et in (tuple(stmt.in_edges or ())
                       + tuple(stmt.out_edges or ())
                       + tuple(stmt.both_edges or ())):
                if et != "*" and not _has_edge(pctx, et):
                    raise ValidationError(f"edge `{et}' not found")

    @_svalidator(A.MatchSentence)
    def v_match(stmt, pctx):
        # Pattern predicates `WHERE (a)-[:e]->()` are legal only in a
        # MATCH clause's WHERE; their patterns get the same structural
        # checks as inline patterns.  Anywhere else (WITH WHERE, RETURN
        # columns) they are a semantic error (reference: MatchValidator
        # rejects PatternExpression outside a filter [UNVERIFIED —
        # empty mount, SURVEY §0]).
        def preds_in(e):
            return [x for x in E.walk(e) if x.kind == "pattern_pred"] \
                if e is not None else []

        def screen(e):
            if preds_in(e):
                raise ValidationError(
                    "pattern predicate is only supported in a MATCH "
                    "WHERE clause")

        def screen_proj(cl):
            for c in getattr(cl, "columns", None) or []:
                screen(c.expr)
            for f in getattr(cl, "order_by", None) or []:
                screen(f.expr)

        for cl in getattr(stmt, "clauses", ()) or ():
            if isinstance(cl, A.MatchClauseAst):
                continue
            if isinstance(cl, A.UnwindClauseAst):
                screen(cl.expr)
                continue
            screen(getattr(cl, "where", None))
            screen_proj(cl)
        ret = getattr(stmt, "return_", None)
        if ret is not None:
            screen_proj(ret)
        for cl in getattr(stmt, "clauses", ()) or ():
            pat = list(getattr(cl, "patterns", None) or ())
            for pe in preds_in(getattr(cl, "where", None)):
                pat.append(pe.pattern)
            for pp in pat or ():
                for ep in getattr(pp, "edges", ()) or ():
                    if ep.min_hop < 0:
                        raise ValidationError(
                            f"hop lower bound {ep.min_hop} is negative")
                    if ep.max_hop != -1 and ep.max_hop < ep.min_hop:
                        raise ValidationError(
                            f"hop upper bound {ep.max_hop} must be "
                            f">= lower bound {ep.min_hop}")
                    if pctx.space:
                        for et in ep.types or ():
                            if not _has_edge(pctx, et):
                                raise ValidationError(
                                    f"edge `{et}' not found")
                for np_ in getattr(pp, "nodes", ()) or ():
                    if pctx.space:
                        for lb, _props in np_.labels or ():
                            if not _has_tag(pctx, lb):
                                raise ValidationError(
                                    f"tag `{lb}' not found")

    @_svalidator(A.InsertVerticesSentence)
    def v_insert_v(stmt, pctx):
        for row in stmt.rows:
            if len(row.values) != len(stmt.prop_names):
                raise ValidationError(
                    f"vertex row has {len(row.values)} values for "
                    f"{len(stmt.prop_names)} properties")
        seen_tags = set()
        seen_props = set()
        for tag, names in stmt.tags:
            if tag in seen_tags:
                raise ValidationError(f"duplicate tag `{tag}'")
            seen_tags.add(tag)
            for pn in names:
                if (tag, pn) in seen_props:
                    raise ValidationError(
                        f"duplicate property `{pn}' on tag `{tag}'")
                seen_props.add((tag, pn))
        if not pctx.space:
            return
        for tag, names in stmt.tags:
            if not _has_tag(pctx, tag):
                raise ValidationError(f"tag `{tag}' not found")
            sv = pctx.catalog.get_tag(pctx.space, tag).latest
            have = {p.name for p in sv.props}
            for pn in names:
                if pn not in have:
                    raise ValidationError(
                        f"tag `{tag}' has no property `{pn}'")

    @_svalidator(A.InsertEdgesSentence)
    def v_insert_e(stmt, pctx):
        for row in stmt.rows:
            if len(row.values) != len(stmt.prop_names):
                raise ValidationError(
                    f"edge row has {len(row.values)} values for "
                    f"{len(stmt.prop_names)} properties")
        if not pctx.space:
            return
        if not _has_edge(pctx, stmt.etype):
            raise ValidationError(f"edge `{stmt.etype}' not found")
        sv = pctx.catalog.get_edge(pctx.space, stmt.etype).latest
        have = {p.name for p in sv.props}
        for pn in stmt.prop_names:
            if pn not in have:
                raise ValidationError(
                    f"edge `{stmt.etype}' has no property `{pn}'")

    @_svalidator(A.UpdateSentence)
    def v_update(stmt, pctx):
        if not pctx.space:
            return
        get = _has_edge if stmt.is_edge else _has_tag
        if not get(pctx, stmt.schema_name):
            kind = "edge" if stmt.is_edge else "tag"
            raise ValidationError(
                f"{kind} `{stmt.schema_name}' not found")
        getter = (pctx.catalog.get_edge if stmt.is_edge
                  else pctx.catalog.get_tag)
        sv = getter(pctx.space, stmt.schema_name).latest
        have = {p.name for p in sv.props}
        for pn, _e in stmt.sets:
            if pn not in have:
                raise ValidationError(
                    f"`{stmt.schema_name}' has no property `{pn}'")

    @_svalidator(A.CreateSchemaSentence)
    def v_create_schema(stmt, pctx):
        seen = set()
        for p in stmt.props:
            if p.name in seen:
                raise ValidationError(
                    f"duplicate property `{p.name}'")
            seen.add(p.name)
        if stmt.ttl_col:
            pd = next((p for p in stmt.props if p.name == stmt.ttl_col),
                      None)
            if pd is None:
                raise ValidationError(
                    f"TTL column `{stmt.ttl_col}' does not exist")
            if pd.type_name.upper() not in ("INT", "INT64", "TIMESTAMP"):
                raise ValidationError(
                    f"TTL column `{stmt.ttl_col}' must be "
                    f"int/timestamp typed")

    @_svalidator(A.CreateIndexSentence)
    def v_create_index(stmt, pctx):
        if len(set(stmt.fields)) != len(stmt.fields):
            raise ValidationError("duplicate index field")
        if not pctx.space:
            return
        get = _has_edge if stmt.is_edge else _has_tag
        if not get(pctx, stmt.schema_name):
            kind = "edge" if stmt.is_edge else "tag"
            raise ValidationError(
                f"{kind} `{stmt.schema_name}' not found")
        getter = (pctx.catalog.get_edge if stmt.is_edge
                  else pctx.catalog.get_tag)
        sv = getter(pctx.space, stmt.schema_name).latest
        have = {p.name for p in sv.props}
        for f in stmt.fields:
            if f not in have:
                raise ValidationError(
                    f"`{stmt.schema_name}' has no property `{f}'")

    @_svalidator(A.LimitSentence)
    def v_limit(stmt, pctx):
        if stmt.count is not None and stmt.count < 0:
            raise ValidationError("LIMIT count is negative")
        if getattr(stmt, "offset", None) is not None and stmt.offset < 0:
            raise ValidationError("LIMIT offset is negative")

    @_svalidator(A.DeleteTagsSentence)
    def v_delete_tags(stmt, pctx):
        if not pctx.space:
            return
        for t in stmt.tags:
            if not _has_tag(pctx, t):
                raise ValidationError(f"tag `{t}' not found")

    _GRANTABLE = ("ADMIN", "DBA", "USER", "GUEST")

    def v_role(stmt, pctx):
        r = (stmt.role or "").upper()
        if r == "GOD":
            raise ValidationError(
                "GOD role can not be granted or revoked")
        if r not in _GRANTABLE:
            raise ValidationError(
                f"role `{stmt.role}' does not exist "
                f"(one of {', '.join(_GRANTABLE)})")

    _SENTENCE_VALIDATORS[A.GrantRoleSentence] = v_role
    _SENTENCE_VALIDATORS[A.RevokeRoleSentence] = v_role

    @_svalidator(A.AlterSchemaSentence)
    def v_alter_schema(stmt, pctx):
        """ALTER TAG/EDGE op conformance: DROP/CHANGE name an existing
        property, ADD a new one, TTL column int/timestamp-typed and
        present after the alter (reference: AlterSchema validators)."""
        if not pctx.space:
            return
        getter = (pctx.catalog.get_edge if stmt.is_edge
                  else pctx.catalog.get_tag)
        try:
            sv = getter(pctx.space, stmt.name).latest
        except SchemaError:
            kind = "edge" if stmt.is_edge else "tag"
            raise ValidationError(f"{kind} `{stmt.name}' not found")
        have = {p.name for p in sv.props}
        for n in stmt.drops:
            if n not in have:
                raise ValidationError(
                    f"`{stmt.name}' has no property `{n}' to drop")
            if sv.ttl_col and n == sv.ttl_col and not stmt.ttl_col:
                raise ValidationError(
                    f"`{n}' is the TTL column of `{stmt.name}' — "
                    f"reset TTL_COL before dropping it")
        for p in stmt.changes:
            if p.name not in have:
                raise ValidationError(
                    f"`{stmt.name}' has no property `{p.name}' to change")
        dropped = set(stmt.drops)
        for p in stmt.adds:
            if p.name in have and p.name not in dropped:
                raise ValidationError(
                    f"property `{p.name}' already exists on "
                    f"`{stmt.name}'")
        if stmt.ttl_col:
            # catalog PropDefs carry a PropType enum; AST prop defs a
            # type_name string — normalize both to the spelled type
            after = {p.name: p.ptype.value for p in sv.props
                     if p.name not in dropped}
            after.update({p.name: p.type_name
                          for p in list(stmt.adds) + list(stmt.changes)})
            tn = after.get(stmt.ttl_col)
            if tn is None:
                raise ValidationError(
                    f"TTL column `{stmt.ttl_col}' does not exist")
            if tn.upper() not in ("INT", "INT64", "TIMESTAMP"):
                raise ValidationError(
                    f"TTL column `{stmt.ttl_col}' must be "
                    f"int/timestamp typed")

    @_svalidator(A.DropSchemaSentence)
    def v_drop_schema(stmt, pctx):
        """Reference semantics: a schema with a live index can not be
        dropped — the index must go first."""
        if not pctx.space:
            return
        get = _has_edge if stmt.is_edge else _has_tag
        if not get(pctx, stmt.name):
            return               # IF EXISTS handling stays downstream
        related = list(pctx.catalog.indexes_for(pctx.space, stmt.name,
                                                stmt.is_edge))
        related += list(pctx.catalog.fulltext_indexes_for(
            pctx.space, stmt.name, stmt.is_edge))
        if related:
            kind = "edge" if stmt.is_edge else "tag"
            raise ValidationError(
                f"{kind} `{stmt.name}' has index "
                f"`{related[0].name}' — drop the index first")


_register_sentence_validators()


# ---------------------------------------------------------------------------
# sentence-level validation
# ---------------------------------------------------------------------------


def _exprs_of(stmt) -> list:
    """Expressions a sentence carries, by sentence shape (yield/where)."""
    from . import ast as A
    out = []
    where = getattr(stmt, "where", None)
    if where is not None:
        cond = getattr(where, "filter", where)
        if isinstance(cond, E.Expr):
            out.append(("where", cond))
    yld = getattr(stmt, "yield_", None)
    if yld is not None:
        for c in getattr(yld, "columns", []) or []:
            out.append(("yield", c.expr))
    return out


def validate(stmt, pctx) -> None:
    """Type-deduce every expression the sentence carries; raise
    ValidationError on provable type errors.  Composition sentences
    recurse; statements the deducer has no model for pass through."""
    from . import ast as A
    if isinstance(stmt, A.SeqSentence):
        for sub in stmt.stmts:
            validate(sub, pctx)
        return
    if isinstance(stmt, (A.PipedSentence, A.SetOpSentence)):
        validate(stmt.left, pctx)
        # the right side of a pipe reads $-.cols whose types come from
        # the left's output — deducible only to UNKNOWN; still validate
        # its literal/schema-typed subtrees
        validate(stmt.right, pctx)
        return
    if isinstance(stmt, A.ExplainSentence):
        validate(stmt.stmt, pctx)
        return
    if isinstance(stmt, A.AssignSentence):
        validate(stmt.stmt, pctx)
        return

    sv = _SENTENCE_VALIDATORS.get(type(stmt))
    if sv is not None:
        try:
            sv(stmt, pctx)
        except ValidationError:
            raise
        except Exception:  # noqa: BLE001 — structural checks never block
            pass

    edge_types = ()
    if isinstance(stmt, A.GoSentence) and stmt.over is not None:
        edge_types = tuple(stmt.over.edges or ())
    scope = Scope(pctx, edge_types=edge_types)
    for (_where, ex) in _exprs_of(stmt):
        if not isinstance(stmt, A.MatchSentence) and any(
                x.kind == "pattern_pred" for x in E.walk(ex)):
            raise ValidationError(
                "pattern predicate is only supported in a MATCH "
                "WHERE clause")
        try:
            deduce(ex, scope)
        except ValidationError:
            raise
        except Exception:  # noqa: BLE001 — deduction must never block
            return
