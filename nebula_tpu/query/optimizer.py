"""Rule-based optimizer over the plan DAG.

Analog of the reference's memo-based RBO (reference: src/graph/optimizer,
~50 rules [UNVERIFIED — empty mount, SURVEY §0]).  Python plans are small
trees, so instead of an OptGroup memo we run bottom-up rewrite rules to a
fixpoint.  The rule set mirrors the reference's pushdown family; the TPU
fusion rule (`TpuTraverseRule`) registers itself from nebula_tpu.tpu at
import time — a new rule here is exactly where the TPU rewrite plugs in.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from ..core.expr import (Binary, Expr, InputProp, join_conjuncts,
                         split_conjuncts, walk)
from .plan import ExecutionPlan, PlanNode, transform_plan, walk_plan

Rule = Callable[[PlanNode], Optional[PlanNode]]

RULES: List[Rule] = []

# TPU fusion rule factories: each is called per-pass with a {node_id:
# parent_count} map and returns a Rule.  Populated by nebula_tpu.tpu
# (kept here so query/ has no jax dependency).
TPU_RULES: List = []


def register_rule(fn: Rule) -> Rule:
    RULES.append(fn)
    return fn


def optimize(plan: ExecutionPlan, enable: bool = True,
             tpu: bool = False) -> ExecutionPlan:
    if not enable:
        return plan
    # When a rule replaces a node with one of its children, any by-name
    # reference to the removed node's output_var (e.g. Argument.from_var)
    # must be re-pointed at the survivor.
    var_alias = {}
    for _ in range(8):  # fixpoint with a safety bound
        changed = [False]

        def apply_once(node: PlanNode) -> Optional[PlanNode]:
            for rule in RULES:
                r = rule(node)
                if r is not None:
                    changed[0] = True
                    if r.output_var != node.output_var:
                        var_alias[node.output_var] = r.output_var
                    return r
            return None

        plan.root = transform_plan(plan.root, apply_once)
        if not changed[0]:
            break
    if tpu and TPU_RULES:
        # Fusion pass after pushdowns.  TOP-down (outermost node first) so a
        # whole N-step frontier chain fuses as one unit — bottom-up would
        # fuse the 1-step chain head and break the outer match.  Rules get
        # parent counts to refuse fusing chains other branches reference.
        uses: dict = {}
        for n in walk_plan(plan.root):
            for d in n.deps:
                uses[d.id] = uses.get(d.id, 0) + 1
        rules = [factory(uses) for factory in TPU_RULES]
        memo: dict = {}

        def rec(node: PlanNode) -> PlanNode:
            if node.id in memo:
                return memo[node.id]
            for rule in rules:
                r = rule(node)
                if r is not None:
                    if r.output_var != node.output_var:
                        var_alias[node.output_var] = r.output_var
                    memo[node.id] = r
                    return r
            memo[node.id] = node        # pre-seed: cycles impossible in DAG
            new_deps = [rec(d) for d in node.deps]
            if new_deps != node.deps:
                node.deps = new_deps
                node.input_vars = [d.output_var for d in new_deps]
            return node

        plan.root = rec(plan.root)
    if var_alias:
        # Only references to nodes that actually LEFT the plan may be
        # re-pointed.  Swap rules (e.g. Limit(Project) → Project(Limit))
        # alias the old root to the new one, but BOTH nodes survive —
        # rewriting the new root's own input would self-loop it.
        live = {n.output_var for n in walk_plan(plan.root)}

        def resolve(v):
            seen = set()
            while v not in live and v in var_alias and v not in seen:
                seen.add(v)
                v = var_alias[v]
            return v
        for n in walk_plan(plan.root):
            if "from_var" in n.args:
                n.args["from_var"] = resolve(n.args["from_var"])
            n.input_vars = [resolve(v) for v in n.input_vars]
    return plan


# ---------------------------------------------------------------------------
# Rules (reference analogs noted per rule)
# ---------------------------------------------------------------------------


def _refs_only(e: Expr, kinds: tuple) -> bool:
    leaf_kinds = ("literal", "list", "set", "map") + kinds
    for x in walk(e):
        if x.kind in ("src_prop", "edge_prop", "dst_prop", "input_prop",
                      "var", "var_prop", "label", "label_tag_prop",
                      "vertex", "edge", "attribute"):
            if x.kind not in kinds:
                return False
    return True


@register_rule
def push_filter_down_expand(node: PlanNode) -> Optional[PlanNode]:
    """Filter(ExpandAll) → ExpandAll{edge_filter} for conjuncts that only
    touch edge props / src props (reference: PushFilterDownGetNbrsRule)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "ExpandAll":
        return None
    exp = node.dep()
    cond = node.args.get("condition")
    if cond is None:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        if _refs_only(c, ("edge_prop", "src_prop")):
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = exp.args.get("edge_filter")
    allp = ([prev] if prev is not None else []) + pushable
    exp.args["edge_filter"] = join_conjuncts(allp)
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None  # keep the (reduced) filter
    return exp  # filter fully absorbed


@register_rule
def push_filter_down_traverse(node: PlanNode) -> Optional[PlanNode]:
    """Filter(AppendVertices(Traverse)) edge-only conjuncts → Traverse
    (reference: PushFilterDownTraverseRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    av = node.dep()
    if av.kind != "AppendVertices" or not av.deps or av.dep().kind != "Traverse":
        return None
    tv = av.dep()
    cond = node.args.get("condition")
    if cond is None:
        return None
    edge_alias = tv.args.get("edge_alias")
    if tv.args.get("min_hop") != 1 or tv.args.get("max_hop") != 1:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        refs = [x for x in walk(c)
                if x.kind in ("label", "label_tag_prop", "attribute",
                              "input_prop", "var", "var_prop")]
        names = set()
        for r in refs:
            if r.kind == "label":
                names.add(r.name)
            elif r.kind == "label_tag_prop":
                names.add(r.var)
            elif r.kind == "attribute":
                o = r.obj
                while o.kind == "attribute":
                    o = o.obj
                if o.kind == "label":
                    names.add(o.name)
                else:
                    names.add("__other__")
            else:
                names.add("__other__")
        if names and names <= {edge_alias}:
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = tv.args.get("edge_filter")
    tv.args["edge_filter"] = join_conjuncts(
        ([prev] if prev is not None else []) + pushable)
    tv.args["edge_filter_alias"] = edge_alias
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return av


@register_rule
def push_limit_down_expand(node: PlanNode) -> Optional[PlanNode]:
    """Limit(ExpandAll) → ExpandAll{limit} (reference: PushLimitDownGetNeighborsRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "ExpandAll":
        return None
    exp = node.dep()
    if node.args.get("offset"):
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    if exp.args.get("limit") is not None:
        return None
    exp.args["limit"] = cnt
    return None  # keep Limit for exactness; Expand just over-produces less


@register_rule
def collapse_project(node: PlanNode) -> Optional[PlanNode]:
    """Project(Project(x)) where the outer only renames InputProp columns
    (reference: CollapseProjectRule)."""
    if node.kind != "Project" or not node.deps or node.dep().kind != "Project":
        return None
    inner = node.dep()
    if node.args.get("go_row") or node.args.get("match_row") or \
       inner.args.get("go_row") or inner.args.get("match_row"):
        return None
    inner_map = {n: e for e, n in inner.args.get("columns", [])}
    new_cols = []
    for e, n in node.args.get("columns", []):
        if isinstance(e, InputProp) and e.name in inner_map:
            new_cols.append((inner_map[e.name], n))
        else:
            return None
    node.args["columns"] = new_cols
    # the substituted expressions came from the inner project — they need
    # its evaluation context (lookup_row/fetch_row resolve Tag.prop etc.
    # against the scanned entity, not plain input columns)
    for flag in ("lookup_row", "fetch_row", "schema", "is_edge"):
        if flag in inner.args:
            node.args[flag] = inner.args[flag]
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


@register_rule
def merge_sort_limit_to_topn(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Sort(x)) → TopN (reference: TopNRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Sort":
        return None
    srt = node.dep()
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    return PlanNode("TopN", deps=list(srt.deps),
                    col_names=list(node.col_names),
                    args={"factors": srt.args["factors"],
                          "offset": node.args.get("offset", 0),
                          "count": cnt,
                          "match_row": srt.args.get("match_row", False)})


@register_rule
def dedup_before_expand(node: PlanNode) -> Optional[PlanNode]:
    """ExpandAll fed by a Project of dsts without Dedup gains dedup_src
    (reference: the GetDstBySrc dedup optimization)."""
    if node.kind != "ExpandAll" or not node.deps:
        return None
    d = node.dep()
    if d.kind == "Dedup":
        node.args["dedup_input"] = True
    return None


def _col_refs(e: Expr) -> Optional[set]:
    """Column names a predicate reads, or None if it touches anything
    that is not a plain column reference (then it can't be re-homed)."""
    names = set()
    for x in walk(e):
        if x.kind in ("input_prop", "var"):
            names.add(x.name)
        elif x.kind == "label":
            names.add(x.name)
        elif x.kind == "var_prop":
            names.add(x.var)
        elif x.kind == "label_tag_prop":
            names.add(x.var)
        elif x.kind in ("src_prop", "edge_prop", "dst_prop", "vertex",
                        "edge"):
            return None
    return names


@register_rule
def merge_adjacent_filters(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Filter(x)) → Filter(x) with the conjunction (reference:
    CombineFilterRule)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "Filter":
        return None
    inner = node.dep()
    a, b = node.args.get("condition"), inner.args.get("condition")
    if a is None or b is None:
        return None
    if node.args.get("match_row") != inner.args.get("match_row"):
        return None
    node.args["condition"] = join_conjuncts([b, a])
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


@register_rule
def eliminate_true_filter(node: PlanNode) -> Optional[PlanNode]:
    """Filter(cond=true) → child (reference: the constant-fold/remove
    family)."""
    if node.kind != "Filter" or not node.deps:
        return None
    cond = node.args.get("condition")
    if cond is not None and cond.kind == "literal" and cond.value is True:
        return node.dep()
    return None


@register_rule
def eliminate_false_filter(node: PlanNode) -> Optional[PlanNode]:
    """Filter(cond=false|null) → empty result (reference: the
    degenerate-plan constant-fold family).  A constant-false predicate
    can skip the whole subtree — the columns survive, the rows never
    materialize."""
    if node.kind != "Filter" or not node.deps:
        return None
    cond = node.args.get("condition")
    if cond is None or cond.kind != "literal":
        return None
    from ..core.value import is_null
    if cond.value is False or (is_null(cond.value)
                               and not isinstance(cond.value, bool)):
        return PlanNode("Project", deps=[],
                        col_names=list(node.col_names),
                        args={"empty": True})
    return None


@register_rule
def merge_adjacent_limits(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Limit(x)) → one Limit (reference: MergeGetNbrsAndDedupRule
    sibling cleanups).  rows[o2:o2+c2][o1:o1+c1] = rows[o1+o2 : ...]."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Limit":
        return None
    inner = node.dep()
    o1, c1 = node.args.get("offset", 0) or 0, node.args.get("count", -1)
    o2, c2 = inner.args.get("offset", 0) or 0, inner.args.get("count", -1)
    if c2 is None or c2 < 0:
        cnt = c1
    else:
        avail = max(0, c2 - o1)
        cnt = avail if c1 is None or c1 < 0 else min(c1, avail)
    node.args["offset"] = o1 + o2
    node.args["count"] = cnt
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


# NOTE deliberately ABSENT: a Sort(Sort(x)) → Sort(x) collapse.  The
# engine's Sort is stable, so the inner sort is observable through ties
# of the outer keys — collapsing changes row order for equal keys.

@register_rule
def eliminate_limit_zero(node: PlanNode) -> Optional[PlanNode]:
    """Limit(count=0) → empty result: the subtree can't contribute rows
    (reference: the degenerate-plan prune family)."""
    if node.kind != "Limit" or not node.deps:
        return None
    if node.args.get("count") == 0:
        return PlanNode("Project", deps=[],
                        col_names=list(node.col_names),
                        args={"empty": True})
    return None


@register_rule
def eliminate_noop_limit(node: PlanNode) -> Optional[PlanNode]:
    """Limit(offset=0, count=unbounded) → child."""
    if node.kind != "Limit" or not node.deps:
        return None
    cnt = node.args.get("count", -1)
    off = node.args.get("offset", 0) or 0
    if off == 0 and (cnt is None or cnt < 0):
        return node.dep()
    return None


@register_rule
def collapse_dedup(node: PlanNode) -> Optional[PlanNode]:
    """Dedup(Dedup(x)) → Dedup(x)."""
    if node.kind != "Dedup" or not node.deps or node.dep().kind != "Dedup":
        return None
    return node.dep()


@register_rule
def push_filter_through_dedup(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Dedup(x)) → Dedup(Filter(x)) — row-wise filters commute
    with dedup, and filtering first shrinks the dedup set (reference:
    PushFilterDownNode family)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "Dedup":
        return None
    dd = node.dep()
    if len(dd.deps) != 1:
        return None
    node.deps = list(dd.deps)
    node.input_vars = [d.output_var for d in node.deps]
    dd.deps = [node]
    dd.input_vars = [node.output_var]
    return dd


@register_rule
def push_limit_down_project(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Project(x)) → Project(Limit(x)) — Project is 1:1, so limit
    first and evaluate fewer rows (reference: PushLimitDownProjectRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Project":
        return None
    pj = node.dep()
    if len(pj.deps) != 1:
        return None
    # constant-YIELD projects synthesize one row from column-less empty
    # input; moving the limit below them would bypass it (LIMIT 0 bug)
    if not pj.dep(0).col_names:
        return None
    cnt = node.args.get("count", -1)
    if cnt == 0:
        return None
    node.deps = list(pj.deps)
    node.input_vars = [d.output_var for d in node.deps]
    node.col_names = list(pj.dep(0).col_names) if pj.deps else node.col_names
    pj.deps = [node]
    pj.input_vars = [node.output_var]
    return pj


@register_rule
def push_limit_down_scan(node: PlanNode) -> Optional[PlanNode]:
    """Limit(ScanVertices) plants a scan stop bound (reference:
    PushLimitDownScanVerticesRule)."""
    if node.kind != "Limit" or not node.deps:
        return None
    sc = node.dep()
    if sc.kind != "ScanVertices":
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0 or sc.args.get("limit") is not None:
        return None
    sc.args["limit"] = (node.args.get("offset", 0) or 0) + cnt
    return None     # Limit stays for exactness


@register_rule
def push_limit_down_index_scan(node: PlanNode) -> Optional[PlanNode]:
    """Limit(IndexScan) / Limit(Project(IndexScan)) plants a scan bound
    (reference: PushLimitDownIndexScanRule); the scan counts rows AFTER
    its residual filter, so the bound is exact."""
    if node.kind != "Limit" or not node.deps:
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    target = node.dep()
    if target.kind == "Project" and target.deps:
        target = target.dep()
    if target.kind not in ("IndexScan", "FulltextIndexScan") or \
            target.args.get("limit") is not None:
        return None
    target.args["limit"] = (node.args.get("offset", 0) or 0) + cnt
    return None


@register_rule
def push_filter_down_append_vertices(node: PlanNode) -> Optional[PlanNode]:
    """Filter(AppendVertices) conjuncts that only touch the appended
    vertex alias merge into the node's own filter (reference:
    PushFilterDownAppendVerticesRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    av = node.dep()
    if av.kind != "AppendVertices":
        return None
    alias = av.args.get("col")
    cond = node.args.get("condition")
    if cond is None or not alias:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs is not None and refs and refs <= {alias}:
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = av.args.get("filter")
    av.args["filter"] = join_conjuncts(
        ([prev] if prev is not None else []) + pushable)
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return av


@register_rule
def push_filter_into_join_sides(node: PlanNode) -> Optional[PlanNode]:
    """Filter(HashInnerJoin/CrossJoin) conjuncts that read only one
    side's columns move below the join (reference:
    PushFilterDownInnerJoinRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    jn = node.dep()
    if jn.kind not in ("HashInnerJoin", "CrossJoin") or len(jn.deps) != 2:
        return None
    cond = node.args.get("condition")
    if cond is None:
        return None
    sides = [set(jn.dep(0).col_names), set(jn.dep(1).col_names)]
    moved = {0: [], 1: []}
    rest = []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs is None or not refs:
            rest.append(c)
        elif refs <= sides[0]:
            moved[0].append(c)
        elif refs <= sides[1]:
            moved[1].append(c)
        else:
            rest.append(c)
    if not moved[0] and not moved[1]:
        return None
    match_row = node.args.get("match_row", False)
    for i in (0, 1):
        if moved[i]:
            child = jn.dep(i)
            f = PlanNode("Filter", deps=[child],
                         col_names=list(child.col_names),
                         args={"condition": join_conjuncts(moved[i]),
                               "match_row": match_row})
            jn.deps[i] = f
    jn.input_vars = [d.output_var for d in jn.deps]
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return jn


@register_rule
def eliminate_noop_project(node: PlanNode) -> Optional[PlanNode]:
    """Project that only re-emits its input columns unchanged and in
    order → child (reference: RemoveNoopProjectRule)."""
    if node.kind != "Project" or len(node.deps) != 1:
        return None
    if any(node.args.get(f) for f in
           ("go_row", "match_row", "lookup_row", "fetch_row")):
        return None
    child = node.dep()
    cols = node.args.get("columns", [])
    if [n for _, n in cols] != list(child.col_names):
        return None
    for e, n in cols:
        if not (isinstance(e, InputProp) and e.name == n):
            return None
    return child
