"""Rule-based optimizer over the plan DAG.

Analog of the reference's memo-based RBO (reference: src/graph/optimizer,
~50 rules [UNVERIFIED — empty mount, SURVEY §0]).  Python plans are small
trees, so instead of an OptGroup memo we run bottom-up rewrite rules to a
fixpoint.  The rule set mirrors the reference's pushdown family; the TPU
fusion rule (`TpuTraverseRule`) registers itself from nebula_tpu.tpu at
import time — a new rule here is exactly where the TPU rewrite plugs in.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

from ..core.expr import (Binary, Expr, InputProp, join_conjuncts,
                         split_conjuncts, to_text, walk)
from .plan import ExecutionPlan, PlanNode, transform_plan, walk_plan

Rule = Callable[[PlanNode], Optional[PlanNode]]

RULES: List[Rule] = []

# Exploration rules (the OptGroup-memo leg): called as fn(node, pctx)
# and return a LIST of alternative subtrees for the node's group; the
# cost model picks the cheapest member (see find_best_plan).  Unlike
# RULES (rewrites that are always-better), these are choices — e.g.
# which index seeds a MATCH label scan.
ExploreRule = Callable[[PlanNode, Any], List[PlanNode]]
EXPLORE_RULES: List[ExploreRule] = []

# TPU fusion rule factories: each is called per-pass with a {node_id:
# parent_count} map AND the plan root (pipeline fusion must see
# by-name Argument references that dep edges don't carry) and returns
# a Rule.  Populated by nebula_tpu.tpu (kept here so query/ has no jax
# dependency).  Order matters: the first factory whose rule matches a
# node wins it, so specialized single-chain fusions register before
# the general pipeline fusion.
TPU_RULES: List = []


def register_rule(fn: Rule) -> Rule:
    RULES.append(fn)
    return fn


def register_explore_rule(fn: ExploreRule) -> ExploreRule:
    EXPLORE_RULES.append(fn)
    return fn


# ---------------------------------------------------------------------------
# Cost-lite memo (reference analog: Optimizer::findBestPlan over OptGroup
# alternatives [UNVERIFIED — empty mount, SURVEY §2 row 22]).  Plans here
# are trees of tens of nodes, so the memo is a per-node group of
# alternative subtrees with a cardinality-flow cost; exhaustive
# exploration is affordable and deterministic.
# ---------------------------------------------------------------------------

_BASE_ROWS = 1_000_000.0          # assumed table cardinality for scans
_EQ_SELECTIVITY = 100.0           # one bound eq column divides rows by this
_RANGE_SELECTIVITY = 10.0


def est_rows(node: PlanNode, child_rows: List[float]) -> float:
    """Heuristic output cardinality of one node."""
    k = node.kind
    inp = max(child_rows) if child_rows else 0.0
    if k in ("ScanVertices", "ScanEdges"):
        return _BASE_ROWS
    if k in ("IndexScan", "FulltextIndexScan"):
        if not node.args.get("index"):
            return _BASE_ROWS
        sel = _EQ_SELECTIVITY ** len(node.args.get("eq") or ())
        if node.args.get("range"):
            sel *= _RANGE_SELECTIVITY
        if node.args.get("geo_ranges"):
            # covering-cell scan ≈ a range binding (bbox-selective)
            sel *= _RANGE_SELECTIVITY
        return max(_BASE_ROWS / sel, 1.0)
    if k == "Filter":
        return inp / 4.0
    if k in ("Limit", "TopN", "Sample"):
        lim = node.args.get("count")
        return min(inp, float(lim)) if lim is not None else inp
    if k == "Dedup":
        return inp / 2.0
    if k == "Aggregate":
        return max(inp / 10.0, 1.0)
    if k in ("GetNeighbors", "Traverse", "Expand", "TpuTraverse"):
        return inp * 10.0
    return inp


def est_cost(node: PlanNode, memo: dict) -> float:
    """Total cardinality flowing through the subtree (each node costs
    its own output rows; children shared by id are costed once)."""
    got = memo.get(node.id)
    if got is not None:
        return got[1]
    child_rows = []
    total = 0.0
    for d in node.deps:
        est_cost(d, memo)
        rows_d, cost_d = memo[d.id]
        child_rows.append(rows_d)
        total += cost_d
    rows = est_rows(node, child_rows)
    total += rows
    memo[node.id] = (rows, total)
    return total


def find_best_plan(root: PlanNode, pctx) -> PlanNode:
    """Bottom-up group exploration: children first, then this node's
    alternatives from EXPLORE_RULES; the cheapest subtree (est_cost)
    wins its group.  Memoized by node id (shared deps explored once)."""
    chosen: dict = {}

    def rec(node: PlanNode) -> PlanNode:
        got = chosen.get(node.id)
        if got is not None:
            return got
        new_deps = [rec(d) for d in node.deps]
        if new_deps != node.deps:
            node.deps = new_deps
            node.input_vars = [d.output_var for d in new_deps]
        alts = [node]
        for rule in EXPLORE_RULES:
            try:
                alts.extend(rule(node, pctx) or ())
            except Exception:  # noqa: BLE001 — exploration must not fail a plan
                continue
        best = min(alts, key=lambda n: est_cost(n, {}))
        chosen[node.id] = best
        return best

    return rec(root)


def optimize(plan: ExecutionPlan, enable: bool = True,
             tpu: bool = False, pctx=None) -> ExecutionPlan:
    if not enable:
        return plan
    # When a rule replaces a node with one of its children, any by-name
    # reference to the removed node's output_var (e.g. Argument.from_var)
    # must be re-pointed at the survivor.
    var_alias = {}
    for _ in range(8):  # fixpoint with a safety bound
        changed = [False]

        def apply_once(node: PlanNode) -> Optional[PlanNode]:
            for rule in RULES:
                r = rule(node)
                if r is not None:
                    changed[0] = True
                    if r.output_var != node.output_var:
                        var_alias[node.output_var] = r.output_var
                    return r
            return None

        plan.root = transform_plan(plan.root, apply_once)
        if not changed[0]:
            break
    if pctx is not None and EXPLORE_RULES:
        plan.root = find_best_plan(plan.root, pctx)
    if tpu and TPU_RULES:
        # Fusion pass after pushdowns.  TOP-down (outermost node first) so a
        # whole N-step frontier chain fuses as one unit — bottom-up would
        # fuse the 1-step chain head and break the outer match.  Rules get
        # parent counts to refuse fusing chains other branches reference.
        uses: dict = {}
        for n in walk_plan(plan.root):
            for d in n.deps:
                uses[d.id] = uses.get(d.id, 0) + 1
        rules = [factory(uses, plan.root) for factory in TPU_RULES]
        memo: dict = {}

        def rec(node: PlanNode) -> PlanNode:
            if node.id in memo:
                return memo[node.id]
            for rule in rules:
                r = rule(node)
                if r is not None:
                    if r.output_var != node.output_var:
                        var_alias[node.output_var] = r.output_var
                    memo[node.id] = r
                    return r
            memo[node.id] = node        # pre-seed: cycles impossible in DAG
            new_deps = [rec(d) for d in node.deps]
            if new_deps != node.deps:
                node.deps = new_deps
                node.input_vars = [d.output_var for d in new_deps]
            return node

        plan.root = rec(plan.root)
    if var_alias:
        # Only references to nodes that actually LEFT the plan may be
        # re-pointed.  Swap rules (e.g. Limit(Project) → Project(Limit))
        # alias the old root to the new one, but BOTH nodes survive —
        # rewriting the new root's own input would self-loop it.
        live = {n.output_var for n in walk_plan(plan.root)}

        def resolve(v):
            seen = set()
            while v not in live and v in var_alias and v not in seen:
                seen.add(v)
                v = var_alias[v]
            return v
        for n in walk_plan(plan.root):
            if "from_var" in n.args:
                n.args["from_var"] = resolve(n.args["from_var"])
            n.input_vars = [resolve(v) for v in n.input_vars]
    return plan


# ---------------------------------------------------------------------------
# Rules (reference analogs noted per rule)
# ---------------------------------------------------------------------------


def _refs_only(e: Expr, kinds: tuple) -> bool:
    leaf_kinds = ("literal", "list", "set", "map") + kinds
    for x in walk(e):
        if x.kind in ("src_prop", "edge_prop", "dst_prop", "input_prop",
                      "var", "var_prop", "label", "label_tag_prop",
                      "vertex", "edge", "attribute"):
            if x.kind not in kinds:
                return False
    return True


@register_rule
def push_filter_down_expand(node: PlanNode) -> Optional[PlanNode]:
    """Filter(ExpandAll) → ExpandAll{edge_filter} for conjuncts that only
    touch edge props / src props (reference: PushFilterDownGetNbrsRule)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "ExpandAll":
        return None
    exp = node.dep()
    cond = node.args.get("condition")
    if cond is None:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        if _refs_only(c, ("edge_prop", "src_prop")):
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = exp.args.get("edge_filter")
    allp = ([prev] if prev is not None else []) + pushable
    exp.args["edge_filter"] = join_conjuncts(allp)
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None  # keep the (reduced) filter
    return exp  # filter fully absorbed


@register_rule
def push_filter_down_traverse(node: PlanNode) -> Optional[PlanNode]:
    """Filter(AppendVertices(Traverse)) edge-only conjuncts → Traverse
    (reference: PushFilterDownTraverseRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    av = node.dep()
    if av.kind != "AppendVertices" or not av.deps or av.dep().kind != "Traverse":
        return None
    tv = av.dep()
    cond = node.args.get("condition")
    if cond is None:
        return None
    edge_alias = tv.args.get("edge_alias")
    if tv.args.get("min_hop") != 1 or tv.args.get("max_hop") != 1:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        refs = [x for x in walk(c)
                if x.kind in ("label", "label_tag_prop", "attribute",
                              "input_prop", "var", "var_prop")]
        names = set()
        for r in refs:
            if r.kind == "label":
                names.add(r.name)
            elif r.kind == "label_tag_prop":
                names.add(r.var)
            elif r.kind == "attribute":
                o = r.obj
                while o.kind == "attribute":
                    o = o.obj
                if o.kind == "label":
                    names.add(o.name)
                else:
                    names.add("__other__")
            else:
                names.add("__other__")
        if names and names <= {edge_alias}:
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = tv.args.get("edge_filter")
    tv.args["edge_filter"] = join_conjuncts(
        ([prev] if prev is not None else []) + pushable)
    tv.args["edge_filter_alias"] = edge_alias
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return av


@register_rule
def push_limit_down_expand(node: PlanNode) -> Optional[PlanNode]:
    """Limit(ExpandAll) → ExpandAll{limit} (reference: PushLimitDownGetNeighborsRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "ExpandAll":
        return None
    exp = node.dep()
    if node.args.get("offset"):
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    if exp.args.get("limit") is not None:
        return None
    exp.args["limit"] = cnt
    return None  # keep Limit for exactness; Expand just over-produces less


@register_rule
def collapse_project(node: PlanNode) -> Optional[PlanNode]:
    """Project(Project(x)) where the outer only renames InputProp columns
    (reference: CollapseProjectRule)."""
    if node.kind != "Project" or not node.deps or node.dep().kind != "Project":
        return None
    inner = node.dep()
    if node.args.get("go_row") or node.args.get("match_row") or \
       inner.args.get("go_row") or inner.args.get("match_row"):
        return None
    inner_map = {n: e for e, n in inner.args.get("columns", [])}
    new_cols = []
    for e, n in node.args.get("columns", []):
        if isinstance(e, InputProp) and e.name in inner_map:
            new_cols.append((inner_map[e.name], n))
        else:
            return None
    node.args["columns"] = new_cols
    # the substituted expressions came from the inner project — they need
    # its evaluation context (lookup_row/fetch_row resolve Tag.prop etc.
    # against the scanned entity, not plain input columns)
    for flag in ("lookup_row", "fetch_row", "schema", "is_edge"):
        if flag in inner.args:
            node.args[flag] = inner.args[flag]
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


@register_rule
def merge_sort_limit_to_topn(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Sort(x)) → TopN (reference: TopNRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Sort":
        return None
    srt = node.dep()
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    return PlanNode("TopN", deps=list(srt.deps),
                    col_names=list(node.col_names),
                    args={"factors": srt.args["factors"],
                          "offset": node.args.get("offset", 0),
                          "count": cnt,
                          "match_row": srt.args.get("match_row", False)})


@register_rule
def dedup_before_expand(node: PlanNode) -> Optional[PlanNode]:
    """ExpandAll fed by a Project of dsts without Dedup gains dedup_src
    (reference: the GetDstBySrc dedup optimization)."""
    if node.kind != "ExpandAll" or not node.deps:
        return None
    d = node.dep()
    if d.kind == "Dedup":
        node.args["dedup_input"] = True
    return None


def _col_refs(e: Expr) -> Optional[set]:
    """Column names a predicate reads, or None if it touches anything
    that is not a plain column reference (then it can't be re-homed)."""
    names = set()
    for x in walk(e):
        if x.kind in ("input_prop", "var"):
            names.add(x.name)
        elif x.kind == "label":
            names.add(x.name)
        elif x.kind == "var_prop":
            names.add(x.var)
        elif x.kind == "label_tag_prop":
            names.add(x.var)
        elif x.kind in ("src_prop", "edge_prop", "dst_prop", "vertex",
                        "edge"):
            return None
    return names


@register_rule
def merge_adjacent_filters(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Filter(x)) → Filter(x) with the conjunction (reference:
    CombineFilterRule)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "Filter":
        return None
    inner = node.dep()
    a, b = node.args.get("condition"), inner.args.get("condition")
    if a is None or b is None:
        return None
    if node.args.get("match_row") != inner.args.get("match_row"):
        return None
    node.args["condition"] = join_conjuncts([b, a])
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


@register_rule
def eliminate_true_filter(node: PlanNode) -> Optional[PlanNode]:
    """Filter(cond=true) → child (reference: the constant-fold/remove
    family)."""
    if node.kind != "Filter" or not node.deps:
        return None
    cond = node.args.get("condition")
    if cond is not None and cond.kind == "literal" and cond.value is True:
        return node.dep()
    return None


@register_rule
def eliminate_false_filter(node: PlanNode) -> Optional[PlanNode]:
    """Filter(cond=false|null) → empty result (reference: the
    degenerate-plan constant-fold family).  A constant-false predicate
    can skip the whole subtree — the columns survive, the rows never
    materialize."""
    if node.kind != "Filter" or not node.deps:
        return None
    cond = node.args.get("condition")
    if cond is None or cond.kind != "literal":
        return None
    from ..core.value import is_null
    if cond.value is False or (is_null(cond.value)
                               and not isinstance(cond.value, bool)):
        return PlanNode("Project", deps=[],
                        col_names=list(node.col_names),
                        args={"empty": True})
    return None


@register_rule
def merge_adjacent_limits(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Limit(x)) → one Limit (reference: MergeGetNbrsAndDedupRule
    sibling cleanups).  rows[o2:o2+c2][o1:o1+c1] = rows[o1+o2 : ...]."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Limit":
        return None
    inner = node.dep()
    o1, c1 = node.args.get("offset", 0) or 0, node.args.get("count", -1)
    o2, c2 = inner.args.get("offset", 0) or 0, inner.args.get("count", -1)
    if c2 is None or c2 < 0:
        cnt = c1
    else:
        avail = max(0, c2 - o1)
        cnt = avail if c1 is None or c1 < 0 else min(c1, avail)
    node.args["offset"] = o1 + o2
    node.args["count"] = cnt
    node.deps = list(inner.deps)
    node.input_vars = [d.output_var for d in node.deps]
    return node


# NOTE on Sort(Sort(x)): a plain drop-the-inner-sort collapse would be
# WRONG — the engine's Sort is stable, so the inner sort is observable
# through ties of the outer keys.  merge_consecutive_sorts (below)
# instead folds the inner keys in as SECONDARY factors of one Sort,
# which is order-identical and deletes the extra O(n log n) pass.

@register_rule
def eliminate_limit_zero(node: PlanNode) -> Optional[PlanNode]:
    """Limit(count=0) → empty result: the subtree can't contribute rows
    (reference: the degenerate-plan prune family)."""
    if node.kind != "Limit" or not node.deps:
        return None
    if node.args.get("count") == 0:
        return PlanNode("Project", deps=[],
                        col_names=list(node.col_names),
                        args={"empty": True})
    return None


@register_rule
def eliminate_topn_zero(node: PlanNode) -> Optional[PlanNode]:
    """TopN(count=0) → empty result: sorting zero output rows is pure
    waste (same degenerate-plan prune as Limit 0)."""
    if node.kind != "TopN" or not node.deps:
        return None
    if node.args.get("count") == 0:
        return PlanNode("Project", deps=[],
                        col_names=list(node.col_names),
                        args={"empty": True})
    return None


@register_rule
def eliminate_dedup_after_distinct_union(node: PlanNode
                                         ) -> Optional[PlanNode]:
    """Dedup(Union{distinct}) → Union{distinct}: a distinct set op
    already emits unique rows, the outer Dedup re-hashes them for
    nothing (UNION DISTINCT ... | YIELD DISTINCT shapes)."""
    if node.kind != "Dedup" or len(node.deps) != 1:
        return None
    u = node.dep()
    if u.kind in ("Union", "Intersect", "Minus") \
            and u.args.get("distinct"):
        return u
    return None


@register_rule
def eliminate_noop_limit(node: PlanNode) -> Optional[PlanNode]:
    """Limit(offset=0, count=unbounded) → child."""
    if node.kind != "Limit" or not node.deps:
        return None
    cnt = node.args.get("count", -1)
    off = node.args.get("offset", 0) or 0
    if off == 0 and (cnt is None or cnt < 0):
        return node.dep()
    return None


@register_rule
def collapse_dedup(node: PlanNode) -> Optional[PlanNode]:
    """Dedup(Dedup(x)) → Dedup(x)."""
    if node.kind != "Dedup" or not node.deps or node.dep().kind != "Dedup":
        return None
    return node.dep()


@register_rule
def push_filter_through_dedup(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Dedup(x)) → Dedup(Filter(x)) — row-wise filters commute
    with dedup, and filtering first shrinks the dedup set (reference:
    PushFilterDownNode family)."""
    if node.kind != "Filter" or not node.deps or node.dep().kind != "Dedup":
        return None
    dd = node.dep()
    if len(dd.deps) != 1:
        return None
    node.deps = list(dd.deps)
    node.input_vars = [d.output_var for d in node.deps]
    dd.deps = [node]
    dd.input_vars = [node.output_var]
    return dd


@register_rule
def push_limit_down_project(node: PlanNode) -> Optional[PlanNode]:
    """Limit(Project(x)) → Project(Limit(x)) — Project is 1:1, so limit
    first and evaluate fewer rows (reference: PushLimitDownProjectRule)."""
    if node.kind != "Limit" or not node.deps or node.dep().kind != "Project":
        return None
    pj = node.dep()
    if len(pj.deps) != 1:
        return None
    # constant-YIELD projects synthesize one row from column-less empty
    # input; moving the limit below them would bypass it (LIMIT 0 bug)
    if not pj.dep(0).col_names:
        return None
    cnt = node.args.get("count", -1)
    if cnt == 0:
        return None
    node.deps = list(pj.deps)
    node.input_vars = [d.output_var for d in node.deps]
    node.col_names = list(pj.dep(0).col_names) if pj.deps else node.col_names
    pj.deps = [node]
    pj.input_vars = [node.output_var]
    return pj


@register_rule
def push_limit_down_scan(node: PlanNode) -> Optional[PlanNode]:
    """Limit(ScanVertices) plants a scan stop bound (reference:
    PushLimitDownScanVerticesRule)."""
    if node.kind != "Limit" or not node.deps:
        return None
    sc = node.dep()
    if sc.kind != "ScanVertices":
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0 or sc.args.get("limit") is not None:
        return None
    sc.args["limit"] = (node.args.get("offset", 0) or 0) + cnt
    return None     # Limit stays for exactness


@register_rule
def push_limit_down_index_scan(node: PlanNode) -> Optional[PlanNode]:
    """Limit(IndexScan) / Limit(Project(IndexScan)) plants a scan bound
    (reference: PushLimitDownIndexScanRule); the scan counts rows AFTER
    its residual filter, so the bound is exact."""
    if node.kind != "Limit" or not node.deps:
        return None
    cnt = node.args.get("count", -1)
    if cnt is None or cnt < 0:
        return None
    target = node.dep()
    if target.kind == "Project" and target.deps:
        target = target.dep()
    if target.kind not in ("IndexScan", "FulltextIndexScan") or \
            target.args.get("limit") is not None:
        return None
    target.args["limit"] = (node.args.get("offset", 0) or 0) + cnt
    return None


@register_rule
def push_filter_down_append_vertices(node: PlanNode) -> Optional[PlanNode]:
    """Filter(AppendVertices) conjuncts that only touch the appended
    vertex alias merge into the node's own filter (reference:
    PushFilterDownAppendVerticesRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    av = node.dep()
    if av.kind != "AppendVertices":
        return None
    alias = av.args.get("col")
    cond = node.args.get("condition")
    if cond is None or not alias:
        return None
    pushable, rest = [], []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs is not None and refs and refs <= {alias}:
            pushable.append(c)
        else:
            rest.append(c)
    if not pushable:
        return None
    prev = av.args.get("filter")
    av.args["filter"] = join_conjuncts(
        ([prev] if prev is not None else []) + pushable)
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return av


@register_rule
def push_filter_into_join_sides(node: PlanNode) -> Optional[PlanNode]:
    """Filter(HashInnerJoin/CrossJoin) conjuncts that read only one
    side's columns move below the join (reference:
    PushFilterDownInnerJoinRule)."""
    if node.kind != "Filter" or not node.deps:
        return None
    jn = node.dep()
    if jn.kind not in ("HashInnerJoin", "CrossJoin") or len(jn.deps) != 2:
        return None
    cond = node.args.get("condition")
    if cond is None:
        return None
    sides = [set(jn.dep(0).col_names), set(jn.dep(1).col_names)]
    moved = {0: [], 1: []}
    rest = []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs is None or not refs:
            rest.append(c)
        elif refs <= sides[0]:
            moved[0].append(c)
        elif refs <= sides[1]:
            moved[1].append(c)
        else:
            rest.append(c)
    if not moved[0] and not moved[1]:
        return None
    match_row = node.args.get("match_row", False)
    for i in (0, 1):
        if moved[i]:
            child = jn.dep(i)
            f = PlanNode("Filter", deps=[child],
                         col_names=list(child.col_names),
                         args={"condition": join_conjuncts(moved[i]),
                               "match_row": match_row})
            jn.deps[i] = f
    jn.input_vars = [d.output_var for d in jn.deps]
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return jn


@register_rule
def eliminate_noop_project(node: PlanNode) -> Optional[PlanNode]:
    """Project that only re-emits its input columns unchanged and in
    order → child (reference: RemoveNoopProjectRule)."""
    if node.kind != "Project" or len(node.deps) != 1:
        return None
    if any(node.args.get(f) for f in
           ("go_row", "match_row", "lookup_row", "fetch_row")):
        return None
    child = node.dep()
    cols = node.args.get("columns", [])
    if [n for _, n in cols] != list(child.col_names):
        return None
    for e, n in cols:
        if not (isinstance(e, InputProp) and e.name == n):
            return None
    return child


def _rename_only_project(node: PlanNode) -> bool:
    """Project whose every column is a bare input reference (possibly
    renamed) — commuting row-count operators through it is safe."""
    if node.kind != "Project" or len(node.deps) != 1:
        return False
    if any(node.args.get(f) for f in
           ("go_row", "match_row", "lookup_row", "fetch_row")):
        return False
    return all(isinstance(e, InputProp)
               for e, _ in node.args.get("columns", []))


@register_rule
def push_topn_down_project(node: PlanNode) -> Optional[PlanNode]:
    """TopN(Project[rename-only]) → Project(TopN') with sort keys
    remapped through the rename (reference: PushTopNDownProjectRule) —
    the Project then materializes only the kept rows."""
    if node.kind != "TopN" or len(node.deps) != 1:
        return None
    proj = node.dep()
    if not _rename_only_project(proj) or len(proj.deps) != 1:
        return None
    rename = {n: e.name for e, n in proj.args.get("columns", [])}
    factors = node.args.get("factors", [])
    try:
        new_factors = [(rename[name], asc) for name, asc in factors]
    except (KeyError, TypeError, ValueError):
        return None
    child = proj.dep()
    topn = PlanNode("TopN", deps=[child], col_names=list(child.col_names),
                    args={"factors": new_factors,
                          "count": node.args.get("count"),
                          "offset": node.args.get("offset", 0),
                          "match_row": node.args.get("match_row", False)})
    return PlanNode("Project", deps=[topn],
                    col_names=list(proj.col_names),
                    args=dict(proj.args))


@register_rule
def push_dedup_through_project(node: PlanNode) -> Optional[PlanNode]:
    """Dedup(Project[rename-only, no duplicated source col]) →
    Project(Dedup) (reference: PushDedupDownProjectRule analog): dedup
    on the narrower pre-rename rows is the same row set when the
    projection is a bijection of columns."""
    if node.kind != "Dedup" or len(node.deps) != 1:
        return None
    proj = node.dep()
    if not _rename_only_project(proj) or len(proj.deps) != 1:
        return None
    srcs = [e.name for e, _ in proj.args.get("columns", [])]
    child = proj.dep()
    # bijection: every input column referenced exactly once, all of them
    if sorted(srcs) != sorted(child.col_names):
        return None
    dd = PlanNode("Dedup", deps=[child], col_names=list(child.col_names),
                  args={"match_row": node.args.get("match_row", False)})
    return PlanNode("Project", deps=[dd], col_names=list(proj.col_names),
                    args=dict(proj.args))


@register_rule
def push_filter_into_index_scan(node: PlanNode) -> Optional[PlanNode]:
    """Filter(IndexScan) in LOOKUP (schema-name) form → IndexScan with
    the residual filter applied during the scan (reference:
    PushFilterDownIndexScanRule): entities are dropped before the
    Project materializes them."""
    if node.kind != "Filter" or len(node.deps) != 1:
        return None
    if node.args.get("match_row"):      # MATCH-form exprs bind aliases,
        return None                     # not the schema name
    scan = node.dep()
    if scan.kind != "IndexScan" or scan.args.get("filter") is not None \
            or scan.args.get("limit") is not None:
        return None
    schema = scan.args.get("schema")
    cond = node.args.get("condition")
    if cond is None:
        return None
    # only conditions over the scanned schema's own props evaluate
    # identically inside the scan's row context
    for x in walk(cond):
        if x.kind == "label_tag_prop":
            if x.var != schema:
                return None
        elif x.kind not in ("literal", "binary", "unary", "list", "set",
                            "edge_prop"):
            return None
        elif x.kind == "edge_prop" and x.edge not in (schema, "__edge__"):
            return None
    new_args = dict(scan.args)
    new_args["filter"] = node.args["condition"]
    return PlanNode("IndexScan", deps=[], col_names=list(scan.col_names),
                    args=new_args)


@register_rule
def eliminate_dedup_after_unique_scan(node: PlanNode) -> Optional[PlanNode]:
    """Dedup over a scan that already emits unique single-entity rows
    (ScanVertices / vertex IndexScan dedup by vid internally) → child
    (reference: RemoveNoopDedupRule class)."""
    if node.kind != "Dedup" or len(node.deps) != 1:
        return None
    child = node.dep()
    if child.kind == "ScanVertices" and len(child.col_names) == 1:
        return child
    if child.kind == "IndexScan" and not child.args.get("is_edge") \
            and len(child.col_names) == 1:
        return child
    return None


@register_rule
def const_fold_filter_condition(node: PlanNode) -> Optional[PlanNode]:
    """Filter whose condition is a literal-only expression folds to the
    TRUE/FALSE form the eliminate_{true,false}_filter rules consume
    (reference: FoldConstantExprRule, filter leg)."""
    from ..core.expr import DictContext, Literal, to_bool3
    if node.kind != "Filter":
        return None
    cond = node.args.get("condition")
    if cond is None or cond.kind == "literal":
        return None
    if any(x.kind not in ("literal", "binary", "unary", "list", "set")
           for x in walk(cond)):
        return None
    try:
        val = to_bool3(cond.eval(DictContext()))
    except Exception:  # noqa: BLE001 — leave runtime errors to runtime
        return None
    new_args = dict(node.args)
    new_args["condition"] = Literal(val is True)
    return PlanNode("Filter", deps=list(node.deps),
                    col_names=list(node.col_names), args=new_args)


def _setop_pushable(node: PlanNode) -> bool:
    if len(node.deps) != 2:
        return False
    l, r = node.deps
    return list(l.col_names) == list(node.col_names) \
        and list(r.col_names) == list(node.col_names)


@register_rule
def push_filter_down_set_op(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Union/Intersect/Minus) → SetOp(Filter(l), Filter(r)) —
    row-level predicates commute with all three set ops (reference:
    PushFilterDownUnionRule family); each branch shrinks before the
    hash-join/dedup work."""
    if node.kind != "Filter" or len(node.deps) != 1:
        return None
    op = node.dep()
    if op.kind not in ("Union", "Intersect", "Minus") \
            or not _setop_pushable(op):
        return None
    if any(x.kind == "input_prop" and x.name not in op.col_names
           for x in walk(node.args.get("condition"))):
        return None
    branches = []
    for d in op.deps:
        f = PlanNode("Filter", deps=[d], col_names=list(d.col_names),
                     args=dict(node.args))
        branches.append(f)
    return PlanNode(op.kind, deps=branches,
                    col_names=list(op.col_names), args=dict(op.args))


def _planted_bound(d: PlanNode, kinds: Tuple[str, ...]) -> bool:
    """True when a branch already carries a planted row-bound node,
    looking THROUGH rename-only Projects: the push-through-project
    rules rewrite a planted Limit/TopN into Project(Limit/TopN), and a
    guard on the immediate child kind alone would re-plant every
    fixpoint round (code-review r4 finding)."""
    while _rename_only_project(d):
        d = d.dep()
    return d.kind in kinds


@register_rule
def push_limit_into_union_all(node: PlanNode) -> Optional[PlanNode]:
    """Limit(UNION ALL) keeps its outer cut but plants the same bound on
    each branch (reference: PushLimitDownUnionAllRule): each side stops
    producing past offset+count rows."""
    if node.kind != "Limit" or len(node.deps) != 1:
        return None
    u = node.dep()
    if u.kind != "Union" or u.args.get("distinct") \
            or not _setop_pushable(u):
        return None
    cnt = node.args.get("count")
    if cnt is None or cnt < 0:
        return None
    bound = cnt + (node.args.get("offset") or 0)
    if any(_planted_bound(d, ("Limit", "TopN")) for d in u.deps):
        return None                      # already planted (fixpoint stop)
    branches = [PlanNode("Limit", deps=[d], col_names=list(d.col_names),
                         args={"count": bound, "offset": 0})
                for d in u.deps]
    nu = PlanNode("Union", deps=branches, col_names=list(u.col_names),
                  args=dict(u.args))
    return PlanNode("Limit", deps=[nu], col_names=list(node.col_names),
                    args=dict(node.args))


def _is_empty_marker(n: PlanNode) -> bool:
    return n.kind == "Project" and n.args.get("empty") and not n.deps


@register_rule
def eliminate_empty_set_op_branch(node: PlanNode) -> Optional[PlanNode]:
    """Set op with a statically-empty branch simplifies (reference: the
    degenerate-plan prune family): UNION keeps the live side (deduped
    when distinct), INTERSECT dies, MINUS keeps/dies by side."""
    if node.kind not in ("Union", "Intersect", "Minus") \
            or not _setop_pushable(node):
        return None                      # branch col names must equal the
    l, r = node.deps                     # op's: the survivor replaces it
    le, re_ = _is_empty_marker(l), _is_empty_marker(r)
    if not le and not re_:
        return None

    def empty():
        return PlanNode("Project", deps=[], col_names=list(node.col_names),
                        args={"empty": True})

    def distinct_of(side):
        # set-op executors dedup their output; the surviving branch
        # must keep that semantics
        return PlanNode("Dedup", deps=[side],
                        col_names=list(side.col_names), args={})

    if node.kind == "Union":
        if le and re_:
            return empty()
        live = r if le else l
        return distinct_of(live) if node.args.get("distinct") else live
    if node.kind == "Intersect":
        return empty()
    # Minus
    if le:
        return empty()
    return distinct_of(l)


@register_rule
def fold_constant_project_columns(node: PlanNode) -> Optional[PlanNode]:
    """Project columns that are literal-only arithmetic fold to their
    value at plan time (reference: FoldConstantExprRule, project leg)."""
    from ..core.expr import DictContext, Literal
    if node.kind != "Project":
        return None
    cols = node.args.get("columns") or []
    new_cols, changed = [], False
    for e, n in cols:
        if e.kind in ("binary", "unary") and all(
                x.kind in ("literal", "binary", "unary")
                for x in walk(e)):
            try:
                val = e.eval(DictContext())
            except Exception:  # noqa: BLE001 — leave runtime errors alone
                new_cols.append((e, n))
                continue
            from ..core.value import is_null
            if is_null(val) or isinstance(val, (list, tuple, set, dict)):
                # null KINDS and container identity must survive to
                # runtime untouched
                new_cols.append((e, n))
                continue
            new_cols.append((Literal(val), n))
            changed = True
        else:
            new_cols.append((e, n))
    if not changed:
        return None
    new_args = dict(node.args)
    new_args["columns"] = new_cols
    return PlanNode("Project", deps=list(node.deps),
                    col_names=list(node.col_names), args=new_args)


@register_rule
def push_sample_down_project(node: PlanNode) -> Optional[PlanNode]:
    """Sample(Project[rename-only]) → Project(Sample) — sampling rows
    commutes with a column rename; the Project then materializes only
    the sampled rows (reference: PushSampleDownProjectRule class)."""
    if node.kind != "Sample" or len(node.deps) != 1:
        return None
    proj = node.dep()
    if not _rename_only_project(proj) or len(proj.deps) != 1:
        return None
    child = proj.dep()
    smp = PlanNode("Sample", deps=[child], col_names=list(child.col_names),
                   args=dict(node.args))
    return PlanNode("Project", deps=[smp], col_names=list(proj.col_names),
                    args=dict(proj.args))


@register_rule
def merge_dedup_into_distinct_union(node: PlanNode) -> Optional[PlanNode]:
    """Dedup(UNION DISTINCT) → the union (its executor already dedups)
    (reference: RemoveNoopDedupRule over distinct set ops)."""
    if node.kind != "Dedup" or len(node.deps) != 1:
        return None
    child = node.dep()
    if child.kind in ("Union",) and child.args.get("distinct"):
        return child
    if child.kind in ("Intersect", "Minus"):
        return child                     # both executors emit distinct rows
    return None


@register_rule
def push_filter_down_sort(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Sort) → Sort(Filter): filtering preserves a stable sort's
    order, and the sort then works on fewer rows (reference:
    PushFilterDownSortRule class)."""
    if node.kind != "Filter" or len(node.deps) != 1:
        return None
    srt = node.dep()
    if srt.kind != "Sort" or len(srt.deps) != 1:
        return None
    child = srt.dep()
    f = PlanNode("Filter", deps=[child], col_names=list(child.col_names),
                 args=dict(node.args))
    return PlanNode("Sort", deps=[f], col_names=list(srt.col_names),
                    args=dict(srt.args))


@register_rule
def eliminate_dedup_after_aggregate(node: PlanNode) -> Optional[PlanNode]:
    """Dedup(Aggregate) → Aggregate when every group key is among the
    projected columns — each group emits exactly one row, and rows from
    different groups differ on the key columns."""
    from ..core.expr import to_text
    if node.kind != "Dedup" or len(node.deps) != 1:
        return None
    agg = node.dep()
    if agg.kind != "Aggregate":
        return None
    keys = agg.args.get("group_keys") or []
    if not keys:
        return agg                       # global aggregate: single row
    col_texts = {to_text(e) for e, _ in agg.args.get("columns", [])}
    if all(to_text(k) in col_texts for k in keys):
        return agg
    return None


@register_rule
def merge_limit_into_topn(node: PlanNode) -> Optional[PlanNode]:
    """Limit(TopN) → TopN with the composed window (same offset
    composition as merge_adjacent_limits)."""
    if node.kind != "Limit" or len(node.deps) != 1:
        return None
    tn = node.dep()
    if tn.kind != "TopN":
        return None
    lo, lc = node.args.get("offset") or 0, node.args.get("count")
    to_, tc = tn.args.get("offset") or 0, tn.args.get("count")
    if lc is None or lc < 0 or tc is None or tc < 0:
        return None
    new_off = to_ + lo
    new_cnt = max(0, min(tc - lo, lc))
    new_args = dict(tn.args)
    new_args["offset"], new_args["count"] = new_off, new_cnt
    return PlanNode("TopN", deps=list(tn.deps),
                    col_names=list(node.col_names), args=new_args)


@register_rule
def push_filter_down_left_join(node: PlanNode) -> Optional[PlanNode]:
    """Filter(HashLeftJoin) conjuncts reading only LEFT-side columns
    merge into the left branch's OWN Filter (reference:
    PushFilterDownLeftJoinRule): filtering preserved-side rows before
    the join is equivalent, while right-side conjuncts must stay above
    (they'd drop null-extended rows differently).

    The merge is IN PLACE into an existing left-root Filter — never a
    new node: OPTIONAL MATCH right sides reference the left root by
    output_var (Argument.from_var), so replacing the root would orphan
    that linkage (code-review r4 regression).  When the left root
    isn't a Filter the rule simply doesn't fire."""
    if node.kind != "Filter" or not node.deps:
        return None
    jn = node.dep()
    if jn.kind != "HashLeftJoin" or len(jn.deps) != 2:
        return None
    lroot = jn.dep(0)
    if lroot.kind != "Filter":
        return None
    cond = node.args.get("condition")
    if cond is None:
        return None
    left_cols = set(lroot.col_names)
    moved, rest = [], []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs and refs <= left_cols:
            moved.append(c)
        else:
            rest.append(c)
    if not moved:
        return None
    lroot.args["condition"] = join_conjuncts(
        [lroot.args["condition"]] + moved)
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return jn


@register_rule
def merge_project_into_aggregate(node: PlanNode) -> Optional[PlanNode]:
    """Project[rename-only](Aggregate) → Aggregate emitting the renamed
    (possibly reordered / pruned) columns directly (reference:
    MergeProjectWithAggregateRule analog): grouping is defined by
    group_keys, so dropping or renaming output columns cannot change
    the groups — and one plan node's row materialization disappears."""
    if node.kind != "Project" or len(node.deps) != 1:
        return None
    if not _rename_only_project(node):
        return None
    agg = node.dep()
    if agg.kind != "Aggregate":
        return None
    by_name = {n: e for e, n in agg.args.get("columns", [])}
    new_cols = []
    for e, out in node.args.get("columns", []):
        src = by_name.get(e.name)
        if src is None:
            return None
        new_cols.append((src, out))
    new_args = dict(agg.args)
    new_args["columns"] = new_cols
    return PlanNode("Aggregate", deps=list(agg.deps),
                    col_names=list(node.col_names), args=new_args)


@register_rule
def push_topn_into_union_all(node: PlanNode) -> Optional[PlanNode]:
    """TopN(UNION ALL) keeps its outer cut but plants a bound-sized
    TopN on each branch (reference: PushTopNDownUnionAllRule analog):
    any row beyond each side's top offset+count can never make the
    overall window."""
    if node.kind != "TopN" or len(node.deps) != 1:
        return None
    u = node.dep()
    if u.kind != "Union" or u.args.get("distinct") \
            or not _setop_pushable(u):
        return None
    cnt = node.args.get("count")
    if cnt is None or cnt < 0:
        return None
    bound = cnt + (node.args.get("offset") or 0)
    if any(_planted_bound(d, ("TopN",)) for d in u.deps):
        return None                      # already planted (fixpoint stop)
    branches = [PlanNode("TopN", deps=[d], col_names=list(d.col_names),
                         args={"factors": list(node.args.get("factors", [])),
                               "count": bound, "offset": 0})
                for d in u.deps]
    nu = PlanNode("Union", deps=branches, col_names=list(u.col_names),
                  args=dict(u.args))
    return PlanNode("TopN", deps=[nu], col_names=list(node.col_names),
                    args=dict(node.args))


@register_rule
def push_filter_through_unwind(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Unwind) conjuncts that don't read the unwound alias move
    below the Unwind (reference: PushFilterDownUnwindRule analog): they
    hold once per input row instead of once per unwound element."""
    if node.kind != "Filter" or not node.deps:
        return None
    uw = node.dep()
    if uw.kind != "Unwind" or len(uw.deps) != 1:
        return None
    alias = uw.args.get("alias")
    child = uw.dep()
    child_cols = set(child.col_names)
    cond = node.args.get("condition")
    if cond is None:
        return None
    moved, rest = [], []
    for c in split_conjuncts(cond):
        refs = _col_refs(c)
        if refs and alias not in refs and refs <= child_cols:
            moved.append(c)
        else:
            rest.append(c)
    if not moved:
        return None
    f = PlanNode("Filter", deps=[child], col_names=list(child.col_names),
                 args={"condition": join_conjuncts(moved),
                       "match_row": node.args.get("match_row", False)})
    uw.deps[0] = f
    uw.input_vars = [d.output_var for d in uw.deps]
    if rest:
        node.args["condition"] = join_conjuncts(rest)
        return None
    return uw


# expr kinds expr.rewrite() traverses AND whose column references are
# plain names — a WHITELIST: substitution through any other kind (slice,
# list_comprehension, reduce, compound refs, ...) either can't reach the
# nested reference or can't re-home it, so such conjuncts never move
_SUBSTITUTABLE_KINDS = frozenset((
    "literal", "input_prop", "var", "label", "binary", "unary", "list",
    "map", "function", "aggregate", "subscript", "case", "cast"))


def _plain_col_refs(e: Expr) -> Optional[set]:
    """Column names read through PLAIN references only — None when the
    expr contains ANY node kind outside the substitution whitelist
    (rewrite() must be able to traverse to, and rename, every column
    reference; a nested ref it can't reach would be pushed verbatim and
    bind to the wrong input column)."""
    names = set()
    for x in walk(e):
        if x.kind not in _SUBSTITUTABLE_KINDS:
            return None
        if x.kind in ("input_prop", "var", "label"):
            names.add(x.name)
    return names


@register_rule
def push_filter_through_aggregate(node: PlanNode) -> Optional[PlanNode]:
    """Filter(Aggregate) conjuncts reading only group-key OUTPUT columns
    move below the Aggregate with the key exprs substituted back in
    (reference: PushFilterDownAggregateRule): a group key is constant
    within its group, so pre-filtering input rows drops exactly the
    rejected groups — and the aggregate hashes fewer rows."""
    from ..core.expr import rewrite
    if node.kind != "Filter" or len(node.deps) != 1:
        return None
    agg = node.dep()
    if agg.kind != "Aggregate" or len(agg.deps) != 1:
        return None
    # a MATCH tail (Aggregate over AppendVertices/Traverse) is the
    # TpuMatchAgg fusion shape; planting a Filter inside it would break
    # the device fusion for a host-side win that doesn't pay for it
    if any(n.kind in ("AppendVertices", "Traverse")
           for n in walk_plan(agg)):
        return None
    keys = agg.args.get("group_keys") or []
    if not keys:
        return None
    key_texts = {to_text(k) for k in keys}
    key_cols = {}
    for e, n in agg.args.get("columns", []):
        if to_text(e) in key_texts \
                and not any(x.kind == "aggregate" for x in walk(e)):
            key_cols[n] = e
    cond = node.args.get("condition")
    if cond is None or not key_cols:
        return None
    moved, rest = [], []
    for c in split_conjuncts(cond):
        refs = _plain_col_refs(c)
        if refs and refs <= set(key_cols):
            moved.append(rewrite(
                c, lambda x: key_cols[x.name]
                if x.kind in ("input_prop", "var", "label")
                and x.name in key_cols else None))
        else:
            rest.append(c)
    if not moved:
        return None
    child = agg.dep()
    f = PlanNode("Filter", deps=[child], col_names=list(child.col_names),
                 args={"condition": join_conjuncts(moved),
                       "match_row": node.args.get("match_row", False)})
    agg.deps[0] = f
    agg.input_vars = [d.output_var for d in agg.deps]
    if rest:
        # return the mutated node (not None) so the fixpoint records a
        # change and the next pass can keep pushing the planted Filter
        # (e.g. through a Dedup below); re-entry terminates because the
        # remaining conjuncts no longer reference only key columns
        node.args["condition"] = join_conjuncts(rest)
        return node
    return agg


@register_rule
def merge_consecutive_sorts(node: PlanNode) -> Optional[PlanNode]:
    """Sort/TopN over Sort → ONE node ordering by (outer keys, inner
    keys).  Both executors sort stably, so the outer pass over
    inner-sorted rows IS the composite order — merging preserves
    byte-identical output while deleting a full O(n log n) pass
    (reference: EliminateSortRule-family analog, kept exact)."""
    if node.kind not in ("Sort", "TopN") or len(node.deps) != 1:
        return None
    inner = node.dep()
    if inner.kind != "Sort" or len(inner.deps) != 1:
        return None
    outer_f = list(node.args.get("factors") or [])
    inner_f = list(inner.args.get("factors") or [])
    seen = {to_text(e) for e, _ in outer_f}
    merged = outer_f + [(e, d) for e, d in inner_f
                        if to_text(e) not in seen]
    node.args["factors"] = merged
    node.deps[0] = inner.dep()
    node.input_vars = [d.output_var for d in node.deps]
    return node


# duplicate rows cannot change these folds: min/max are idempotent
# under repetition, collect_set and bit_and/bit_or absorb duplicates
_DUP_INSENSITIVE_AGGS = {"min", "max", "collect_set", "bit_and", "bit_or"}


@register_rule
def eliminate_dedup_under_dupfree_aggregate(node: PlanNode
                                            ) -> Optional[PlanNode]:
    """Aggregate(Dedup(x)) → Aggregate(x) when every output column is a
    group key or a duplicate-insensitive / DISTINCT aggregate: dup rows
    land in the same group and cannot move any such fold (reference:
    EliminateAggDedupRule analog)."""
    if node.kind != "Aggregate" or len(node.deps) != 1:
        return None
    dd = node.dep()
    if dd.kind != "Dedup" or len(dd.deps) != 1:
        return None
    key_texts = {to_text(k) for k in (node.args.get("group_keys") or [])}
    for e, _ in node.args.get("columns", []):
        aggs = [x for x in walk(e) if x.kind == "aggregate"]
        if aggs:
            if not all(x.distinct or x.func in _DUP_INSENSITIVE_AGGS
                       for x in aggs):
                return None
        elif to_text(e) not in key_texts:
            return None          # impl-picked value could change
    node.deps[0] = dd.dep()
    node.input_vars = [d.output_var for d in node.deps]
    return node


@register_explore_rule
def index_seed_for_match_scan(node: PlanNode, pctx) -> List[PlanNode]:
    """MATCH (a:T) WHERE a.T.prop ... : offer Filter(IndexScan) as an
    alternative to Filter(ScanVertices) — one alternative per index
    whose column hints bind at least one predicate (reference:
    OptimizeTagIndexScanByFilterRule).  The full filter stays on top
    (the hints are implied by it), so rows are identical; the cost
    model picks the most selective binding."""
    if node.kind != "Filter" or len(node.deps) != 1:
        return []
    scan = node.dep()
    if scan.kind != "ScanVertices" or not scan.args.get("tag"):
        return []
    tag = scan.args["tag"]
    alias = scan.args.get("as_col") or scan.col_names[0]
    space = scan.args["space"]
    cond = node.args.get("condition")
    if cond is None:
        return []
    conds = {}
    for i, c in enumerate(split_conjuncts(cond)):
        if c.kind != "binary" or c.op not in ("==", "<", "<=", ">", ">="):
            continue
        lhs, rhs, op = c.lhs, c.rhs, c.op
        if rhs.kind == "label_tag_prop" and lhs.kind == "literal":
            lhs, rhs = rhs, lhs
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if lhs.kind != "label_tag_prop" or rhs.kind != "literal":
            continue
        if lhs.var != alias or lhs.tag != tag:
            continue
        conds.setdefault(lhs.prop, []).append((op, rhs.value, i))
    if not conds:
        return []
    try:
        indexes = pctx.catalog.indexes_for(space, tag, False)
    except Exception:  # noqa: BLE001 — schema raced away; no alternative
        return []
    from .planner import score_index_hints
    alts = []
    for d in indexes:
        if any(getattr(d, "field_lens", None) or []):
            # string-prefix indexes need value truncation + a full
            # residual; the LOOKUP planner handles that — this scan
            # alternative would probe with untruncated values and miss
            continue
        best = score_index_hints([d], conds)
        if best is None:
            continue
        (n_eq, has_rng), name, eq, rng, _used = best
        if n_eq == 0 and not has_rng:
            continue
        iscan = PlanNode("IndexScan", deps=[], col_names=[alias],
                         args={"space": space, "schema": tag,
                               "is_edge": False, "index": name,
                               "eq": eq, "range": rng})
        filt = PlanNode("Filter", deps=[iscan],
                        col_names=list(node.col_names),
                        args=dict(node.args))
        filt.output_var = node.output_var
        alts.append(filt)
    return alts


@register_explore_rule
def geo_index_seed_for_match_scan(node: PlanNode, pctx) -> List[PlanNode]:
    """MATCH (a:T) WHERE ST_Intersects(a.T.g, <const>) ...: offer
    Filter(IndexScan geo_ranges) over the cell-token geo index as an
    alternative to Filter(ScanVertices) (reference: the geo variant of
    OptimizeTagIndexScanByFilterRule [UNVERIFIED — empty mount, SURVEY
    §0 row 15]).  The full filter stays on top — the covering ranges
    are a bbox superset, so rows are identical."""
    if node.kind != "Filter" or len(node.deps) != 1:
        return []
    scan = node.dep()
    if scan.kind != "ScanVertices" or not scan.args.get("tag"):
        return []
    tag = scan.args["tag"]
    alias = scan.args.get("as_col") or scan.col_names[0]
    space = scan.args["space"]
    cond = node.args.get("condition")
    if cond is None:
        return []
    from .planner import _geo_index_for, _lookup_geo_cond
    for c in split_conjuncts(cond):
        m = _lookup_geo_cond(c, tag, False, alias=alias)
        if m is None:
            continue
        d = _geo_index_for(pctx, space, tag, False, m[0])
        if d is None:
            continue
        iscan = PlanNode("IndexScan", deps=[], col_names=[alias],
                         args={"space": space, "schema": tag,
                               "is_edge": False, "index": d.name,
                               "geo_ranges": m[1]})
        filt = PlanNode("Filter", deps=[iscan],
                        col_names=list(node.col_names),
                        args=dict(node.args))
        filt.output_var = node.output_var
        return [filt]
    return []
