"""PlanNode layer — the optimizer/executor boundary.

This boundary is kept deliberately close to the reference's PlanNode
vocabulary (reference: src/graph/planner/plan/*.h [UNVERIFIED — empty
mount, SURVEY §0]) because it is the plugin seam the TPU backend hooks
into: `TpuTraverseRule` rewrites ExpandAll/Traverse(+Filter…) chains into
a fused `TpuTraverse` node, exactly as the north star prescribes.

One generic dataclass with a `kind` string + typed helper constructors —
60 subclasses would buy nothing in Python; golden-plan tests assert on
kind sequences, executors dispatch on kind.

Node kinds (grouped):
  control : Start, Loop, Argument, PassThrough
  explore : ExpandAll, Traverse, AppendVertices, GetVertices, GetEdges,
            ScanVertices, ScanEdges, IndexScan, TpuTraverse (tpu/)
  query   : Filter, Project, Aggregate, Dedup, Sort, TopN, Limit, Sample,
            Unwind, DataCollect, HashInnerJoin, HashLeftJoin, CrossJoin,
            Union, Intersect, Minus
  algo    : ShortestPath, AllPaths, Subgraph
  mutate  : InsertVertices, InsertEdges, Delete*, Update
  admin   : the DDL/SHOW/DESC/etc. one-shot nodes
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_ids = itertools.count()


@dataclass
class PlanNode:
    kind: str
    deps: List["PlanNode"] = field(default_factory=list)
    args: Dict[str, Any] = field(default_factory=dict)
    col_names: List[str] = field(default_factory=list)
    output_var: str = ""
    input_vars: List[str] = field(default_factory=list)
    id: int = field(default_factory=lambda: next(_ids))

    def __post_init__(self):
        if not self.output_var:
            self.output_var = f"__{self.kind}_{self.id}"
        if not self.input_vars and self.deps:
            self.input_vars = [d.output_var for d in self.deps]

    def dep(self, i: int = 0) -> "PlanNode":
        return self.deps[i]

    # -- description (EXPLAIN / golden-plan tests) --
    def describe(self, indent: int = 0) -> str:
        from ..core.expr import Expr, to_text
        pad = "  " * indent
        bits = []
        for k, v in self.args.items():
            if v is None or v == [] or v == {}:
                continue
            if isinstance(v, Expr):
                bits.append(f"{k}={to_text(v)}")
            elif isinstance(v, list) and v and isinstance(v[0], Expr):
                bits.append(f"{k}=[{', '.join(to_text(x) for x in v)}]")
            else:
                r = repr(v)
                if len(r) > 160:
                    # compiled-program args (TpuMatchPipeline segment
                    # lists) would swamp EXPLAIN — elide the body
                    r = r[:150] + f"…+{len(r) - 150}ch"
                bits.append(f"{k}={r}")
        line = f"{pad}{self.kind}#{self.id}"
        if bits:
            line += " {" + ", ".join(bits) + "}"
        if self.col_names:
            line += f" -> {self.col_names}"
        out = [line]
        for d in self.deps:
            out.append(d.describe(indent + 1))
        return "\n".join(out)

    def kind_tree(self) -> List[str]:
        """Flattened kinds, depth-first — golden-plan assertion target."""
        out = [self.kind]
        for d in self.deps:
            out.extend(d.kind_tree())
        return out


@dataclass
class ExecutionPlan:
    root: PlanNode
    space: Optional[str] = None

    def describe(self, fmt: str = "row") -> str:
        if fmt == "dot":
            return self.describe_dot()
        return self.root.describe()

    def describe_dot(self) -> str:
        """Graphviz rendering of the plan DAG (reference: EXPLAIN
        FORMAT=\"dot\")."""
        def esc(t: str) -> str:
            return t.replace("\\", "\\\\").replace('"', '\\"')

        lines = ["digraph exec_plan {", "  rankdir=BT;"]
        for n in walk_plan(self.root):
            label = n.kind + (f"\\n{esc(str(n.col_names))}"
                              if n.col_names else "")
            lines.append(f'  n{n.id} [label="{label}#{n.id}", '
                         f"shape=box];")
        for n in walk_plan(self.root):
            for d in n.deps:
                lines.append(f"  n{d.id} -> n{n.id};")
        lines.append("}")
        return "\n".join(lines)


# -- walk/transform helpers used by the optimizer ---------------------------


def walk_plan(node: PlanNode, seen=None):
    if seen is None:
        seen = set()
    if node.id in seen:
        return
    seen.add(node.id)
    yield node
    for d in node.deps:
        yield from walk_plan(d, seen)


def transform_plan(node: PlanNode, fn, memo: Optional[Dict[int, PlanNode]] = None) -> PlanNode:
    """Bottom-up rewrite; fn(node) returns a replacement or None to keep.
    Shared sub-DAGs are rewritten once (memo keyed by node id)."""
    if memo is None:
        memo = {}
    if node.id in memo:
        return memo[node.id]
    new_deps = [transform_plan(d, fn, memo) for d in node.deps]
    if new_deps != node.deps:
        node.deps = new_deps
        node.input_vars = [d.output_var for d in new_deps]
    r = fn(node)
    out = r if r is not None else node
    memo[node.id] = out
    return out
