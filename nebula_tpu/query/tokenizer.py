"""nGQL lexer.

Replaces the reference's flex scanner (reference: src/parser/scanner.lex
[UNVERIFIED — empty mount, SURVEY §0]) with a hand-written tokenizer: the
grammar is the spec; parse time is microseconds against millisecond queries,
so a generated scanner buys nothing here.

Token kinds: KEYWORD (uppercased), IDENT, STRING, INT, FLOAT, BOOL, and
punctuation/operator tokens whose `kind` is the operator text itself
('==', '->', '..', '$-', etc.).
"""
from __future__ import annotations

from typing import Any, List, NamedTuple, Optional

KEYWORDS = {
    # statements
    "GO", "FROM", "OVER", "WHERE", "YIELD", "AS", "STEPS", "STEP", "TO",
    "REVERSELY", "BIDIRECT", "USE", "CREATE", "DROP", "SPACE", "SPACES",
    "TAG", "TAGS", "EDGE", "EDGES", "IF", "NOT", "EXISTS", "ALTER", "ADD",
    "CHANGE", "DESCRIBE", "DESC", "SHOW", "HOSTS", "PARTS", "PARTITION",
    "INSERT", "VERTEX", "VERTICES", "VALUES", "DELETE", "UPDATE", "UPSERT",
    "SET", "WHEN", "FETCH", "PROP", "ON", "LOOKUP", "MATCH", "OPTIONAL",
    "RETURN", "WITH", "UNWIND", "SKIP", "LIMIT", "OFFSET", "ORDER", "BY",
    "ASC", "ASCENDING", "DESCENDING", "GROUP", "DISTINCT", "FIND", "PATH",
    "SHORTEST", "ALL", "NOLOOP", "UPTO", "GET", "SUBGRAPH", "BOTH", "IN",
    "OUT", "EXPLAIN", "PROFILE", "FORMAT", "UNION", "INTERSECT", "MINUS",
    "INDEX", "INDEXES", "REBUILD", "STATS", "SUBMIT", "JOB", "JOBS",
    "BALANCE", "DATA", "LEADER", "SNAPSHOT", "SNAPSHOTS", "SESSION",
    "SESSIONS", "KILL", "QUERY", "QUERIES", "CONFIGS", "TTL_DURATION",
    "TTL_COL", "DEFAULT", "NULL", "COMMENT", "SAMPLE", "INGEST",
    "USER", "USERS", "PASSWORD", "GRANT", "REVOKE", "ROLE", "ROLES",
    "ZONE", "ZONES", "INTO", "FULLTEXT", "LISTENER", "ELASTICSEARCH",
    "REMOVE", "CHARSET", "COLLATION", "CLEAR", "STOP", "RECOVER", "SIGN",
    "MERGE", "RENAME", "DIVIDE", "TEXT", "SERVICE", "SEARCH", "CLIENTS",
    "STATUS",
    "META", "GRAPH", "STORAGE", "DOWNLOAD", "HDFS",
    "BACKUP", "BACKUPS", "RESTORE", "NEW", "LOCAL", "TRACES",
    "FLIGHT", "RECORDER", "SLO", "STALLS", "CALL", "REPAIRS",
    "STATEMENTS", "HOTSPOTS", "TENANTS",
    # types
    "INT", "INT64", "INT32", "INT16", "INT8", "FLOAT", "DOUBLE", "STRING",
    "FIXED_STRING", "BOOL", "TIMESTAMP", "DATE", "TIME", "DATETIME",
    "DURATION", "GEOGRAPHY",
    # expression keywords
    "AND", "OR", "XOR", "TRUE", "FALSE", "CONTAINS", "STARTS", "ENDS",
    "IS", "CASE", "THEN", "ELSE", "END", "EMPTY",
    # reserved column-ish
    "VID_TYPE", "PARTITION_NUM", "REPLICA_FACTOR",
}

PUNCT2 = ["==", "!=", ">=", "<=", "=~", "->", "<-", "..", "|>", "+=", "::",
          "$-", "$^", "$$", "//", "--"]
PUNCT1 = list("()[]{}<>+-*/%!=.,:;|@?&^~#")


class Token(NamedTuple):
    kind: str         # 'KEYWORD' | 'IDENT' | 'STRING' | 'INT' | 'FLOAT' | op-text
    value: Any
    pos: int
    raw: str = ""     # keyword tokens keep the source spelling so an
                      # unreserved keyword used as an identifier (a tag
                      # named `User`, a prop named `role`) round-trips
                      # case-sensitively through Parser.ident()

    def __repr__(self):
        return f"{self.kind}({self.value!r})"


class LexError(Exception):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"{msg} near position {pos}")
        self.pos = pos


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c in " \t\r\n":
            i += 1
            continue
        # comments: # ... EOL and // ... EOL
        if c == "#" or text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise LexError("unterminated comment", i)
            i = j + 2
            continue
        # strings
        if c in "'\"":
            s, j = _scan_string(text, i)
            toks.append(Token("STRING", s, i))
            i = j
            continue
        # backquoted identifier
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise LexError("unterminated backquoted identifier", i)
            toks.append(Token("IDENT", text[i + 1:j], i))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            tok, j = _scan_number(text, i)
            toks.append(tok)
            i = j
            continue
        # identifiers / keywords / $var
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            up = word.upper()
            if up in KEYWORDS:
                toks.append(Token("KEYWORD", up, i, word))
            else:
                toks.append(Token("IDENT", word, i))
            i = j
            continue
        if c == "$":
            # $-, $^, $$ handled below via PUNCT2; $name here
            two = text[i:i + 2]
            if two in ("$-", "$^", "$$"):
                toks.append(Token(two, two, i))
                i += 2
                continue
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            if j == i + 1:
                raise LexError("bare `$'", i)
            toks.append(Token("VAR", text[i + 1:j], i))
            i = j
            continue
        # two-char operators
        two = text[i:i + 2]
        if two in PUNCT2 and two not in ("$-", "$^", "$$", "//", "--"):
            toks.append(Token(two, two, i))
            i += 2
            continue
        if c in PUNCT1:
            toks.append(Token(c, c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r}", i)
    toks.append(Token("EOF", None, n))
    return toks


def _scan_string(text: str, i: int):
    quote = text[i]
    out = []
    j = i + 1
    n = len(text)
    while j < n:
        c = text[j]
        if c == "\\" and j + 1 < n:
            nxt = text[j + 1]
            esc = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", "'": "'",
                   '"': '"', "0": "\0", "b": "\b", "f": "\f"}.get(nxt)
            out.append(esc if esc is not None else nxt)
            j += 2
            continue
        if c == quote:
            return "".join(out), j + 1
        out.append(c)
        j += 1
    raise LexError("unterminated string", i)


def _scan_number(text: str, i: int):
    n = len(text)
    j = i
    if text.startswith("0x", i) or text.startswith("0X", i):
        j = i + 2
        while j < n and text[j] in "0123456789abcdefABCDEF":
            j += 1
        return Token("INT", int(text[i:j], 16), i), j
    is_float = False
    while j < n and text[j].isdigit():
        j += 1
    if j < n and text[j] == "." and not text.startswith("..", j):
        if j + 1 < n and text[j + 1].isdigit():
            is_float = True
            j += 1
            while j < n and text[j].isdigit():
                j += 1
    if j < n and text[j] in "eE":
        k = j + 1
        if k < n and text[k] in "+-":
            k += 1
        if k < n and text[k].isdigit():
            is_float = True
            j = k
            while j < n and text[j].isdigit():
                j += 1
    s = text[i:j]
    if is_float:
        return Token("FLOAT", float(s), i), j
    return Token("INT", int(s), i), j
