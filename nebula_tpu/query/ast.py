"""Sentence AST — the parser's output vocabulary.

Analog of the reference's ~90 ``Sentence`` classes (reference: src/parser/
*.h [UNVERIFIED — empty mount, SURVEY §0]), trimmed to the supported nGQL
subset and expressed as plain dataclasses.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core.expr import Expr


class Sentence:
    pass


# ---- composition ----------------------------------------------------------


@dataclass
class SeqSentence(Sentence):
    """stmt; stmt; ..."""
    stmts: List[Sentence]


@dataclass
class PipedSentence(Sentence):
    left: Sentence
    right: Sentence


@dataclass
class AssignSentence(Sentence):
    var: str
    stmt: Sentence


@dataclass
class SetOpSentence(Sentence):
    op: str                      # UNION | UNION ALL | INTERSECT | MINUS
    left: Sentence
    right: Sentence


@dataclass
class ExplainSentence(Sentence):
    stmt: Sentence
    profile: bool = False
    fmt: str = "row"


# ---- clauses --------------------------------------------------------------


@dataclass
class YieldColumn:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class YieldClause:
    columns: List[YieldColumn]
    distinct: bool = False


@dataclass
class FromClause:
    vids: Optional[List[Expr]] = None   # literal/expr vid list
    ref: Optional[Expr] = None          # $-.col or $var.col


@dataclass
class OverClause:
    edges: List[str] = field(default_factory=list)  # empty = OVER *
    direction: str = "out"               # out | in (REVERSELY) | both (BIDIRECT)

    @property
    def is_all(self) -> bool:
        return not self.edges


@dataclass
class StepClause:
    m: int = 1                           # lower bound (GO m TO n STEPS)
    n: int = 1


@dataclass
class WhereClause:
    filter: Expr


@dataclass
class TruncateClause:                    # LIMIT/SAMPLE pushdown in GO
    counts: List[int] = field(default_factory=list)
    is_sample: bool = False


@dataclass
class OrderFactor:
    expr: Expr
    ascending: bool = True


# ---- admin / DDL ----------------------------------------------------------


@dataclass
class UseSentence(Sentence):
    space: str


@dataclass
class CreateSpaceSentence(Sentence):
    name: str
    if_not_exists: bool = False
    partition_num: int = 8
    replica_factor: int = 1
    vid_type: str = "FIXED_STRING(32)"
    comment: str = ""


@dataclass
class DropSpaceSentence(Sentence):
    name: str
    if_exists: bool = False


@dataclass
class PropDefAst:
    name: str
    type_name: str
    fixed_len: int = 0
    nullable: bool = True
    default: Optional[Expr] = None
    comment: str = ""


@dataclass
class CreateSchemaSentence(Sentence):
    is_edge: bool
    name: str
    props: List[PropDefAst]
    if_not_exists: bool = False
    ttl_duration: int = 0
    ttl_col: str = ""
    comment: str = ""


@dataclass
class AlterSchemaSentence(Sentence):
    is_edge: bool
    name: str
    adds: List[PropDefAst] = field(default_factory=list)
    drops: List[str] = field(default_factory=list)
    changes: List[PropDefAst] = field(default_factory=list)
    ttl_duration: Optional[int] = None
    ttl_col: Optional[str] = None


@dataclass
class DropSchemaSentence(Sentence):
    is_edge: bool
    name: str
    if_exists: bool = False


@dataclass
class DescribeSentence(Sentence):
    kind: str                            # space | tag | edge | index
    name: str


@dataclass
class ShowSentence(Sentence):
    kind: str                            # spaces|tags|edges|hosts|parts|stats|...
    extra: Any = None


@dataclass
class CreateIndexSentence(Sentence):
    is_edge: bool
    index_name: str
    schema_name: str
    fields: List[str]
    if_not_exists: bool = False
    # per-field string prefix length, 0 = full value (reference:
    # CREATE TAG INDEX i ON t(name(10)))
    field_lens: List[int] = field(default_factory=list)


@dataclass
class DropIndexSentence(Sentence):
    is_edge: bool
    index_name: str
    if_exists: bool = False


@dataclass
class RebuildIndexSentence(Sentence):
    is_edge: bool
    index_name: str


@dataclass
class CreateSpaceAsSentence(Sentence):
    name: str
    source: str
    if_not_exists: bool = False


@dataclass
class CreateFulltextIndexSentence(Sentence):
    is_edge: bool
    index_name: str
    schema_name: str
    field: str
    if_not_exists: bool = False


@dataclass
class DropFulltextIndexSentence(Sentence):
    index_name: str
    if_exists: bool = False


@dataclass
class RebuildFulltextIndexSentence(Sentence):
    index_name: Optional[str] = None     # None → all


@dataclass
class AddListenerSentence(Sentence):
    ltype: str                           # ELASTICSEARCH
    endpoints: List[str]


@dataclass
class RemoveListenerSentence(Sentence):
    ltype: str


@dataclass
class SubmitJobSentence(Sentence):
    job: str                             # balance data | balance leader | compact | stats | ingest


@dataclass
class ShowJobsSentence(Sentence):
    job_id: Optional[int] = None


@dataclass
class CreateSnapshotSentence(Sentence):
    pass


@dataclass
class DropSnapshotSentence(Sentence):
    name: str


@dataclass
class CreateBackupSentence(Sentence):
    name: Optional[str] = None


@dataclass
class DropBackupSentence(Sentence):
    name: str = ""


@dataclass
class RestoreBackupSentence(Sentence):
    name: str = ""


@dataclass
class KillQuerySentence(Sentence):
    session_id: Optional[int] = None
    plan_id: Optional[int] = None


# ---- DML ------------------------------------------------------------------


@dataclass
class VertexRowAst:
    vid: Expr
    values: List[Expr]


@dataclass
class InsertVerticesSentence(Sentence):
    # tag groups: [(tag_name, [prop, ...]), ...] — the reference grammar
    # allows INSERT VERTEX t1(a, b), t2(c) VALUES v:(x, y, z) with the
    # value list spanning the groups in order
    tags: list
    rows: List[VertexRowAst]
    if_not_exists: bool = False

    @property
    def prop_names(self) -> List[str]:
        return [n for _, ns in self.tags for n in ns]


@dataclass
class EdgeRowAst:
    src: Expr
    dst: Expr
    rank: int
    values: List[Expr]


@dataclass
class InsertEdgesSentence(Sentence):
    etype: str
    prop_names: List[str]
    rows: List[EdgeRowAst]
    if_not_exists: bool = False


@dataclass
class DeleteVerticesSentence(Sentence):
    vids: FromClause
    with_edge: bool = False


@dataclass
class EdgeKeyAst:
    src: Expr
    dst: Expr
    rank: int = 0


@dataclass
class DeleteEdgesSentence(Sentence):
    etype: str
    keys: List[EdgeKeyAst]
    ref: Optional[Tuple[Expr, Expr, Optional[Expr]]] = None  # src,dst,rank pipe refs


@dataclass
class DeleteTagsSentence(Sentence):
    tags: List[str]                     # empty = all (*)
    vids: FromClause


@dataclass
class AddHostsSentence(Sentence):
    hosts: list
    zone: str


@dataclass
class DropHostsSentence(Sentence):
    hosts: list


@dataclass
class DropZoneSentence(Sentence):
    zone: str


@dataclass
class MergeZoneSentence(Sentence):
    zones: List[str]
    into: str


@dataclass
class RenameZoneSentence(Sentence):
    old: str
    new: str


@dataclass
class DivideZoneSentence(Sentence):
    zone: str
    parts: list            # [(new_zone_name, [host, ...]), ...]


@dataclass
class DescZoneSentence(Sentence):
    zone: str


@dataclass
class ClearSpaceSentence(Sentence):
    name: str
    if_exists: bool = False


@dataclass
class StopJobSentence(Sentence):
    job_id: int


@dataclass
class RecoverJobSentence(Sentence):
    job_id: Optional[int] = None        # None = all failed jobs


@dataclass
class KillSessionSentence(Sentence):
    session_id: int


@dataclass
class GetConfigsSentence(Sentence):
    name: Optional[str] = None          # None = all (== SHOW CONFIGS)


@dataclass
class SignInTextServiceSentence(Sentence):
    endpoints: List[str]
    user: Optional[str] = None
    password: Optional[str] = None


@dataclass
class SignOutTextServiceSentence(Sentence):
    pass


@dataclass
class DescribeUserSentence(Sentence):
    name: str


@dataclass
class AlterSpaceSentence(Sentence):
    name: str
    op: str                             # add_zone
    zone: str


@dataclass
class DownloadSentence(Sentence):
    """DOWNLOAD HDFS "url" — the legacy bulk-load pipeline's fetch leg
    (always errors here: no HDFS offline; the surface exists for grammar
    parity)."""
    url: str


@dataclass
class IngestSentence(Sentence):
    """INGEST — the legacy bulk-load pipeline's apply leg (canonicalized
    to the ingest job)."""
    pass


@dataclass
class CreateUserSentence(Sentence):
    name: str
    password: str
    if_not_exists: bool = False


@dataclass
class DropUserSentence(Sentence):
    name: str
    if_exists: bool = False


@dataclass
class AlterUserSentence(Sentence):
    name: str
    password: str


@dataclass
class ChangePasswordSentence(Sentence):
    name: str
    old: str
    new: str


@dataclass
class GrantRoleSentence(Sentence):
    role: str
    space: str
    user: str


@dataclass
class RevokeRoleSentence(Sentence):
    role: str
    space: str
    user: str


@dataclass
class UpdateConfigsSentence(Sentence):
    # [(name, value_expr), ...] — UPDATE CONFIGS a = 1, b = 2 applies
    # atomically through Config.set_dynamic_many (all-or-nothing)
    updates: list


@dataclass
class UpdateSentence(Sentence):
    is_edge: bool
    schema_name: str
    vid: Optional[Expr] = None           # vertex target
    edge_key: Optional[EdgeKeyAst] = None
    sets: List[Tuple[str, Expr]] = field(default_factory=list)
    when: Optional[Expr] = None
    yield_: Optional[YieldClause] = None
    insertable: bool = False             # UPSERT


# ---- queries --------------------------------------------------------------


@dataclass
class GoSentence(Sentence):
    steps: StepClause
    from_: FromClause
    over: OverClause
    where: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None
    truncate: Optional[TruncateClause] = None


@dataclass
class FetchVerticesSentence(Sentence):
    tags: List[str]                      # empty = * (all tags)
    vids: FromClause
    yield_: Optional[YieldClause] = None


@dataclass
class FetchEdgesSentence(Sentence):
    etype: str
    keys: List[EdgeKeyAst]
    ref: Optional[Tuple[Expr, Expr, Optional[Expr]]] = None
    yield_: Optional[YieldClause] = None


@dataclass
class LookupSentence(Sentence):
    schema_name: str
    where: Optional[WhereClause] = None
    yield_: Optional[YieldClause] = None


@dataclass
class FindPathSentence(Sentence):
    kind: str                            # shortest | all | noloop
    from_: FromClause = None
    to: FromClause = None
    over: OverClause = None
    where: Optional[WhereClause] = None
    upto: int = 5
    with_prop: bool = False
    yield_: Optional[YieldClause] = None


@dataclass
class SubgraphSentence(Sentence):
    steps: int
    from_: FromClause
    in_edges: List[str] = field(default_factory=list)
    out_edges: List[str] = field(default_factory=list)
    both_edges: List[str] = field(default_factory=list)
    all_edges: bool = False
    where: Optional[WhereClause] = None
    with_prop: bool = False
    yield_: Optional[YieldClause] = None


@dataclass
class CallAlgoSentence(Sentence):
    """CALL algo.<func>(name=value, ...) [YIELD col [AS alias], ...]
    — the graph-analytics plane statement (ISSUE 13).  Parameter
    values are constant expressions (literals), evaluated at plan
    time."""
    module: str
    func: str
    params: Dict[str, Expr] = field(default_factory=dict)
    yield_: Optional[YieldClause] = None


@dataclass
class YieldSentence(Sentence):
    yield_: YieldClause
    where: Optional[WhereClause] = None


# pipe segments
@dataclass
class GroupBySentence(Sentence):
    keys: List[Expr]
    yield_: YieldClause = None


@dataclass
class OrderBySentence(Sentence):
    factors: List[OrderFactor]


@dataclass
class LimitSentence(Sentence):
    offset: int
    count: int


@dataclass
class SampleSentence(Sentence):
    count: int


# ---- MATCH ----------------------------------------------------------------


@dataclass
class NodePattern:
    alias: Optional[str] = None
    labels: List[Tuple[str, Optional[Dict[str, Expr]]]] = field(default_factory=list)
    props: Optional[Dict[str, Expr]] = None


@dataclass
class EdgePattern:
    alias: Optional[str] = None
    types: List[str] = field(default_factory=list)
    direction: str = "out"               # out | in | both
    min_hop: int = 1
    max_hop: int = 1                     # -1 = unbounded (*)
    props: Optional[Dict[str, Expr]] = None


@dataclass
class PathPattern:
    alias: Optional[str] = None          # p = (a)-[e]->(b)
    nodes: List[NodePattern] = field(default_factory=list)
    edges: List[EdgePattern] = field(default_factory=list)


def pattern_text(pat: "PathPattern") -> str:
    """Canonical source rendering of a path pattern — the to_text form of
    a pattern-predicate expression (EXPLAIN output, expr equality)."""
    from ..core.expr import to_text

    def props_text(props):
        return "{" + ", ".join(f"{k}: {to_text(v)}" for k, v in props.items()) + "}"

    def node_text(np: NodePattern) -> str:
        s = np.alias if np.alias and not np.alias.startswith("__anon_") else ""
        for lbl, lprops in np.labels:
            s += f":{lbl}"
            if lprops:
                s += props_text(lprops)
        if np.props:
            s += props_text(np.props)
        return f"({s})"

    out = [node_text(pat.nodes[0])]
    for ep, np in zip(pat.edges, pat.nodes[1:]):
        e = ep.alias if ep.alias and not ep.alias.startswith("__anon_") else ""
        if ep.types:
            e += ":" + "|".join(ep.types)
        if ep.min_hop != 1 or ep.max_hop != 1:
            e += "*"
            if ep.max_hop == -1:
                e += f"{ep.min_hop}.." if ep.min_hop != 1 else ""
            elif ep.min_hop == ep.max_hop:
                e += str(ep.min_hop)
            else:
                e += f"{ep.min_hop}..{ep.max_hop}"
        if ep.props:
            e += props_text(ep.props)
        body = f"[{e}]" if e else ""
        arrow = {"out": f"-{body}->", "in": f"<-{body}-",
                 "both": f"-{body}-"}[ep.direction]
        out.append(arrow)
        out.append(node_text(np))
    return "".join(out)


@dataclass
class MatchClauseAst:
    patterns: List[PathPattern]
    where: Optional[Expr] = None
    optional: bool = False


@dataclass
class UnwindClauseAst:
    expr: Expr
    alias: str = ""


@dataclass
class WithClauseAst:
    columns: List[YieldColumn] = None
    distinct: bool = False
    where: Optional[Expr] = None
    order_by: List[OrderFactor] = field(default_factory=list)
    skip: int = 0
    limit: int = -1


@dataclass
class ReturnClauseAst:
    columns: Optional[List[YieldColumn]] = None   # None = RETURN *
    distinct: bool = False
    order_by: List[OrderFactor] = field(default_factory=list)
    skip: int = 0
    limit: int = -1


@dataclass
class MatchSentence(Sentence):
    clauses: List[Any]                   # Match/Unwind/With clause asts in order
    return_: ReturnClauseAst = None
