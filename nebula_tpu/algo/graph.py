"""Flat edge-array form of a CsrSnapshot for whole-graph algorithms.

PageRank/WCC/SSSP touch EVERY edge every iteration, so the traversal
plane's budgeted frontier expansion (escalating EB buckets, overflow
retries) is the wrong shape — the right one is the dense SpMV /
segment-sum form of PAPERS.md (BLEST; Sparse GNNs on Dense Hardware):
one flat (E,) edge list with global dense endpoint ids, and per-vertex
state as one flat (P*vmax,) array indexed directly by dense id
(dense = local * P + part, so the id space is exactly [0, P*vmax)).

Built ONCE per (snapshot epoch, block set, weight prop) from the HOST
CsrSnapshot with vectorized numpy (np.repeat over indptr diffs — no
per-edge Python), then device_put once and reused by every iteration
kernel.  Degree-split hub rows map through `hub_dense` exactly like
the expansion kernels do.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..graphstore.csr import INT_NULL, CsrSnapshot


@dataclass
class AlgoGraph:
    """One algorithm run's graph view: flat edges + vertex-id space."""
    n_slots: int                      # P * vmax — state-array length
    n_vertices: int                   # real (non-phantom) vertices
    esrc: np.ndarray                  # (E,) int64 global dense src
    edst: np.ndarray                  # (E,) int64 global dense dst
    weight: Optional[np.ndarray]      # (E,) float64, or None (unweighted)
    vmask: np.ndarray                 # (n_slots,) bool — real vertices
    dense_to_vid: List                # dense id → vid (None = phantom)

    @property
    def n_edges(self) -> int:
        return int(self.esrc.size)

    def out_degree(self) -> np.ndarray:
        """(n_slots,) float64 out-degrees over the selected edge set."""
        return np.bincount(self.esrc, minlength=self.n_slots) \
            .astype(np.float64)

    def by_dst(self):
        """Destination-sorted edge view (computed once, cached):
        -> (order, esrc_sorted, edst_sorted, starts) where starts is
        the (n_slots+1,) CSC-style segment index into the sorted
        arrays.  The device kernels run on THIS order — PageRank's
        combine becomes a prefix-sum segment reduction (5× the XLA CPU
        scatter-add) and the min-combines pass indices_are_sorted
        (min is exactly order-independent, so sorting never changes
        WCC/SSSP results)."""
        cached = getattr(self, "_by_dst", None)
        if cached is None:
            order = np.argsort(self.edst, kind="stable")
            edst_s = self.edst[order]
            starts = np.searchsorted(
                edst_s, np.arange(self.n_slots + 1, dtype=np.int64))
            cached = (order, self.esrc[order], edst_s, starts)
            self._by_dst = cached
        return cached


def blocks_for(snap: CsrSnapshot, etypes: Optional[Sequence[str]],
               direction: str) -> List[Tuple[str, str]]:
    """(etype, direction) block keys for an algorithm's edge set.
    etypes=None selects every edge type present in the snapshot."""
    if etypes is None:
        names = sorted({et for et, _ in snap.blocks})
    else:
        names = [etypes] if isinstance(etypes, str) else list(etypes)
    keys: List[Tuple[str, str]] = []
    for et in names:
        if direction in ("out", "both"):
            keys.append((et, "out"))
        if direction in ("in", "both"):
            keys.append((et, "in"))
    missing = [k for k in keys if k not in snap.blocks]
    if missing:
        raise KeyError(f"snapshot has no CSR block(s) {missing}")
    return keys


def _decode_weight(raw: np.ndarray) -> np.ndarray:
    """Numeric edge-prop column → float64 weights; NULL weighs 1.0
    (documented lenient default — a missing weight must not silently
    poison a whole run with NaN/INT_NULL sentinels)."""
    if raw.dtype.kind == "f":
        w = raw.astype(np.float64, copy=True)
        w[np.isnan(w)] = 1.0
        return w
    w = raw.astype(np.float64)
    w[raw == INT_NULL] = 1.0
    return w


def build_algo_graph(snap: CsrSnapshot,
                     block_keys: Sequence[Tuple[str, str]],
                     weight_prop: Optional[str] = None) -> AlgoGraph:
    """Flatten the selected CSR blocks into one (E,) edge list."""
    P, vmax = snap.num_parts, snap.vmax
    hub_dense = np.asarray(
        getattr(snap, "hub_dense", None)
        if getattr(snap, "hub_dense", None) is not None else [],
        np.int64)
    srcs, dsts, ws = [], [], []
    for bk in block_keys:
        b = snap.blocks[bk]
        indptr = np.asarray(b.indptr, np.int64)       # (P, R+1)
        nbr = np.asarray(b.nbr)
        R = indptr.shape[1] - 1                       # vmax (+ hub rows)
        deg = indptr[:, 1:] - indptr[:, :-1]          # (P, R)
        rows_all = np.arange(R, dtype=np.int64)
        wcol = None
        if weight_prop is not None:
            if weight_prop not in b.props:
                raise KeyError(
                    f"edge type `{b.etype}' has no prop "
                    f"`{weight_prop}'")
            wcol = np.asarray(b.props[weight_prop])
            if wcol.dtype.kind not in "fiu":
                raise ValueError(
                    f"weight prop `{weight_prop}' is not numeric")
        for p in range(P):
            n_e = int(indptr[p, -1])
            if n_e == 0:
                continue
            rows = np.repeat(rows_all, deg[p])        # (n_e,)
            if hub_dense.size:
                src = np.where(
                    rows < vmax, rows * P + p,
                    hub_dense[np.clip(rows - vmax, 0,
                                      hub_dense.size - 1)])
            else:
                src = rows * P + p
            dst = nbr[p, :n_e].astype(np.int64)
            ok = dst >= 0
            srcs.append(src[ok] if not ok.all() else src)
            dsts.append(dst[ok] if not ok.all() else dst)
            if wcol is not None:
                w = _decode_weight(wcol[p, :n_e])
                ws.append(w[ok] if not ok.all() else w)

    def _cat(parts, dtype):
        if not parts:
            return np.empty(0, dtype)
        return np.concatenate(parts).astype(dtype, copy=False)

    esrc = _cat(srcs, np.int64)
    edst = _cat(dsts, np.int64)
    weight = _cat(ws, np.float64) if weight_prop is not None else None

    n_slots = max(P * vmax, 1)
    # a vertex EXISTS for the algo plane when it has a tag row or is
    # incident to a selected edge: a DELETE VERTEX leaves its dense
    # slot behind (dense ids are stable), so dense_to_vid alone would
    # resurrect deleted vertices; tag-presence ∪ edge-endpoints is the
    # contract both the device kernels and the oracles share
    present = np.zeros(n_slots, bool)
    for t in snap.tags.values():
        pres = np.asarray(t.present)                  # (P, vmax)
        present |= pres.T.reshape(-1)[:n_slots]       # [local*P + p]
    if esrc.size:
        present[esrc] = True
        present[edst] = True
    d2v = list(snap.dense_to_vid)
    named = np.zeros(n_slots, bool)
    live = [i for i, v in enumerate(d2v) if v is not None]
    if live:
        named[np.asarray(live, np.int64)] = True
    vmask = named & present
    return AlgoGraph(n_slots=n_slots, n_vertices=int(vmask.sum()),
                     esrc=esrc, edst=edst, weight=weight,
                     vmask=vmask, dense_to_vid=d2v)
