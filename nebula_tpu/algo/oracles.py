"""Independent numpy host oracles for the algo plane — the parity
contract (ISSUE 13).

Each oracle deliberately uses a DIFFERENT algorithm family than the
device kernels so parity tests compare two implementations that share
nothing but the graph:

  * pagerank_np — classic power iteration with np.add.at (the device
    kernel is a jax segment scatter-add); same math, independent
    summation order, so equality is within float tolerance.
  * wcc_np      — union-find with path compression (the device kernel
    is min-label propagation); results are EXACT integers.
  * sssp_np     — Dijkstra over adjacency lists with a heap (the
    device kernel is Bellman-Ford-style frontier relaxation); exact
    for integer weights (float64 path sums below 2**53 are exact).

All three operate on the AlgoGraph flat form and return the same
state-array shapes the device drivers produce, so row assembly is one
shared code path (engine.py) and host-mode execution IS the oracle.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from .graph import AlgoGraph

BIG = np.iinfo(np.int64).max


def pagerank_np(g: AlgoGraph, damping: float, max_iter: int,
                tol: float, check=None) -> Tuple[np.ndarray, int]:
    """-> (rank (n_slots,) float64 — 0 on phantom slots, iterations).
    `check` (when given) is called before every iteration — the engine
    passes the cancel check so KILL QUERY / query_timeout land between
    host-oracle iterations exactly as on the device path."""
    n = max(g.n_vertices, 1)
    rank = np.where(g.vmask, 1.0 / n, 0.0)
    outdeg = g.out_degree()
    out_inv = np.zeros(g.n_slots)
    nz = outdeg > 0
    out_inv[nz] = 1.0 / outdeg[nz]
    dangling = g.vmask & ~nz
    iters = 0
    for _ in range(max_iter):
        if check is not None:
            check()
        iters += 1
        contrib = rank * out_inv
        acc = np.zeros(g.n_slots)
        np.add.at(acc, g.edst, contrib[g.esrc])
        base = (1.0 - damping + damping * rank[dangling].sum()) / n
        new = np.where(g.vmask, base + damping * acc, 0.0)
        delta = np.abs(new - rank).sum()
        rank = new
        if delta < tol:
            break
    return rank, iters


def wcc_np(g: AlgoGraph) -> np.ndarray:
    """-> component (n_slots,) int64: each real vertex's component id =
    the smallest dense id in its component; BIG on phantom slots."""
    parent = np.arange(g.n_slots, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:        # path compression
            parent[x], x = root, parent[x]
        return root

    for u, v in zip(g.esrc.tolist(), g.edst.tolist()):
        ru, rv = find(u), find(v)
        if ru != rv:
            # union by MIN id — the root is the component id
            if ru < rv:
                parent[rv] = ru
            else:
                parent[ru] = rv
    comp = np.full(g.n_slots, BIG, np.int64)
    for d in np.flatnonzero(g.vmask).tolist():
        comp[d] = find(d)
    return comp


def sssp_np(g: AlgoGraph, src_dense: int) -> np.ndarray:
    """-> dist (n_slots,) float64 (inf unreached), Dijkstra."""
    import heapq
    dist = np.full(g.n_slots, np.inf)
    if not (0 <= src_dense < g.n_slots) or not g.vmask[src_dense]:
        return dist
    # adjacency lists from the flat edge form (one argsort, no Python
    # per-edge loop to build)
    order = np.argsort(g.esrc, kind="stable")
    s_sorted = g.esrc[order]
    starts = np.searchsorted(s_sorted, np.arange(g.n_slots + 1))
    dst_sorted = g.edst[order]
    w_sorted = (g.weight[order] if g.weight is not None
                else np.ones(order.size))
    dist[src_dense] = 0.0
    heap = [(0.0, src_dense)]
    done = np.zeros(g.n_slots, bool)
    while heap:
        d, u = heapq.heappop(heap)
        if done[u]:
            continue
        done[u] = True
        for i in range(int(starts[u]), int(starts[u + 1])):
            v = int(dst_sorted[i])
            nd = d + float(w_sorted[i])
            if nd < dist[v]:
                dist[v] = nd
                heapq.heappush(heap, (nd, v))
    return dist
