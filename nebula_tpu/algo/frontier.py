"""The shared frontier-iteration step (ISSUE 13 satellite).

Before this module, the per-level "expand the frontier bitmap through
every CSR block, apply the predicate, mark candidate destinations"
body lived INSIDE tpu/bfs.py's two kernel builders (local and sharded),
so any new frontier-style program would have re-implemented it.  The
step now lives here, defined once:

  * `expand_part`        — one part × one block expansion + predicate
                           mask (the former bfs `one_part`, including
                           the bottom-up endpoint swap);
  * `top_down_step`      — single-chip level body: expand every block
                           from the frontier bitmap, OR the ownership
                           marks (the degenerate all_to_all);
  * `bottom_up_step`     — single-chip direction-optimizing level body:
                           unvisited vertices scan their REVERSE
                           adjacency against the resident frontier
                           bitmap (no routing exchange at all);
  * `sharded_level_step` — the shard_map level body: expand + mark,
                           the caller exchanges marks over ICI.

tpu/bfs.py composes its kernels from these; the vertex-program engine
(algo/engine.py) drives its frontier-style algorithms through the same
helpers when a program is expansion-shaped (the dense whole-edge-list
algorithms — PageRank's SpMV — use the flat form in algo/graph.py
instead, which has no frontier to expand).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tpu.hop import _expand_block, _mark, _merge_delta

__all__ = ["expand_part", "top_down_step", "bottom_up_step",
           "sharded_level_step"]


def expand_part(block, fbm, pid, EB: int, P: int, vmax: int,
                pred=None, pred_cols=(), hub_dense=None,
                swap_ends: bool = False):
    """Expand ONE part's frontier bitmap through ONE block and apply
    the compiled edge predicate.

    `swap_ends` is the bottom-up contract: $^/$$ are TRAVERSAL
    source/destination, and bottom-up expands the REVERSE adjacency,
    so the expansion source is the traversal DESTINATION (the newly
    reached vertex) and the neighbor is the frontier side — the
    endpoint columns the predicate sees are swapped.

    Returns (src, dst, keep, total, ovf) per the _expand_block slot
    contract with the predicate folded into `keep`."""
    src, dst, rk, eidx, ve, total, ovf = _expand_block(
        block["indptr"], block["nbr"], block["rank"], fbm, EB, P,
        pid, vmax_local=vmax, hub_dense=hub_dense)
    dcap = 0
    if not swap_ends and "d_src" in block:
        # ISSUE 19: merge the device-resident delta plane (tombstone
        # base slots, append live delta edges) before the predicate so
        # fresh writes flow through the same filter.  Bottom-up never
        # takes this path — the runtime disables direction-optimizing
        # while a delta is live (the reverse adjacency has no delta).
        dcap = block["d_src"].shape[-1]
        src, dst, rk, eidx, ve, total = _merge_delta(
            block, fbm, src, dst, rk, eidx, ve, total, P, pid,
            block["nbr"].shape[-1])
    if pred is not None:
        ps, pd = (dst, src) if swap_ends else (src, dst)
        cols = {"_rank": rk, "_src": ps, "_dst": pd}
        for name in pred_cols:
            if not name.startswith("_"):
                c = block["props"][name]
                if dcap:
                    c = jnp.concatenate([c, block["d_props"][name]])
                cols[name] = c[eidx]
        keep = pred(cols) & ve
    else:
        keep = ve
    return src, dst, keep, total, ovf


def top_down_step(blocks_data, efbm, EB: int, P: int, vmax: int, pids,
                  pred=None, pred_cols=(), hub_dense=None):
    """Single-chip level body, forward direction: expand every block
    from the (possibly hub-extended) frontier bitmap `efbm`, mark
    destinations in the (P, vmax) ownership bitmap, OR-reduce the
    per-source mark matrices (the degenerate all_to_all).

    -> (cand (P, vmax) bool, edges (P,) i32, ovf (P,) bool)."""
    marks = None
    edges = jnp.zeros((P,), jnp.int32)
    ovf = jnp.zeros((P,), bool)
    for bi in range(len(blocks_data)):
        b = blocks_data[bi]
        # vmap the whole block dict: every leaf (indptr/nbr/rank/props
        # and the d_* delta plane when present) has a leading part axis
        _s, dst, keep, total, ov = jax.vmap(
            lambda blk, f, pd: expand_part(
                blk, f, pd, EB, P, vmax,
                pred=pred, pred_cols=pred_cols, hub_dense=hub_dense)
        )(b, efbm, pids)
        ovf = ovf | ov
        edges = edges + total
        blk_marks = jax.vmap(
            lambda d, k: _mark(d, k, P, vmax))(dst, keep)
        marks = blk_marks if marks is None else marks | blk_marks
    return marks.any(axis=0), edges, ovf


def bottom_up_step(blocks_data, fbm, eunvis, EB: int, P: int,
                   vmax: int, pids, pred=None, pred_cols=(),
                   hub_dense=None):
    """Single-chip direction-optimizing level body: expand the REVERSE
    adjacency of unvisited vertices (`eunvis`, hub-extended by the
    caller); a vertex joins the frontier if any in-neighbor's bit is
    set in the resident frontier bitmap `fbm`.  Needs NO routing
    exchange: each owner decides its own vertices from the global
    bitmap.

    -> (cand (P, vmax) bool, edges (P,) i32, ovf (P,) bool)."""
    cand = jnp.zeros((P, vmax), bool)
    edges = jnp.zeros((P,), jnp.int32)
    ovf = jnp.zeros((P,), bool)
    for bi in range(len(blocks_data)):
        b = blocks_data[bi]
        src, nb, keep, total, ov = jax.vmap(
            lambda ip, nbr, rkk, prp, f, pd: expand_part(
                {"indptr": ip, "nbr": nbr, "rank": rkk,
                 "props": prp}, f, pd, EB, P, vmax,
                pred=pred, pred_cols=pred_cols, hub_dense=hub_dense,
                swap_ends=True)
        )(b["rev_indptr"], b["rev_nbr"], b["rev_rank"],
          b.get("rev_props", {}), eunvis, pids)
        ovf = ovf | ov
        edges = edges + total
        member = fbm[nb % P, nb // P] & keep       # (P, EB)
        # route the reached vertex to its OWNER row (a degree-split
        # hub row's src belongs to another part, so the plain
        # local-index scatter would mis-home it)
        blk = jax.vmap(lambda s, m: _mark(s, m, P, vmax))(src, member)
        cand = cand | blk.any(axis=0)
    return cand, edges, ovf


def sharded_level_step(blocks_data, efbm, EB: int, P: int, pid,
                       vmax: int, pred=None, pred_cols=(),
                       hub_dense=None):
    """shard_map level body (one part per chip): expand every block
    from this shard's (hub-extended) expansion bitmap and accumulate
    the (P, vmax) mark matrix; the caller ships row d to part d with
    the packed all_to_all exchange.

    -> (marks (P, vmax) bool, edges () i32, ovf () bool)."""
    marks = None
    edges = jnp.zeros((), jnp.int32)
    ovf = jnp.zeros((), bool)
    for bi in range(len(blocks_data)):
        b = blocks_data[bi]
        blk = {"indptr": b["indptr"][0], "nbr": b["nbr"][0],
               "rank": b["rank"][0],
               "props": {n: v[0]
                         for n, v in b.get("props", {}).items()}}
        if "d_src" in b:
            for k in ("d_src", "d_dst", "d_rank", "d_valid", "d_tomb"):
                blk[k] = b[k][0]
            blk["d_props"] = {n: v[0]
                              for n, v in b.get("d_props", {}).items()}
        _s, dst, keep, total, ov = expand_part(
            blk, efbm, pid, EB, P, vmax,
            pred=pred, pred_cols=pred_cols, hub_dense=hub_dense)
        ovf = ovf | ov
        edges = edges + total
        marks = _mark(dst, keep, P, vmax, marks)
    return marks, edges, ovf
