"""`CALL algo.*` execution driver (ISSUE 13 tentpole).

One shared iterative vertex-program loop drives all three algorithms:
dense per-vertex state arrays + an edge-propagate/combine/apply step
compiled as ONE jitted kernel per iteration (algo/kernels.py), with
convergence/max-iteration termination decided on the HOST between
dispatches.  That host-side seam is the whole point for a production
engine: between iterations the statement

  * stamps live progress into its LiveQuery row — SHOW QUERIES shows
    `algo.pagerank[iter k/K active_frontier=N]` while it runs;
  * runs the PR 5 cancel check — KILL QUERY and query_timeout land
    BETWEEN iterations with partial state discarded;
  * hits the `algo:iter` failpoint (deterministic delay/raise for the
    kill/stall tests);
  * emits `algo_iterations` / `algo_iter_us` and a `tpu:algo_iter`
    trace span per device dispatch.

Execution modes (the `mode` parameter): `auto` uses the device plane
when a TpuRuntime serves the space and falls back to the numpy host
oracles otherwise (`algo_fallback` counts why); `device` errors
instead of falling back; `host` forces the oracle (the bench A/B
lever).  Both paths share graph preparation (algo/graph.py) and row
assembly, so rows are identical by construction up to PageRank's
documented float tolerance.

The distributed store is not yet served: algorithms need the dense
CSR snapshot (graphd-resident or device-pinned); ROADMAP item 1's
sharded mesh is where the partitioned variant lands.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import ALGORITHMS, DEFAULT_MAX_ITER, REQUIRED, _DIRECTIONS, _MODES
from .graph import AlgoGraph, blocks_for, build_algo_graph
from .oracles import BIG, pagerank_np, sssp_np, wcc_np


class AlgoError(Exception):
    """User-facing algo-plane error (the executor re-raises as
    ExecError so the client sees ExecutionError: ...)."""


# -- graph preparation (shared by both modes) -------------------------------

#: host-snapshot LRU for stores WITHOUT a device runtime (a runtime's
#: pin() already caches per epoch); key (space, store uid) → (epoch, snap)
_snap_cache: Dict[Tuple, Tuple[int, Any]] = {}
#: flat-edge LRU; key (id(snap), blocks, weight) → (snap ref, AlgoGraph)
_graph_cache: Dict[Tuple, Tuple[Any, AlgoGraph]] = {}
#: device-resident edge arrays; same key → (snap ref, dict of jax
#: arrays).  BOTH id(snap)-keyed caches hold the snapshot itself: a
#: key is only reachable while its snapshot is alive, so a recycled
#: object id can never serve another graph's arrays.
_dev_cache: Dict[Tuple, Tuple[Any, Dict[str, Any]]] = {}


def _lru_put(cache: Dict, key, value, cap: int = 4):
    cache[key] = value
    while len(cache) > cap:
        cache.pop(next(iter(cache)))


def _lru_get(cache: Dict, key):
    """Dict-as-LRU read: re-insert on hit so eviction tracks RECENCY,
    not insertion order (a hot entry must survive a cold parade)."""
    ent = cache.pop(key, None)
    if ent is not None:
        cache[key] = ent
    return ent


def _host_snapshot(qctx, space: str):
    """-> (CsrSnapshot, space-data) for the statement's space, or
    raise AlgoError when the store has no dense-snapshot form."""
    store = qctx.store
    snap = getattr(store, "snap", None)
    if snap is not None:                 # prebuilt bench SnapshotStore
        return snap, store.space(space)
    try:
        sd = store.space(space)
        sd.dense_id
    except AttributeError:
        raise AlgoError(
            "CALL algo.* needs the dense-snapshot store (standalone "
            "engine or device-pinned space); the distributed store "
            "is not yet served") from None
    rt = getattr(qctx, "tpu_runtime", None)
    if rt is not None:
        dev = rt.pin(store, space)
        hd = dev.delta.host if dev.delta is not None else None
        if hd is not None and (hd.total_edges() or hd.total_tombs()):
            # algorithms read the BASE host CSR directly — pending delta
            # edges live only in the mirror, so fold them in with a full
            # re-pin before handing the adjacency out (ISSUE 19)
            dev = rt.pin(store, space, force=True)
        return dev.host, sd
    key = (space, getattr(sd, "uid", None) or id(sd))
    ent = _lru_get(_snap_cache, key)
    if ent is not None and ent[0] == sd.epoch:
        return ent[1], sd
    from ..graphstore.csr import build_snapshot
    snap = build_snapshot(store, space)
    _lru_put(_snap_cache, key, (sd.epoch, snap))
    return snap, sd


def _algo_graph(snap, block_keys, weight_prop) -> AlgoGraph:
    key = (id(snap), tuple(block_keys), weight_prop)
    ent = _lru_get(_graph_cache, key)
    if ent is not None:
        return ent[1]
    g = build_algo_graph(snap, block_keys, weight_prop)
    _lru_put(_graph_cache, key, (snap, g))
    return g


def _device_edges(rt, snap, block_keys, weight_prop,
                  g: AlgoGraph) -> Dict[str, Any]:
    """Device-resident flat edge arrays, uploaded once per (snapshot,
    block set, weight) and reused by every iteration and every run.

    Edges go up DST-SORTED (AlgoGraph.by_dst): PageRank's combine is
    then a prefix-sum segment reduction and the min-combines pass
    indices_are_sorted — min is exactly order-independent, so the
    sort can never change WCC/SSSP results."""
    import jax
    key = (id(snap), tuple(block_keys), weight_prop)
    ent = _lru_get(_dev_cache, key)
    if ent is not None:
        return ent[1]
    order, esrc_s, edst_s, starts = g.by_dst()
    dev0 = rt.mesh.devices.reshape(-1)[0]
    arrs = {
        "esrc": jax.device_put(esrc_s.astype(np.int32), dev0),
        "edst": jax.device_put(edst_s.astype(np.int32), dev0),
        "starts": jax.device_put(starts, dev0),
        "vmask": jax.device_put(g.vmask, dev0),
    }
    if g.weight is not None:
        arrs["weight"] = jax.device_put(g.weight[order], dev0)
    _lru_put(_dev_cache, key, (snap, arrs))
    return arrs


# -- parameter resolution ---------------------------------------------------


def resolve_params(func: str, given: Dict[str, Any]) -> Dict[str, Any]:
    """Defaults + type/range checks on the literal parameter values
    (the validator already vetted names/required/yields)."""
    spec = ALGORITHMS[func]
    p = {k: v for k, v in spec.params.items() if v is not REQUIRED}
    p.update(given)
    if p.get("mode") not in _MODES:
        raise AlgoError(f"mode must be one of {_MODES}")
    if "direction" in p and p["direction"] not in _DIRECTIONS:
        raise AlgoError(f"direction must be one of {_DIRECTIONS}")
    mi = p.get("max_iter")
    if not isinstance(mi, int) or isinstance(mi, bool) or mi < 0:
        raise AlgoError("max_iter must be a non-negative integer")
    if func == "pagerank":
        d = p["damping"]
        if not isinstance(d, (int, float)) or not 0.0 < float(d) < 1.0:
            raise AlgoError("damping must be in (0, 1)")
        t = p["tol"]
        if not isinstance(t, (int, float)) or float(t) < 0:
            raise AlgoError("tol must be non-negative")
    if func == "sssp":
        w = p.get("weight")
        if w is not None and not isinstance(w, str):
            raise AlgoError("weight must name an edge prop (string)")
    et = p.get("edge_types")
    if isinstance(et, str):
        p["edge_types"] = [et]
    elif et is not None and not (isinstance(et, list)
                                 and all(isinstance(x, str) for x in et)):
        raise AlgoError("edge_types must be a string or list of strings")
    return p


def _effective_max_iter(func: str, params: Dict[str, Any],
                        g: AlgoGraph) -> int:
    k = int(params.get("max_iter") or 0)
    if k > 0:
        return k
    dflt = DEFAULT_MAX_ITER[func]
    if func in ("wcc", "sssp"):
        # both converge within the graph diameter; n_vertices bounds it
        return max(min(dflt, max(g.n_vertices, 1)), 1)
    return dflt


# -- the shared iteration loop ----------------------------------------------


def _iterate(name: str, max_iter: int, live, body,
             iter_us: Optional[List[int]] = None) -> int:
    """Drive `body(it) -> (active, converged)` with the per-iteration
    contract: cancel check (kill/deadline land HERE, between
    iterations), the `algo:iter` failpoint, the `tpu:algo_iter` span,
    `algo_*` metrics, and the live-progress stamp SHOW QUERIES
    renders.  Returns the iterations actually run; `iter_us` (when
    given) collects per-iteration wall µs — the bench's A/B probe."""
    from ..utils import cancel as _cancel
    from ..utils import trace
    from ..utils.failpoints import fail
    from ..utils.stats import stats
    iters = 0
    for it in range(1, max_iter + 1):
        _cancel.check()
        fail.hit("algo:iter", key=name)
        t0 = time.perf_counter()
        with trace.span("tpu:algo_iter", algo=name, iteration=it):
            active, converged = body(it)
        us = int((time.perf_counter() - t0) * 1e6)
        stats().inc_labeled("algo_iterations", {"algo": name})
        stats().observe("algo_iter_us", us, {"algo": name})
        if iter_us is not None:
            iter_us.append(us)
        if live is not None:
            live.set_operator(f"algo.{name}[iter {it}/{max_iter} "
                              f"active_frontier={int(active)}]")
        iters = it
        if converged:
            break
    # a kill/deadline that landed during the LAST body must still win
    _cancel.check()
    return iters


# -- device drivers ---------------------------------------------------------


def _device_pagerank(rt, snap, block_keys, g, params, live,
                     iter_us=None):
    import jax
    from . import kernels
    dev = _device_edges(rt, snap, block_keys, None, g)
    damping, tol = float(params["damping"]), float(params["tol"])
    step = kernels.pagerank_step(g.n_slots, damping, tol)
    n = float(max(g.n_vertices, 1))
    outdeg = g.out_degree()
    out_inv = np.zeros(g.n_slots)
    nz = outdeg > 0
    out_inv[nz] = 1.0 / outdeg[nz]
    _order, esrc_s, _edst_s, _starts = g.by_dst()
    dev0 = rt.mesh.devices.reshape(-1)[0]
    # per-edge 1/outdeg pre-gathered once (static within a run): the
    # iteration kernel then needs ONE gather per edge, not two
    out_inv_e = jax.device_put(out_inv[esrc_s], dev0)
    dmask_d = jax.device_put(g.vmask & ~nz, dev0)
    state = {"rank": jax.device_put(
        np.where(g.vmask, 1.0 / n, 0.0), dev0)}
    K = _effective_max_iter("pagerank", params, g)

    def body(it):
        (rank, delta, active), _us = rt.algo_dispatch(
            "algo.pagerank", step, state["rank"], dev["esrc"],
            dev["starts"], out_inv_e, dmask_d, dev["vmask"], n)
        state["rank"] = rank
        return int(active), float(delta) < tol

    iters = _iterate("pagerank", K, live, body, iter_us)
    return np.asarray(state["rank"]), iters


def _device_wcc(rt, snap, block_keys, g, params, live, iter_us=None):
    import jax
    from . import kernels
    dev = _device_edges(rt, snap, block_keys, None, g)
    step = kernels.wcc_step(g.n_slots)
    dev0 = rt.mesh.devices.reshape(-1)[0]
    label0 = np.where(g.vmask, np.arange(g.n_slots, dtype=np.int64),
                      BIG)
    state = {"label": jax.device_put(label0, dev0),
             "active": dev["vmask"]}
    K = _effective_max_iter("wcc", params, g)

    def body(it):
        (label, active, changed), _us = rt.algo_dispatch(
            "algo.wcc", step, state["label"], state["active"],
            dev["esrc"], dev["edst"])
        state["label"], state["active"] = label, active
        return int(changed), int(changed) == 0

    iters = _iterate("wcc", K, live, body, iter_us)
    return np.asarray(state["label"]), iters


def _device_sssp(rt, snap, block_keys, g, params, live, src_dense,
                 iter_us=None):
    import jax
    from . import kernels
    weight_prop = params.get("weight")
    dev = _device_edges(rt, snap, block_keys, weight_prop, g)
    step = kernels.sssp_step(g.n_slots, weight_prop is not None)
    dev0 = rt.mesh.devices.reshape(-1)[0]
    dist0 = np.full(g.n_slots, np.inf)
    dist0[src_dense] = 0.0
    front0 = np.zeros(g.n_slots, bool)
    front0[src_dense] = True
    state = {"dist": jax.device_put(dist0, dev0),
             "front": jax.device_put(front0, dev0)}
    K = _effective_max_iter("sssp", params, g)
    extra = (dev["weight"],) if weight_prop is not None else ()

    def body(it):
        (dist, front, changed), _us = rt.algo_dispatch(
            "algo.sssp", step, state["dist"], state["front"],
            dev["esrc"], dev["edst"], *extra)
        state["dist"], state["front"] = dist, front
        return int(changed), int(changed) == 0

    iters = _iterate("sssp", K, live, body, iter_us)
    return np.asarray(state["dist"]), iters


# -- row assembly (one code path for device AND host rows) ------------------


def assemble_rows(func: str, g: AlgoGraph,
                  state: np.ndarray) -> List[List[Any]]:
    """Final state array → full-width rows, ordered by vid (the
    documented deterministic order — identical for device and host
    because both sort the same vid domain the same way)."""
    d2v = g.dense_to_vid
    out: List[List[Any]] = []
    live = np.flatnonzero(g.vmask).tolist()
    if func == "pagerank":
        for d in live:
            out.append([d2v[d], float(state[d])])
    elif func == "wcc":
        for d in live:
            out.append([d2v[d], d2v[int(state[d])]])
    else:  # sssp: reached vertices only
        for d in live:
            v = float(state[d])
            if np.isfinite(v):
                out.append([d2v[d], v])
    try:
        out.sort(key=lambda r: r[0])
    except TypeError:        # heterogeneous vids: canonical repr order
        out.sort(key=lambda r: repr(r[0]))
    return out


# -- the shared driver (executor AND bench entry point) ---------------------


def run_algorithm(func: str, params: Dict[str, Any], snap, sd,
                  rt=None, live=None, iter_us: Optional[List[int]] = None,
                  on_fallback=None):
    """Run one algorithm against a host CsrSnapshot, device when `rt`
    serves it (per `params['mode']`), numpy oracle otherwise.

    -> (rows, info) where rows are full-width [vid, value] rows in the
    canonical vid order and info = {'mode', 'iterations', 'n_edges',
    'n_vertices'}.  `iter_us` collects per-iteration wall µs on the
    device path (the bench's A/B probe); `on_fallback(exc)` observes
    an auto-mode device failure before the oracle takes over."""
    from ..utils import cancel as _cancel
    from ..utils.stats import stats

    params = resolve_params(func, dict(params))
    direction = params.get("direction", "out")
    if func == "pagerank":
        direction = "out"
    elif func == "wcc":
        direction = "both"
    try:
        block_keys = blocks_for(snap, params.get("edge_types"),
                                direction)
    except KeyError as ex:
        raise AlgoError(str(ex)) from None
    weight_prop = params.get("weight") if func == "sssp" else None
    try:
        g = _algo_graph(snap, block_keys, weight_prop)
    except (KeyError, ValueError) as ex:
        raise AlgoError(str(ex)) from None
    if g.weight is not None and g.n_edges and g.weight.min() < 0:
        raise AlgoError(
            f"algo.sssp requires non-negative weights "
            f"(prop `{weight_prop}' has negative values)")

    src_dense = None
    if func == "sssp":
        try:
            src_dense = sd.dense_id(params["src"])
        except Exception:  # noqa: BLE001 — vid-type mismatch: unknown
            src_dense = -1
        if src_dense is None or src_dense < 0 \
                or not g.vmask[src_dense]:
            # unknown source: no reachable set — empty result, not an
            # error (FIND PATH's missing-vid contract)
            return [], {"mode": "none", "iterations": 0,
                        "n_edges": g.n_edges,
                        "n_vertices": g.n_vertices}

    mode = params["mode"]
    if mode == "device" and rt is None:
        raise AlgoError("mode=device but no device runtime serves "
                        "this engine")

    state, iters, ran_mode = None, 0, "host"
    if mode != "host" and rt is not None:
        from ..tpu.device import TpuUnavailable
        from ..tpu.traverse import _JAX_RT_ERRORS
        try:
            if func == "pagerank":
                state, iters = _device_pagerank(
                    rt, snap, block_keys, g, params, live, iter_us)
            elif func == "wcc":
                state, iters = _device_wcc(
                    rt, snap, block_keys, g, params, live, iter_us)
            else:
                state, iters = _device_sssp(
                    rt, snap, block_keys, g, params, live, src_dense,
                    iter_us)
            ran_mode = "device"
        except (TpuUnavailable,) + _JAX_RT_ERRORS as ex:
            if mode == "device":
                raise AlgoError(f"device execution failed: {ex}") \
                    from ex
            stats().inc_labeled(
                "algo_fallback",
                {"algo": func, "reason": type(ex).__name__})
            if on_fallback is not None:
                on_fallback(ex)
            state = None

    if state is None:                   # host oracle (mode or fallback)
        _cancel.check()
        if live is not None:
            live.set_operator(f"algo.{func}[host oracle]")
        if func == "pagerank":
            state, iters = pagerank_np(
                g, float(params["damping"]),
                _effective_max_iter(func, params, g),
                float(params["tol"]), check=_cancel.check)
        elif func == "wcc":
            state, iters = wcc_np(g), 1
        else:
            state, iters = sssp_np(g, src_dense), 1
        _cancel.check()

    stats().inc_labeled("algo_runs", {"algo": func, "mode": ran_mode})
    return assemble_rows(func, g, state), \
        {"mode": ran_mode, "iterations": iters,
         "n_edges": g.n_edges, "n_vertices": g.n_vertices}


# -- the executor entry point -----------------------------------------------


def run_call_algo(node, qctx, ectx):
    """Executor body for the CallAlgo plan node."""
    from ..core.value import DataSet
    from ..utils.workload import current_live

    a = node.args
    func = a["algo"]
    snap, sd = _host_snapshot(qctx, a["space"])

    def note_fallback(ex):
        qctx.last_tpu_fallback = f"{type(ex).__name__}: {ex}"

    rows, _info = run_algorithm(
        func, a["params"], snap, sd,
        rt=getattr(qctx, "tpu_runtime", None),
        live=current_live(), on_fallback=note_fallback)
    cols = a["yield"]                   # [(col, alias), ...]
    spec = ALGORITHMS[func]
    idx = {c: i for i, c in enumerate(spec.yield_cols)}
    names = [al for _, al in cols]
    sel = [idx[c] for c, _ in cols]
    if sel == list(range(len(spec.yield_cols))):
        return DataSet(names, rows)
    return DataSet(names, [[r[i] for i in sel] for r in rows])
