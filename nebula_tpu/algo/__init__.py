"""Device graph-analytics plane (ISSUE 13): `CALL algo.*`.

A second workload class next to OLTP traversal: whole-graph iterative
algorithms (PageRank, WCC, SSSP) on a shared vertex-program engine —
frontier set + dense per-vertex state arrays + an edge-propagate/
combine/apply step compiled as ONE jitted kernel per iteration, with
convergence/max-iteration termination driven from the host so the
statement reports per-iteration progress in SHOW QUERIES and is
killable between iterations (the PR 7/PR 8 long-running-statement
machinery was built for exactly this shape).

Package layout (this module stays import-light — the query validator
reads the registry without pulling jax):

  * `__init__.py` — the algorithm REGISTRY: names, parameters,
    defaults, yield columns.  Pure python.
  * `frontier.py`  — the shared frontier-expansion step (ONE
    frontier-iteration code path: tpu/bfs.py composes its level
    bodies from these helpers, and frontier-style vertex programs
    use the same step when they go sharded).
  * `graph.py`     — flat edge-array preparation from a CsrSnapshot
    (the SpMV/segment-sum form of PAPERS.md: BLEST, Sparse GNNs on
    Dense Hardware).
  * `kernels.py`   — the per-iteration jitted step kernels.
  * `oracles.py`   — independent numpy host oracles (power iteration,
    union-find, Dijkstra) — the parity contract.
  * `engine.py`    — the `CALL algo.*` executor driver: device loop
    with live progress, cancel checks between iterations, `algo:iter`
    failpoint, `tpu:algo_iter` spans and `algo_*` metrics; host-oracle
    execution when no device runtime serves the space.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

#: sentinel default marking a parameter the caller MUST supply
REQUIRED = object()


@dataclass(frozen=True)
class AlgoSpec:
    """One algorithm's statement surface: its parameter schema and the
    columns its YIELD may project."""
    name: str
    yield_cols: Tuple[str, ...]
    params: Dict[str, Any] = field(default_factory=dict)   # name → default
    description: str = ""


#: parameters every algorithm accepts
_COMMON = {
    # edge types to traverse; None = every edge type in the space.
    # A string names one type; a list of strings names several.
    "edge_types": None,
    # execution mode: auto (device when a runtime serves the space,
    # host oracle otherwise), device (error when unavailable), host
    "mode": "auto",
    # iteration cap; 0 = the algorithm's own default
    "max_iter": 0,
}

ALGORITHMS: Dict[str, AlgoSpec] = {
    "pagerank": AlgoSpec(
        name="pagerank",
        yield_cols=("vid", "rank"),
        params={**_COMMON, "damping": 0.85, "tol": 1e-6},
        description="dense SpMV-style rank push over out-edges with "
                    "dangling-mass correction; rows (vid, rank) "
                    "ordered by vid"),
    "wcc": AlgoSpec(
        name="wcc",
        yield_cols=("vid", "component"),
        params=dict(_COMMON),
        description="weakly connected components by min-label "
                    "hooking / label propagation over both edge "
                    "directions; component = vid of the smallest "
                    "dense id in the component"),
    "sssp": AlgoSpec(
        name="sssp",
        yield_cols=("vid", "distance"),
        params={**_COMMON, "src": REQUIRED, "weight": None,
                "direction": "out"},
        description="single-source shortest paths by weighted frontier "
                    "relaxation; weight names a numeric edge prop "
                    "(NULL weighs 1.0), absent = hop count; rows only "
                    "for reached vertices"),
}

#: iteration caps applied when max_iter=0 (the statement default)
DEFAULT_MAX_ITER = {"pagerank": 20, "wcc": 10_000, "sssp": 100_000}

_MODES = ("auto", "device", "host")
_DIRECTIONS = ("out", "in", "both")


def validate_call(func: str, param_names, yield_names) -> None:
    """Static checks shared by the validator and the engine: known
    algorithm, known parameter names, required parameters present,
    known yield columns.  Raises ValueError with a user-facing
    message."""
    spec = ALGORITHMS.get(func)
    if spec is None:
        known = ", ".join(sorted(ALGORITHMS))
        raise ValueError(f"unknown algorithm `algo.{func}' "
                         f"(known: {known})")
    for p in param_names:
        if p not in spec.params:
            known = ", ".join(sorted(spec.params))
            raise ValueError(f"unknown parameter `{p}' for "
                             f"algo.{func} (known: {known})")
    for p, dflt in spec.params.items():
        if dflt is REQUIRED and p not in param_names:
            raise ValueError(f"algo.{func} requires parameter `{p}'")
    for y in yield_names:
        if y not in spec.yield_cols:
            known = ", ".join(spec.yield_cols)
            raise ValueError(f"algo.{func} cannot YIELD `{y}' "
                             f"(columns: {known})")
