"""Per-iteration jitted step kernels for the vertex-program engine.

Each algorithm's edge-propagate/combine/apply step is ONE jitted
kernel (the tentpole contract): gather source state along the flat
edge list, combine per edge, segment-reduce by destination (scatter
add/min — the segment-sum shape of PAPERS.md), apply the vertex
update, and report the convergence scalars.  The host drives the
iteration loop (algo/engine.py) so termination, progress reporting
and kill checks land BETWEEN dispatches.

State arrays are float64/int64 (x64 is enabled package-wide, see
tpu/__init__.py) so host-oracle parity is exact for the integer
algorithms and tight (documented 1e-9 relative tolerance) for
PageRank.

Kernels are cached per (algorithm, shape signature) — the jit trace
is reused across iterations and runs.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

BIG = np.iinfo(np.int64).max

_cache: Dict[Tuple, object] = {}

#: bound on retained executables: n_slots changes whenever a growing
#: snapshot re-pins with a larger vmax and damping/tol are
#: per-statement parameters, so an unbounded cache would accumulate
#: XLA executables for the process lifetime (the same hazard
#: TpuRuntime._seed_fns caps)
_CACHE_CAP = 32


def _cached(key, build):
    fn = _cache.pop(key, None)      # re-insert on hit: recency order
    if fn is None:
        fn = build()
    _cache[key] = fn
    while len(_cache) > _CACHE_CAP:
        _cache.pop(next(iter(_cache)))
    return fn


def pagerank_step(n_slots: int, damping: float, tol: float):
    """(rank, esrc_s, starts, out_inv_e, dangling_mask, vmask, n) →
    (rank', l1_delta, active) — active counts vertices whose rank
    moved more than tol this iteration (the live-progress number).

    Edges arrive DST-SORTED (AlgoGraph.by_dst), so the per-vertex
    combine is a prefix-sum segment reduction — cs[starts[v+1]] -
    cs[starts[v]] — instead of a scatter-add, which XLA CPU
    serializes (measured 5×).  The prefix-sum order is deterministic
    (same graph → bit-identical ranks run-to-run); vs the oracle's
    np.add.at order it differs within the documented 1e-8 tolerance."""
    def build():
        def step(rank, esrc_s, starts, out_inv_e, dmask, vmask, n):
            contrib = rank[esrc_s] * out_inv_e
            cs = jnp.cumsum(contrib)          # inclusive prefix

            def at(idx):                      # exclusive-prefix gather
                return jnp.where(idx > 0, cs[jnp.maximum(idx - 1, 0)],
                                 0.0)
            acc = at(starts[1:]) - at(starts[:-1])
            base = (1.0 - damping
                    + damping * jnp.sum(jnp.where(dmask, rank, 0.0))) / n
            new = jnp.where(vmask, base + damping * acc, 0.0)
            moved = jnp.abs(new - rank)
            return new, jnp.sum(moved), \
                jnp.sum(moved > tol, dtype=jnp.int64)
        return jax.jit(step)
    return _cached(("pagerank", n_slots, damping, tol), build)


def wcc_step(n_slots: int):
    """(label, active, esrc, edst) → (label', active', changed) —
    min-label hooking: every active vertex pushes its label to its
    neighbors; a vertex whose label drops joins the next frontier."""
    def build():
        def step(label, active, esrc, edst):
            send = jnp.where(active[esrc], label[esrc], BIG)
            cand = jnp.full((n_slots,), BIG, label.dtype).at[edst].min(
                send, indices_are_sorted=True)
            new = jnp.minimum(label, cand)
            changed = new < label
            return new, changed, jnp.sum(changed, dtype=jnp.int64)
        return jax.jit(step)
    return _cached(("wcc", n_slots), build)


def sssp_step(n_slots: int, weighted: bool):
    """(dist, frontier, esrc, edst[, w]) → (dist', frontier', changed)
    — weighted frontier relaxation (Bellman-Ford over the active
    set): frontier vertices push dist+w along their edges, scatter-min
    by destination, and strictly-improved vertices form the next
    frontier (strict `<` guarantees termination even with 0-weight
    cycles)."""
    def build():
        if weighted:
            def step(dist, frontier, esrc, edst, w):
                send = jnp.where(frontier[esrc], dist[esrc] + w,
                                 jnp.inf)
                cand = jnp.full((n_slots,), jnp.inf,
                                dist.dtype).at[edst].min(
                    send, indices_are_sorted=True)
                new = jnp.minimum(dist, cand)
                changed = new < dist
                return new, changed, jnp.sum(changed, dtype=jnp.int64)
        else:
            def step(dist, frontier, esrc, edst):
                send = jnp.where(frontier[esrc], dist[esrc] + 1.0,
                                 jnp.inf)
                cand = jnp.full((n_slots,), jnp.inf,
                                dist.dtype).at[edst].min(
                    send, indices_are_sorted=True)
                new = jnp.minimum(dist, cand)
                changed = new < dist
                return new, changed, jnp.sum(changed, dtype=jnp.int64)
        return jax.jit(step)
    return _cached(("sssp", n_slots, weighted), build)
