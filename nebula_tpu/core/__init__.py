"""Core value model + nGQL semantics kernel (pure Python, no JAX).

The oracle layer for the whole framework: exact null semantics,
three-valued logic, expression evaluation, builtin + aggregate functions.
"""
from .value import (EMPTY, NULL, NULL_BAD_DATA, NULL_BAD_TYPE,
                    NULL_DIV_BY_ZERO, NULL_NAN, NULL_OUT_OF_RANGE,
                    NULL_OVERFLOW, NULL_UNKNOWN_PROP, DataSet, Date, DateTime,
                    Duration, Edge, EmptyValue, NullKind, NullValue, Path,
                    Step, Tag, Time, Vertex, hashable_key, is_empty, is_null,
                    total_order_key, type_name, value_to_string)
from .expr import (AggExpr, AttributeExpr, Binary, Case, DictContext, EdgeExpr,
                   EdgeProp, Expr, ExprContext, ExprEvalError, FunctionCall,
                   InputProp, LabelExpr, LabelTagProp, ListComprehension,
                   ListExpr, Literal, MapExpr, PathBuild, PredicateExpr,
                   Reduce, SetExpr, Slice, SrcProp, Subscript, TypeCast,
                   Unary, VarExpr, VarProp, VertexExpr, DstProp,
                   collect_aggregates, find_kinds, has_aggregate,
                   join_conjuncts, rewrite, split_conjuncts, to_text, walk)
from .functions import FUNCTIONS, cast_value
from .aggregates import apply_aggregate
