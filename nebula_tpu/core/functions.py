"""Builtin scalar function registry — analog of the reference's
FunctionManager (reference: src/common/function/FunctionManager.cpp
[UNVERIFIED — empty mount, SURVEY §0]).

Functions take ``(ctx, args: list)`` and return a Value.  Null handling:
most functions propagate null inputs; type mismatches yield BAD_TYPE.
"""
from __future__ import annotations

import datetime as _dt
import hashlib
import math
import random
import time as _time
from typing import Any, Callable, Dict, List

from .value import (NULL, NULL_BAD_DATA, NULL_BAD_TYPE, DataSet, Date,
                    DateTime, Duration, Edge, NullValue, Path, Time, Vertex,
                    is_empty, is_null, total_order_key, type_name, v_lt,
                    value_to_string)

FUNCTIONS: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        FUNCTIONS[name.lower()] = fn
        return fn
    return deco


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _nullprop(args) -> Any:
    for a in args:
        if is_null(a):
            return a
    return None


def _math1(name: str, f: Callable[[float], float], integer_passthrough=False):
    @register(name)
    def _fn(ctx, args, _f=f, _ip=integer_passthrough):
        n = _nullprop(args)
        if n is not None:
            return n
        v = args[0]
        if not _num(v):
            return NULL_BAD_TYPE
        try:
            r = _f(v)
        except (ValueError, OverflowError):
            return NULL_BAD_DATA
        if _ip and isinstance(v, int) and float(r).is_integer():
            return int(r)
        return r
    return _fn


_math1("abs", abs, integer_passthrough=True)
_math1("floor", lambda v: float(math.floor(v)))
_math1("ceil", lambda v: float(math.ceil(v)))
_math1("ceiling", lambda v: float(math.ceil(v)))
_math1("sqrt", math.sqrt)
_math1("cbrt", lambda v: math.copysign(abs(v) ** (1 / 3), v))
_math1("exp", math.exp)
_math1("exp2", lambda v: 2.0 ** v)
_math1("log", math.log)
_math1("log2", math.log2)
_math1("log10", math.log10)
_math1("sin", math.sin)
_math1("cos", math.cos)
_math1("tan", math.tan)
_math1("asin", math.asin)
_math1("acos", math.acos)
_math1("atan", math.atan)
_math1("sign", lambda v: (v > 0) - (v < 0))


@register("round")
def _round(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if not _num(v):
        return NULL_BAD_TYPE
    places = args[1] if len(args) > 1 else 0
    if not isinstance(places, int):
        return NULL_BAD_TYPE
    # round-half-away-from-zero, like the reference (not banker's rounding)
    scale = 10 ** places
    return math.floor(abs(v) * scale + 0.5) / scale * (1 if v >= 0 else -1)


@register("pow")
def _pow(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    a, b = args[0], args[1]
    if not _num(a) or not _num(b):
        return NULL_BAD_TYPE
    try:
        r = a ** b
    except (OverflowError, ZeroDivisionError):
        return NULL_BAD_DATA
    if isinstance(a, int) and isinstance(b, int) and b >= 0:
        return int(r)
    return float(r)


@register("hypot")
def _hypot(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not _num(args[0]) or not _num(args[1]):
        return NULL_BAD_TYPE
    return math.hypot(args[0], args[1])


@register("rand")
def _rand(ctx, args):
    return random.random()


@register("rand32")
def _rand32(ctx, args):
    if len(args) == 2:
        return random.randrange(args[0], args[1])
    if len(args) == 1:
        return random.randrange(args[0])
    return random.randrange(2**31)


@register("rand64")
def _rand64(ctx, args):
    if len(args) == 2:
        return random.randrange(args[0], args[1])
    return random.randrange(2**63)


@register("pi")
def _pi(ctx, args):
    return math.pi


@register("e")
def _e(ctx, args):
    return math.e


@register("range")
def _range(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not all(isinstance(a, int) for a in args):
        return NULL_BAD_TYPE
    start, end = args[0], args[1]
    step = args[2] if len(args) > 2 else 1
    if step == 0:
        return NULL_BAD_DATA
    return list(range(start, end + (1 if step > 0 else -1), step))


# ---- string ----------------------------------------------------------------


def _str1(name, f):
    @register(name)
    def _fn(ctx, args, _f=f):
        n = _nullprop(args)
        if n is not None:
            return n
        if not isinstance(args[0], str):
            return NULL_BAD_TYPE
        return _f(args[0])
    return _fn


_str1("lower", str.lower)
_str1("tolower", str.lower)
_str1("upper", str.upper)
_str1("toupper", str.upper)
_str1("trim", str.strip)
_str1("ltrim", str.lstrip)
_str1("rtrim", str.rstrip)


@register("reverse")
def _reverse(ctx, args):
    """String or list reversal — the reference overloads one name."""
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if isinstance(v, (str, list)):
        return v[::-1]
    return NULL_BAD_TYPE


@register("length")
def _length(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if isinstance(v, str):
        return len(v)
    if isinstance(v, Path):
        return v.length()
    return NULL_BAD_TYPE


@register("size")
def _size(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if isinstance(v, (str, list, set, dict)):
        return len(v)
    if isinstance(v, DataSet):
        return len(v.rows)
    return NULL_BAD_TYPE


@register("substr")
@register("substring")
def _substr(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    s = args[0]
    if not isinstance(s, str) or not isinstance(args[1], int):
        return NULL_BAD_TYPE
    start = args[1]
    if start < 0:
        return NULL_BAD_DATA
    ln = args[2] if len(args) > 2 else len(s) - start
    if not isinstance(ln, int) or ln < 0:
        return NULL_BAD_DATA if isinstance(ln, int) else NULL_BAD_TYPE
    return s[start:start + ln]


@register("left")
def _left(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str) or not isinstance(args[1], int):
        return NULL_BAD_TYPE
    if args[1] < 0:
        return NULL_BAD_DATA
    return args[0][:args[1]]


@register("right")
def _right(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str) or not isinstance(args[1], int):
        return NULL_BAD_TYPE
    if args[1] < 0:
        return NULL_BAD_DATA
    return args[0][-args[1]:] if args[1] > 0 else ""


@register("replace")
def _replace(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not all(isinstance(a, str) for a in args[:3]):
        return NULL_BAD_TYPE
    return args[0].replace(args[1], args[2])


@register("atan2")
def _atan2(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    a, b = args[0], args[1]
    if not _num(a) or not _num(b):
        return NULL_BAD_TYPE
    return math.atan2(a, b)


@register("split")
def _split(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str) or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    return args[0].split(args[1])


@register("concat")
def _concat(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    out = []
    for a in args:
        if isinstance(a, str):
            out.append(a)
        elif isinstance(a, bool):
            out.append("true" if a else "false")
        elif _num(a):
            out.append(str(a))
        else:
            return NULL_BAD_TYPE
    return "".join(out)


@register("concat_ws")
def _concat_ws(ctx, args):
    if is_null(args[0]) or not isinstance(args[0], str):
        return NULL_BAD_TYPE if not is_null(args[0]) else NULL
    sep = args[0]
    parts = []
    for a in args[1:]:
        if is_null(a):
            continue
        if isinstance(a, str):
            parts.append(a)
        elif isinstance(a, bool):
            parts.append("true" if a else "false")
        elif _num(a):
            parts.append(str(a))
    return sep.join(parts)


@register("lpad")
def _lpad(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    s, size, pad = args
    if not isinstance(s, str) or not isinstance(size, int) or not isinstance(pad, str):
        return NULL_BAD_TYPE
    if size < len(s):
        return s[:size]
    if not pad:
        return s
    fill = (pad * size)[: size - len(s)]
    return fill + s


@register("rpad")
def _rpad(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    s, size, pad = args
    if not isinstance(s, str) or not isinstance(size, int) or not isinstance(pad, str):
        return NULL_BAD_TYPE
    if size < len(s):
        return s[:size]
    if not pad:
        return s
    fill = (pad * size)[: size - len(s)]
    return s + fill


@register("strcasecmp")
def _strcasecmp(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str) or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    a, b = args[0].lower(), args[1].lower()
    return 0 if a == b else (-1 if a < b else 1)


@register("hash")
def _hash(ctx, args):
    v = args[0]
    if isinstance(v, str):
        h = int.from_bytes(hashlib.md5(v.encode()).digest()[:8], "little", signed=True)
        return h
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        return v
    if is_null(v):
        return 0
    return hash(value_to_string(v)) & 0x7FFFFFFFFFFFFFFF


@register("md5")
def _md5(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str):
        return NULL_BAD_TYPE
    return hashlib.md5(args[0].encode()).hexdigest()


@register("sha1")
def _sha1(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str):
        return NULL_BAD_TYPE
    return hashlib.sha1(args[0].encode()).hexdigest()


@register("sha256")
def _sha256(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str):
        return NULL_BAD_TYPE
    return hashlib.sha256(args[0].encode()).hexdigest()


# ---- casts -----------------------------------------------------------------


def cast_value(target: str, v: Any) -> Any:
    if target in ("int", "int64", "integer"):
        return FUNCTIONS["tointeger"](None, [v])
    if target in ("float", "double"):
        return FUNCTIONS["tofloat"](None, [v])
    if target == "bool":
        return FUNCTIONS["toboolean"](None, [v])
    if target == "string":
        return FUNCTIONS["tostring"](None, [v])
    if target == "set":
        return FUNCTIONS["toset"](None, [v])
    return NULL_BAD_TYPE


@register("tointeger")
@register("toint")
def _toint(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, bool):
        return NULL_BAD_TYPE
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        if v != v or v in (math.inf, -math.inf):
            return NULL_BAD_DATA
        return int(v)
    if isinstance(v, str):
        try:
            return int(v.strip())
        except ValueError:
            try:
                return int(float(v.strip()))
            except ValueError:
                return NULL
    return NULL_BAD_TYPE


@register("tofloat")
def _tofloat(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, bool):
        return NULL_BAD_TYPE
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v.strip())
        except ValueError:
            return NULL
    return NULL_BAD_TYPE


@register("toboolean")
def _tobool(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        s = v.strip().lower()
        if s == "true":
            return True
        if s == "false":
            return False
        return NULL
    return NULL_BAD_TYPE


@register("tostring")
def _tostring(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        s = f"{v:.15g}"
        return s if ("." in s or "e" in s or "n" in s or "i" in s) else s + ".0"
    if isinstance(v, int):
        return str(v)
    return value_to_string(v).strip('"')


@register("toset")
def _toset(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, set):
        return v
    if isinstance(v, list):
        try:
            return set(v)
        except TypeError:
            return NULL_BAD_TYPE
    return NULL_BAD_TYPE


# ---- list ------------------------------------------------------------------


@register("head")
def _head(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if not isinstance(v, list):
        return NULL_BAD_TYPE
    return v[0] if v else NULL


@register("last")
def _last(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if not isinstance(v, list):
        return NULL_BAD_TYPE
    return v[-1] if v else NULL


@register("tail")
def _tail(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if not isinstance(v, list):
        return NULL_BAD_TYPE
    return v[1:]


@register("coalesce")
def _coalesce(ctx, args):
    for a in args:
        if not is_null(a) and not is_empty(a):
            return a
    return NULL


@register("keys")
def _keys(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, dict):
        return sorted(v.keys())
    if isinstance(v, (Vertex,)):
        return sorted(v.properties().keys())
    if isinstance(v, Edge):
        return sorted(v.props.keys())
    return NULL_BAD_TYPE


# ---- graph accessors -------------------------------------------------------


@register("id")
def _id(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Vertex):
        return v.vid
    return NULL_BAD_TYPE


@register("tags")
@register("labels")
def _tags(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Vertex):
        return v.tag_names()
    return NULL_BAD_TYPE


@register("properties")
def _properties(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Vertex):
        return v.properties()
    if isinstance(v, Edge):
        return dict(v.props)
    if isinstance(v, dict):
        return v
    return NULL_BAD_TYPE


@register("type")
def _type(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Edge):
        return v.name
    return NULL_BAD_TYPE


@register("typeid")
def _typeid(ctx, args):
    v = args[0]
    if isinstance(v, Edge):
        return v.etype
    return NULL_BAD_TYPE


@register("src")
def _src(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Edge):
        return v.src if v.etype >= 0 else v.dst
    return NULL_BAD_TYPE


@register("dst")
def _dst(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Edge):
        return v.dst if v.etype >= 0 else v.src
    return NULL_BAD_TYPE


@register("rank")
def _rank(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Edge):
        return v.ranking
    return NULL_BAD_TYPE


@register("startnode")
def _startnode(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Path):
        return v.src
    return NULL_BAD_TYPE


@register("endnode")
def _endnode(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Path):
        return v.nodes()[-1]
    return NULL_BAD_TYPE


@register("nodes")
def _nodes(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Path):
        return v.nodes()
    return NULL_BAD_TYPE


@register("relationships")
def _relationships(ctx, args):
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, Path):
        return v.relationships()
    return NULL_BAD_TYPE


@register("hassameedgeinpath")
def _has_same_edge(ctx, args):
    v = args[0]
    if isinstance(v, Path):
        return v.has_duplicate_edges()
    return NULL_BAD_TYPE


@register("hassamevertexinpath")
def _has_same_vertex(ctx, args):
    v = args[0]
    if isinstance(v, Path):
        return v.has_duplicate_vertices()
    return NULL_BAD_TYPE


@register("reversepath")
def _reverse_path(ctx, args):
    from .value import Step
    v = args[0]
    if not isinstance(v, Path):
        return NULL_BAD_TYPE
    nodes = v.nodes()
    p = Path(nodes[-1])
    prev = nodes[-1]
    for i in range(len(v.steps) - 1, -1, -1):
        s = v.steps[i]
        src_v = v.src if i == 0 else v.steps[i - 1].dst
        p.steps.append(Step(src_v, s.name, s.ranking, s.props, -s.etype))
        prev = src_v
    return p


# ---- temporal --------------------------------------------------------------


def _parse_date(s: str):
    try:
        d = _dt.date.fromisoformat(s)
        return Date(d.year, d.month, d.day)
    except ValueError:
        return NULL_BAD_DATA


@register("date")
def _date(ctx, args):
    if not args:
        t = _dt.date.today()
        return Date(t.year, t.month, t.day)
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, str):
        return _parse_date(v)
    if isinstance(v, dict):
        try:
            return Date(v.get("year", 1970), v.get("month", 1), v.get("day", 1))
        except Exception:
            return NULL_BAD_DATA
    if isinstance(v, Date):
        return v
    return NULL_BAD_TYPE


@register("time")
def _time_fn(ctx, args):
    if not args:
        t = _dt.datetime.utcnow()
        return Time(t.hour, t.minute, t.second, t.microsecond)
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, str):
        try:
            t = _dt.time.fromisoformat(v)
            return Time(t.hour, t.minute, t.second, t.microsecond)
        except ValueError:
            return NULL_BAD_DATA
    if isinstance(v, dict):
        return Time(v.get("hour", 0), v.get("minute", 0), v.get("second", 0),
                    v.get("microsecond", 0))
    if isinstance(v, Time):
        return v
    return NULL_BAD_TYPE


@register("datetime")
def _datetime_fn(ctx, args):
    if not args:
        t = _dt.datetime.utcnow()
        return DateTime(t.year, t.month, t.day, t.hour, t.minute, t.second, t.microsecond)
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, str):
        try:
            t = _dt.datetime.fromisoformat(v)
            return DateTime(t.year, t.month, t.day, t.hour, t.minute, t.second, t.microsecond)
        except ValueError:
            return NULL_BAD_DATA
    if isinstance(v, dict):
        return DateTime(v.get("year", 1970), v.get("month", 1), v.get("day", 1),
                        v.get("hour", 0), v.get("minute", 0), v.get("second", 0),
                        v.get("microsecond", 0))
    if isinstance(v, (int, float)):
        t = _dt.datetime.utcfromtimestamp(v)
        return DateTime(t.year, t.month, t.day, t.hour, t.minute, t.second, t.microsecond)
    if isinstance(v, DateTime):
        return v
    return NULL_BAD_TYPE


@register("timestamp")
def _timestamp(ctx, args):
    if not args:
        return int(_time.time())
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, DateTime):
        return v.to_timestamp()
    if isinstance(v, str):
        try:
            t = _dt.datetime.fromisoformat(v)
            return int(t.replace(tzinfo=_dt.timezone.utc).timestamp())
        except ValueError:
            return NULL_BAD_DATA
    return NULL_BAD_TYPE


@register("now")
def _now(ctx, args):
    return int(_time.time())


# Reference format-string subset for date_format/time_format (VERDICT r5
# item 7 — the last deferred FunctionManager entries): strftime-style
# two-char specifiers over the temporal's components.  Specifiers whose
# component the value doesn't carry (e.g. %H over a plain date) and
# unknown specifiers answer NULL_BAD_DATA — a tested refusal, not a
# silent passthrough.
_DATE_SPECS = frozenset("YmdeFjW")
_TIME_SPECS = frozenset("HMiSsfT")


def _format_components(fmt: str, comp: dict):
    out = []
    i, n = 0, len(fmt)
    while i < n:
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        if i + 1 >= n:
            return None
        sp = fmt[i + 1]
        i += 2
        if sp == "%":
            out.append("%")
            continue
        if sp in _DATE_SPECS and "Y" not in comp:
            return None
        if sp in _TIME_SPECS and "H" not in comp:
            return None
        if sp == "Y":
            out.append(f"{comp['Y']:04d}")
        elif sp == "m":
            out.append(f"{comp['m']:02d}")
        elif sp in ("d",):
            out.append(f"{comp['d']:02d}")
        elif sp == "e":
            out.append(str(comp["d"]))
        elif sp == "F":
            out.append(f"{comp['Y']:04d}-{comp['m']:02d}-{comp['d']:02d}")
        elif sp == "j":
            doy = (_dt.date(comp["Y"], comp["m"], comp["d"])
                   - _dt.date(comp["Y"], 1, 1)).days + 1
            out.append(f"{doy:03d}")
        elif sp == "W":
            out.append(_dt.date(comp["Y"], comp["m"],
                                comp["d"]).strftime("%W"))
        elif sp == "H":
            out.append(f"{comp['H']:02d}")
        elif sp in ("M", "i"):
            out.append(f"{comp['M']:02d}")
        elif sp in ("S", "s"):
            out.append(f"{comp['S']:02d}")
        elif sp == "f":
            out.append(f"{comp['f']:06d}")
        elif sp == "T":
            out.append(f"{comp['H']:02d}:{comp['M']:02d}:{comp['S']:02d}")
        else:
            return None
    return "".join(out)


def _temporal_components(v):
    if isinstance(v, DateTime):
        return {"Y": v.year, "m": v.month, "d": v.day, "H": v.hour,
                "M": v.minute, "S": v.sec, "f": v.microsec}
    if isinstance(v, Date):
        return {"Y": v.year, "m": v.month, "d": v.day}
    if isinstance(v, Time):
        return {"H": v.hour, "M": v.minute, "S": v.sec, "f": v.microsec}
    if isinstance(v, int) and not isinstance(v, bool):
        t = _dt.datetime.fromtimestamp(v, _dt.timezone.utc)
        return {"Y": t.year, "m": t.month, "d": t.day, "H": t.hour,
                "M": t.minute, "S": t.second, "f": t.microsecond}
    return None


@register("date_format")
def _date_format(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if len(args) != 2 or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    comp = _temporal_components(args[0])
    if comp is None or "Y" not in comp:
        return NULL_BAD_TYPE
    s = _format_components(args[1], comp)
    return NULL_BAD_DATA if s is None else s


@register("time_format")
def _time_format(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if len(args) != 2 or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    comp = _temporal_components(args[0])
    if comp is None or "H" not in comp:
        return NULL_BAD_TYPE
    s = _format_components(args[1], comp)
    return NULL_BAD_DATA if s is None else s


# ---- internal helpers used by MATCH planning -------------------------------


@register("_hastag")
def _hastag(ctx, args):
    v, tag = args[0], args[1]
    if isinstance(v, Vertex):
        return tag in v.tag_names()
    return False


@register("_exists")
def _exists(ctx, args):
    v = args[0]
    return not is_null(v) and not is_empty(v)


@register("_edges_distinct")
def _edges_distinct(ctx, args):
    """Internal: relationship-uniqueness gate the MATCH planner plants
    when a pattern has two or more edge variables (Cypher relationship
    isomorphism; reference: MATCH edges within one pattern never bind
    the same edge twice).  Each arg is an Edge, a list of Edges (a
    variable-length binding), or NULL (zero-hop) — True iff no edge key
    appears twice across all of them."""
    seen = set()
    for v in args:
        edges = v if isinstance(v, list) else ([] if is_null(v) else [v])
        for e in edges:
            if not isinstance(e, Edge):
                continue
            k = e.key()
            if k in seen:
                return False
            seen.add(k)
    return True


@register("duration")
def _duration(ctx, args):
    if len(args) == 2:
        # two-timestamp overload (reference convenience form): the
        # elapsed Duration t1 → t2, i.e. exactly t2 - t1
        n = _nullprop(args)
        if n is not None:
            return n
        a, b = args
        if isinstance(a, DateTime) and isinstance(b, DateTime):
            # calendar-exact epoch-µs diff (to_timestamp truncates toward
            # zero, which is off by 1s for pre-1970 values with µs)
            def us(v):
                delta = (_dt.datetime(v.year, v.month, v.day, v.hour,
                                      v.minute, v.sec, v.microsec,
                                      tzinfo=_dt.timezone.utc)
                         - _dt.datetime(1970, 1, 1,
                                        tzinfo=_dt.timezone.utc))
                return ((delta.days * 86400 + delta.seconds) * 1_000_000
                        + delta.microseconds)
            diff = us(b) - us(a)
            return Duration(diff // 1_000_000, diff % 1_000_000, 0)
        if (isinstance(a, int) and isinstance(b, int)
                and not isinstance(a, bool) and not isinstance(b, bool)):
            return Duration(int(b - a), 0, 0)
        return NULL_BAD_TYPE
    v = args[0]
    if is_null(v):
        return v
    if isinstance(v, dict):
        secs = (v.get("seconds", 0) + v.get("minutes", 0) * 60
                + v.get("hours", 0) * 3600 + v.get("days", 0) * 86400)
        months = v.get("months", 0) + v.get("years", 0) * 12
        return Duration(int(secs), v.get("microseconds", 0), int(months))
    return NULL_BAD_TYPE


# ---------------------------------------------------------------------------
# Spatial functions — the ST_* family over the Geography value type
# (reference: src/common/function geo functions backed by S2
# [UNVERIFIED — empty mount]; simplifications documented in core/geo.py).
# ---------------------------------------------------------------------------


def _geo_args(args, n):
    from .geo import Geography
    nl = _nullprop(args)
    if nl is not None:
        return nl, None
    if len(args) < n:
        return NULL_BAD_TYPE, None
    for a in args[:n]:
        if not isinstance(a, Geography):
            return NULL_BAD_TYPE, None
    return None, args


@register("st_point")
def _st_point(ctx, args):
    from .geo import Geography
    n = _nullprop(args)
    if n is not None:
        return n
    if len(args) != 2 or not _num(args[0]) or not _num(args[1]):
        return NULL_BAD_TYPE
    g = Geography("point", (float(args[0]), float(args[1])))
    return g if g.is_valid() else NULL_BAD_DATA


def _from_text(ctx, args):
    from .geo import GeoError, from_wkt
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str):
        return NULL_BAD_TYPE
    try:
        return from_wkt(args[0])
    except GeoError:
        return NULL_BAD_DATA


register("st_geogfromtext")(_from_text)
register("st_pointfromtext")(_from_text)
register("st_linestringfromtext")(_from_text)
register("st_polygonfromtext")(_from_text)


@register("st_astext")
def _st_astext(ctx, args):
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    return a[0].wkt()


@register("st_x")
def _st_x(ctx, args):
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    if a[0].kind != "point":
        return NULL_BAD_TYPE
    return a[0].coords[0]


@register("st_y")
def _st_y(ctx, args):
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    if a[0].kind != "point":
        return NULL_BAD_TYPE
    return a[0].coords[1]


@register("st_centroid")
def _st_centroid(ctx, args):
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    return a[0].centroid()


@register("st_isvalid")
def _st_isvalid(ctx, args):
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    return a[0].is_valid()


@register("st_distance")
def _st_distance(ctx, args):
    from .geo import distance_m
    err, a = _geo_args(args, 2)
    if a is None:
        return err
    return distance_m(a[0], a[1])


@register("st_dwithin")
def _st_dwithin(ctx, args):
    from .geo import distance_m
    err, a = _geo_args(args, 2)
    if a is None:
        return err
    if len(args) < 3 or not _num(args[2]):
        return NULL_BAD_TYPE
    return distance_m(a[0], a[1]) <= float(args[2])


@register("st_intersects")
def _st_intersects(ctx, args):
    from .geo import intersects
    err, a = _geo_args(args, 2)
    if a is None:
        return err
    return intersects(a[0], a[1])


@register("st_covers")
def _st_covers(ctx, args):
    from .geo import covers
    err, a = _geo_args(args, 2)
    if a is None:
        return err
    return covers(a[0], a[1])


@register("st_coveredby")
def _st_coveredby(ctx, args):
    from .geo import covers
    err, a = _geo_args(args, 2)
    if a is None:
        return err
    return covers(a[1], a[0])


@register("s2_cellidfrompoint")
def _s2_cellid(ctx, args):
    from .geo import cell_token
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    level = args[1] if len(args) > 1 else 30
    if not isinstance(level, int):
        return NULL_BAD_TYPE
    return cell_token(a[0], level)


@register("s2_coveringcellids")
def _s2_covering(ctx, args):
    from .geo import Geography, cell_token
    err, a = _geo_args(args, 1)
    if a is None:
        return err
    level = args[1] if len(args) > 1 else 8
    if not isinstance(level, int):
        return NULL_BAD_TYPE
    return sorted({cell_token(Geography("point", p), level)
                   for p in a[0].points()})


# ---------------------------------------------------------------------------
# Remaining scalar families (bit ops, trig conversions, temporal
# components, list/string helpers) — FunctionManager parity fill-in.
# ---------------------------------------------------------------------------


_math1("radians", math.radians)
_math1("degrees", math.degrees)
_math1("sinh", math.sinh)
_math1("cosh", math.cosh)
_math1("tanh", math.tanh)


@register("udf_is_in")
def _udf_is_in(ctx, args):
    if not args:
        return NULL_BAD_TYPE
    from .value import v_eq
    needle = args[0]
    for x in args[1:]:
        if v_eq(needle, x) is True:
            return True
    return False


@register("cos_similarity")
def _cos_similarity(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    if len(args) % 2 != 0 or not args:
        return NULL_BAD_DATA
    half = len(args) // 2
    xs, ys = args[:half], args[half:]
    if not all(_num(v) for v in xs + ys):
        return NULL_BAD_TYPE
    dot = sum(x * y for x, y in zip(xs, ys))
    nx = math.sqrt(sum(x * x for x in xs))
    ny = math.sqrt(sum(y * y for y in ys))
    if nx == 0.0 or ny == 0.0:
        return NULL_BAD_DATA
    return dot / (nx * ny)


@register("edges")
def _edges_of_path(ctx, args):
    if not args:
        return NULL_BAD_TYPE
    n = _nullprop(args)
    if n is not None:
        return n
    if isinstance(args[0], Path):
        return FUNCTIONS["relationships"](ctx, args)
    return NULL_BAD_TYPE


@register("extract")
def _extract(ctx, args):
    """extract(string, regex) — all non-overlapping matches."""
    import re as _re
    n = _nullprop(args)
    if n is not None:
        return n
    if len(args) != 2 or not isinstance(args[0], str) \
            or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    try:
        # full matched substrings — findall would return capture-group
        # contents (or tuples) when the regex has groups
        return [m.group(0) for m in _re.finditer(args[1], args[0])]
    except _re.error:
        return NULL_BAD_DATA


@register("json_extract")
def _json_extract(ctx, args):
    import json as _json
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str):
        return NULL_BAD_TYPE
    try:
        v = _json.loads(args[0])
    except ValueError:
        return NULL_BAD_DATA
    return v if isinstance(v, dict) else NULL_BAD_DATA


def _temporal_part(name, attr):
    @register(name)
    def _fn(ctx, args, _attr=attr):
        n = _nullprop(args)
        if n is not None:
            return n
        v = args[0]
        for cls in (Date, Time, DateTime):
            if isinstance(v, cls) and hasattr(v, _attr):
                return getattr(v, _attr)
        return NULL_BAD_TYPE
    return _fn


_temporal_part("year", "year")
_temporal_part("month", "month")
_temporal_part("day", "day")
_temporal_part("hour", "hour")
_temporal_part("minute", "minute")
_temporal_part("second", "sec")
_temporal_part("microsecond", "microsec")


@register("dayofweek")
def _dayofweek(ctx, args):
    """1 = Sunday ... 7 = Saturday (the reference's convention)."""
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if not isinstance(v, (Date, DateTime)):
        return NULL_BAD_TYPE
    d = _dt.date(v.year, v.month, v.day)
    return (d.weekday() + 1) % 7 + 1


@register("dayofyear")
def _dayofyear(ctx, args):
    n = _nullprop(args)
    if n is not None:
        return n
    v = args[0]
    if not isinstance(v, (Date, DateTime)):
        return NULL_BAD_TYPE
    return _dt.date(v.year, v.month, v.day).timetuple().tm_yday


# ---- text-search predicates (SURVEY §2 row 10 Listener) -------------------
# LOOKUP's PREFIX/WILDCARD/REGEXP/FUZZY normally plan into a
# FulltextIndexScan; these host evaluators keep the SAME value-level
# semantics (graphstore/fulltext.py) for every other placement — a
# second text conjunct, OR/NOT composition, residual re-checks.

def _text2(args):
    """-> (value, pattern) or a null to propagate / NULL_BAD_TYPE."""
    n = _nullprop(args)
    if n is not None:
        return n
    if not isinstance(args[0], str) or not isinstance(args[1], str):
        return NULL_BAD_TYPE
    return None


@register("prefix")
def _fn_prefix(ctx, args):
    bad = _text2(args)
    if bad is not None:
        return bad
    return args[0].lower().startswith(args[1].lower())


@register("wildcard")
def _fn_wildcard(ctx, args):
    import fnmatch as _fn
    bad = _text2(args)
    if bad is not None:
        return bad
    return _fn.fnmatch(args[0].lower(), args[1].lower())


@register("regexp")
def _fn_regexp(ctx, args):
    import re as _re
    bad = _text2(args)
    if bad is not None:
        return bad
    try:
        return _re.search(args[1], args[0]) is not None
    except _re.error:
        return NULL_BAD_DATA


@register("fuzzy")
def _fn_fuzzy(ctx, args):
    from ..graphstore.fulltext import analyze, levenshtein_leq
    bad = _text2(args)
    if bad is not None:
        return bad
    toks = analyze(args[1])
    if not toks:
        return False
    q = toks[0]
    k = 1 if len(q) < 6 else 2
    return any(levenshtein_leq(t, q, k) for t in analyze(args[0]))
