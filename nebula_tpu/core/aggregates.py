"""Aggregate function semantics — COUNT/SUM/AVG/MIN/MAX/COLLECT/STD/BIT_*.

Analog of the reference's AggData/AggFun machinery
(reference: src/common/function + graph AggregateExecutor [UNVERIFIED]).

Null/empty semantics: aggregates skip null & empty inputs (COUNT counts
non-null values; COUNT(*) counts rows).  SUM/AVG on an empty group → 0 /
NULL respectively; MIN/MAX of nothing → NULL.
"""
from __future__ import annotations

import math
from typing import Any, List

from .value import (NULL, NULL_BAD_TYPE, hashable_key, is_empty, is_null,
                    v_lt)


def _non_null(values: List[Any]) -> List[Any]:
    return [v for v in values if not is_null(v) and not is_empty(v)]


def _dedup(values: List[Any]) -> List[Any]:
    seen = set()
    out = []
    for v in values:
        k = hashable_key(v)
        if k not in seen:
            seen.add(k)
            out.append(v)
    return out


def _numeric(values: List[Any]):
    for v in values:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
    return values


def apply_aggregate(func: str, values: List[Any], distinct: bool = False,
                    star: bool = False) -> Any:
    if func == "count":
        vs = values if star else _non_null(values)
        if distinct:
            vs = _dedup(vs)
        return len(vs)

    vs = _non_null(values)
    if distinct:
        vs = _dedup(vs)

    if func == "collect":
        return list(vs)
    if func == "collect_set":
        try:
            return set(vs)
        except TypeError:
            return NULL_BAD_TYPE

    if func == "sum":
        nums = _numeric(vs)
        if nums is None:
            return NULL_BAD_TYPE
        if not nums:
            return 0
        s = sum(nums)
        return s
    if func == "avg":
        nums = _numeric(vs)
        if nums is None:
            return NULL_BAD_TYPE
        if not nums:
            return NULL
        return float(sum(nums)) / len(nums)
    if func == "min":
        if not vs:
            return NULL
        m = vs[0]
        for v in vs[1:]:
            if v_lt(v, m) is True:
                m = v
        return m
    if func == "max":
        if not vs:
            return NULL
        m = vs[0]
        for v in vs[1:]:
            if v_lt(m, v) is True:
                m = v
        return m
    if func == "std":
        nums = _numeric(vs)
        if nums is None:
            return NULL_BAD_TYPE
        if not nums:
            return NULL
        mean = sum(nums) / len(nums)
        return math.sqrt(sum((x - mean) ** 2 for x in nums) / len(nums))
    if func in ("bit_and", "bit_or", "bit_xor"):
        for v in vs:
            if isinstance(v, bool) or not isinstance(v, int):
                return NULL_BAD_TYPE
        if not vs:
            return NULL
        acc = vs[0]
        for v in vs[1:]:
            if func == "bit_and":
                acc &= v
            elif func == "bit_or":
                acc |= v
            else:
                acc ^= v
        return acc
    raise ValueError(f"unknown aggregate `{func}'")
