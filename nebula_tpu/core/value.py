"""nGQL Value model: the universal data currency of the framework.

Re-designed from the reference's tagged-union ``Value`` (reference:
src/common/datatypes/Value.h — unverified, empty mount; see SURVEY.md §0)
as idiomatic Python: plain Python objects carry scalar values (bool, int,
float, str), and small dataclass-style wrappers carry the graph/temporal
types.  NULL is represented by :class:`NullValue` (8 kinds, matching the
reference's ``NullType`` enum) — NOT by Python ``None`` — so that null-kind
propagation (BAD_TYPE vs DIV_BY_ZERO etc.) survives round trips.

Semantics implemented here (the parity-critical part):
  * 8 null kinds and their propagation rules
  * three-valued logic (AND/OR/XOR/NOT over kNullValue)
  * cross-type comparison: same-type compares naturally, int/float interop,
    different types yield BAD_TYPE null for relational ops but have a
    stable total order for ORDER BY (``total_order_key``)
  * arithmetic overflow → ERR_OVERFLOW, division by zero → DIV_BY_ZERO
"""
from __future__ import annotations

import datetime as _dt
import math
from enum import Enum
from typing import Any, Dict, Iterable, List, Optional, Tuple

INT64_MIN = -(2**63)
INT64_MAX = 2**63 - 1


class NullKind(Enum):
    NULL = "__NULL__"
    NaN = "__NaN__"
    BAD_DATA = "__BAD_DATA__"
    BAD_TYPE = "__BAD_TYPE__"
    ERR_OVERFLOW = "__OVERFLOW__"
    UNKNOWN_PROP = "__UNKNOWN_PROP__"
    DIV_BY_ZERO = "__DIV_BY_ZERO__"
    OUT_OF_RANGE = "__OUT_OF_RANGE__"


class NullValue:
    """An nGQL NULL with a kind. Interned per kind."""

    __slots__ = ("kind",)
    _interned: Dict[NullKind, "NullValue"] = {}

    def __new__(cls, kind: NullKind = NullKind.NULL):
        v = cls._interned.get(kind)
        if v is None:
            v = object.__new__(cls)
            v.kind = kind
            cls._interned[kind] = v
        return v

    def __repr__(self) -> str:
        return self.kind.value

    def __bool__(self) -> bool:
        return False

    # Nulls of any kind are equal to each other for hashing/dedup purposes
    # (kEquals in the reference distinguishes; dedup treats all nulls equal).
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, NullValue)

    def __hash__(self) -> int:
        return hash("__nebula_null__")


NULL = NullValue(NullKind.NULL)
NULL_NAN = NullValue(NullKind.NaN)
NULL_BAD_DATA = NullValue(NullKind.BAD_DATA)
NULL_BAD_TYPE = NullValue(NullKind.BAD_TYPE)
NULL_OVERFLOW = NullValue(NullKind.ERR_OVERFLOW)
NULL_UNKNOWN_PROP = NullValue(NullKind.UNKNOWN_PROP)
NULL_DIV_BY_ZERO = NullValue(NullKind.DIV_BY_ZERO)
NULL_OUT_OF_RANGE = NullValue(NullKind.OUT_OF_RANGE)


class EmptyValue:
    """The kEmpty value: absence of a value (distinct from NULL)."""

    __slots__ = ()
    _inst: Optional["EmptyValue"] = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = object.__new__(cls)
        return cls._inst

    def __repr__(self) -> str:
        return "__EMPTY__"

    def __bool__(self) -> bool:
        return False

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, EmptyValue)

    def __hash__(self) -> int:
        return hash("__nebula_empty__")


EMPTY = EmptyValue()


def is_null(v: Any) -> bool:
    return isinstance(v, NullValue)


def is_empty(v: Any) -> bool:
    return isinstance(v, EmptyValue)


def is_none_or_null(v: Any) -> bool:
    return v is None or isinstance(v, (NullValue, EmptyValue))


# --------------------------------------------------------------------------
# Temporal types
# --------------------------------------------------------------------------


class Date:
    __slots__ = ("year", "month", "day")

    def __init__(self, year: int = 1970, month: int = 1, day: int = 1):
        self.year, self.month, self.day = year, month, day

    def _key(self):
        return (self.year, self.month, self.day)

    def __eq__(self, o):
        return isinstance(o, Date) and self._key() == o._key()

    def __lt__(self, o):
        return self._key() < o._key()

    def __hash__(self):
        return hash(("Date",) + self._key())

    def __repr__(self):
        return f"{self.year:04d}-{self.month:02d}-{self.day:02d}"

    def to_py(self) -> _dt.date:
        return _dt.date(self.year, self.month, self.day)

    def days_since_epoch(self) -> int:
        return (self.to_py() - _dt.date(1970, 1, 1)).days


class Time:
    __slots__ = ("hour", "minute", "sec", "microsec")

    def __init__(self, hour=0, minute=0, sec=0, microsec=0):
        self.hour, self.minute, self.sec, self.microsec = hour, minute, sec, microsec

    def _key(self):
        return (self.hour, self.minute, self.sec, self.microsec)

    def __eq__(self, o):
        return isinstance(o, Time) and self._key() == o._key()

    def __lt__(self, o):
        return self._key() < o._key()

    def __hash__(self):
        return hash(("Time",) + self._key())

    def __repr__(self):
        return f"{self.hour:02d}:{self.minute:02d}:{self.sec:02d}.{self.microsec:06d}"


class DateTime:
    __slots__ = ("year", "month", "day", "hour", "minute", "sec", "microsec")

    def __init__(self, year=1970, month=1, day=1, hour=0, minute=0, sec=0, microsec=0):
        self.year, self.month, self.day = year, month, day
        self.hour, self.minute, self.sec, self.microsec = hour, minute, sec, microsec

    def _key(self):
        return (self.year, self.month, self.day, self.hour, self.minute, self.sec, self.microsec)

    def __eq__(self, o):
        return isinstance(o, DateTime) and self._key() == o._key()

    def __lt__(self, o):
        return self._key() < o._key()

    def __hash__(self):
        return hash(("DateTime",) + self._key())

    def __repr__(self):
        return (f"{self.year:04d}-{self.month:02d}-{self.day:02d}"
                f"T{self.hour:02d}:{self.minute:02d}:{self.sec:02d}.{self.microsec:06d}")

    def to_timestamp(self) -> int:
        dt = _dt.datetime(self.year, self.month, self.day, self.hour, self.minute,
                          self.sec, self.microsec, tzinfo=_dt.timezone.utc)
        return int(dt.timestamp())


class Duration:
    __slots__ = ("seconds", "microseconds", "months")

    def __init__(self, seconds: int = 0, microseconds: int = 0, months: int = 0):
        # normalize: microseconds carry into seconds (0 <= us < 1e6), so
        # arithmetically-equal durations compare/hash equal and repr
        # stays well-formed after +/- (months are calendar-relative and
        # never fold into seconds)
        carry, microseconds = divmod(microseconds, 1_000_000)
        self.seconds = seconds + carry
        self.microseconds = microseconds
        self.months = months

    def _key(self):
        return (self.months, self.seconds, self.microseconds)

    def __eq__(self, o):
        return isinstance(o, Duration) and self._key() == o._key()

    def __hash__(self):
        return hash(("Duration",) + self._key())

    def __repr__(self):
        return f"P{self.months}MT{self.seconds}.{self.microseconds:06d}S"


# --------------------------------------------------------------------------
# Graph types
# --------------------------------------------------------------------------


class Tag:
    __slots__ = ("name", "props")

    def __init__(self, name: str, props: Optional[Dict[str, Any]] = None):
        self.name = name
        self.props = props or {}

    def __eq__(self, o):
        return isinstance(o, Tag) and self.name == o.name and self.props == o.props

    def __hash__(self):
        return hash(("Tag", self.name, tuple(sorted(self.props))))

    def __repr__(self):
        return f":{self.name}{self.props!r}"


class Vertex:
    __slots__ = ("vid", "tags")

    def __init__(self, vid: Any, tags: Optional[List[Tag]] = None):
        self.vid = vid
        self.tags = tags or []

    def tag_names(self) -> List[str]:
        return [t.name for t in self.tags]

    def properties(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for t in self.tags:
            out.update(t.props)
        return out

    def prop(self, tag: str, name: str) -> Any:
        for t in self.tags:
            if t.name == tag:
                return t.props.get(name, NULL_UNKNOWN_PROP)
        return NULL_UNKNOWN_PROP

    def __eq__(self, o):
        return isinstance(o, Vertex) and self.vid == o.vid

    def __lt__(self, o):
        return _lt_raw(self.vid, o.vid)

    def __hash__(self):
        return hash(("Vertex", self.vid))

    def __repr__(self):
        return f'("{self.vid}"' + "".join(repr(t) for t in self.tags) + ")"


class Edge:
    __slots__ = ("src", "dst", "etype", "name", "ranking", "props")

    def __init__(self, src: Any, dst: Any, name: str, ranking: int = 0,
                 props: Optional[Dict[str, Any]] = None, etype: int = 0):
        self.src, self.dst = src, dst
        self.name, self.ranking = name, ranking
        self.props = props or {}
        self.etype = etype  # signed edge-type id; negative = reversed view

    def key(self) -> Tuple:
        # Direction-insensitive identity of the logical edge.
        if self.etype >= 0:
            return (self.src, self.dst, self.name, self.ranking)
        return (self.dst, self.src, self.name, self.ranking)

    def __eq__(self, o):
        return isinstance(o, Edge) and self.key() == o.key() and self.props == o.props

    def __lt__(self, o):
        return self.key() < o.key()

    def __hash__(self):
        return hash(("Edge",) + self.key())

    def __repr__(self):
        return f'[:{self.name} "{self.src}"->"{self.dst}" @{self.ranking} {self.props!r}]'


class Step:
    __slots__ = ("dst", "name", "etype", "ranking", "props")

    def __init__(self, dst: Vertex, name: str, ranking: int = 0,
                 props: Optional[Dict[str, Any]] = None, etype: int = 1):
        self.dst, self.name, self.ranking = dst, name, ranking
        self.props = props or {}
        self.etype = etype

    def __eq__(self, o):
        return (isinstance(o, Step) and self.dst == o.dst and self.name == o.name
                and self.ranking == o.ranking and self.etype == o.etype)

    def __hash__(self):
        return hash(("Step", self.dst.vid, self.name, self.ranking, self.etype))

    def __repr__(self):
        arrow = "-[" if self.etype >= 0 else "<-["
        close = "]->" if self.etype >= 0 else "]-"
        return f"{arrow}:{self.name}@{self.ranking}{close}{self.dst!r}"


class Path:
    __slots__ = ("src", "steps")

    def __init__(self, src: Vertex, steps: Optional[List[Step]] = None):
        self.src = src
        self.steps = steps or []

    def length(self) -> int:
        return len(self.steps)

    def nodes(self) -> List[Vertex]:
        return [self.src] + [s.dst for s in self.steps]

    def relationships(self) -> List[Edge]:
        out = []
        prev = self.src
        for s in self.steps:
            if s.etype >= 0:
                out.append(Edge(prev.vid, s.dst.vid, s.name, s.ranking, s.props, s.etype))
            else:
                out.append(Edge(s.dst.vid, prev.vid, s.name, s.ranking, s.props, s.etype))
            prev = s.dst
        return out

    def has_duplicate_edges(self) -> bool:
        seen = set()
        es = self.relationships()
        for e in es:
            if e.key() in seen:
                return True
            seen.add(e.key())
        return False

    def has_duplicate_vertices(self) -> bool:
        vids = [v.vid for v in self.nodes()]
        return len(set(vids)) != len(vids)

    def __eq__(self, o):
        return isinstance(o, Path) and self.src == o.src and self.steps == o.steps

    def __hash__(self):
        return hash(("Path", self.src.vid, tuple(hash(s) for s in self.steps)))

    def __repr__(self):
        return repr(self.src) + "".join(repr(s) for s in self.steps)


class DataSet:
    """A named-column row table — the result/interchange format.

    Reference: src/common/datatypes/DataSet.h [UNVERIFIED].
    """

    __slots__ = ("column_names", "rows")

    def __init__(self, column_names: Optional[List[str]] = None,
                 rows: Optional[List[List[Any]]] = None):
        self.column_names = column_names or []
        self.rows = rows or []

    def append_row(self, row: List[Any]) -> None:
        self.rows.append(row)

    def col_index(self, name: str) -> int:
        return self.column_names.index(name)

    def column(self, name: str) -> List[Any]:
        i = self.col_index(name)
        return [r[i] for r in self.rows]

    def __len__(self) -> int:
        return len(self.rows)

    def __eq__(self, o):
        return (isinstance(o, DataSet) and self.column_names == o.column_names
                and self.rows == o.rows)

    def __repr__(self):
        head = " | ".join(self.column_names)
        body = "\n".join(" | ".join(value_to_string(c) for c in r) for r in self.rows[:20])
        more = f"\n... ({len(self.rows)} rows)" if len(self.rows) > 20 else ""
        return f"{head}\n{'-' * max(len(head), 1)}\n{body}{more}"


_ROWS_SLOT = DataSet.__dict__["rows"]


class ColumnarDataSet(DataSet):
    """DataSet backed by numpy columns; rows materialize lazily.

    The device plane's result handle (SURVEY §2 row 25): device output
    stays columnar — numpy arrays straight off the fetched capture
    buffers — through the executor/result boundary, and per-row Python
    lists are built only if something actually touches ``.rows`` (the
    wire/print boundary, host executors composing further).  ``len()``,
    ``column()`` and column-wise serialization never pay the per-row
    object cost.
    """

    __slots__ = ("_cols",)

    def __init__(self, column_names: List[str], cols: List[Any]):
        self.column_names = list(column_names)
        self._cols = list(cols)          # 1-D numpy arrays, equal length
        _ROWS_SLOT.__set__(self, None)

    # rows: lazy over the backing columns ------------------------------
    @property
    def rows(self) -> List[List[Any]]:
        r = _ROWS_SLOT.__get__(self, ColumnarDataSet)
        if r is None:
            r = self._build_rows()
            _ROWS_SLOT.__set__(self, r)
            self._cols = None            # rows own the data now
        return r

    @rows.setter
    def rows(self, v) -> None:
        _ROWS_SLOT.__set__(self, v)
        self._cols = None

    def _build_rows(self) -> List[List[Any]]:
        import numpy as np
        cols = self._cols
        n = len(cols[0]) if cols else 0
        if n == 0:
            return []
        # object-matrix assembly: one C-level .tolist() per column plus
        # one for the matrix, instead of a Python per-row loop
        m = np.empty((n, len(cols)), dtype=object)
        for j, c in enumerate(cols):
            m[:, j] = c if c.dtype == object else c.tolist()
        return m.tolist()

    def __len__(self) -> int:
        if self._cols is not None:
            return len(self._cols[0]) if self._cols else 0
        return len(self.rows)

    def __iter__(self):
        return iter(self.rows)

    def column(self, name: str) -> List[Any]:
        if self._cols is not None:
            c = self._cols[self.col_index(name)]
            return list(c) if c.dtype == object else c.tolist()
        return super().column(name)

    def column_array(self, name: str):
        """The backing numpy column; None once rows were materialized."""
        if self._cols is None:
            return None
        return self._cols[self.col_index(name)]


# --------------------------------------------------------------------------
# Typing / printing
# --------------------------------------------------------------------------

_TYPE_NAMES = [
    (EmptyValue, "__EMPTY__"), (NullValue, "__NULL__"), (bool, "bool"),
    (int, "int"), (float, "float"), (str, "string"), (Date, "date"),
    (Time, "time"), (DateTime, "datetime"), (Vertex, "vertex"), (Edge, "edge"),
    (Path, "path"), (list, "list"), (dict, "map"), (set, "set"),
    (DataSet, "dataset"), (Duration, "duration"),
]


def _register_geo_type():
    # late: geo.py imports nothing from here, but keep import cycles out
    from .geo import Geography
    _TYPE_NAMES.insert(-1, (Geography, "geography"))
    # between duration (14) and __NULL__ (15): its own slot, nulls last
    _KIND_ORDER.setdefault("geography", 14.5)





def type_name(v: Any) -> str:
    for t, n in _TYPE_NAMES:
        if isinstance(v, t):
            return n
    return type(v).__name__


def make_edge(src_vid, other_vid, etype_name, rank, props, signed_dir,
              etype_id) -> "Edge":
    """Edge as seen from a traversal row: signed_dir=+1 means the stored
    edge is src->other; -1 is the reversed view (negative EdgeType, the
    reference's convention).  THE single constructor for this rule —
    graphd executors and storage-side filter eval must agree on it."""
    return Edge(src_vid, other_vid, etype_name, rank, dict(props),
                etype=etype_id if signed_dir > 0 else -etype_id)


def value_to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == math.inf:
            return "inf"
        if v == -math.inf:
            return "-inf"
        return repr(v)
    if isinstance(v, str):
        # escaped so the text form round-trips through the tokenizer —
        # pushed-down filters ship as nGQL text, and a raw quote or
        # backslash would re-parse as a different (or broken) literal
        esc = (v.replace("\\", "\\\\").replace('"', '\\"')
               .replace("\n", "\\n").replace("\t", "\\t")
               .replace("\r", "\\r"))
        return f'"{esc}"'
    if isinstance(v, list):
        return "[" + ", ".join(value_to_string(x) for x in v) + "]"
    if isinstance(v, set):
        return "{" + ", ".join(sorted(value_to_string(x) for x in v)) + "}"
    if isinstance(v, dict):
        return "{" + ", ".join(f"{k}: {value_to_string(x)}" for k, x in sorted(v.items())) + "}"
    return repr(v)


# --------------------------------------------------------------------------
# Truthiness / three-valued logic
# --------------------------------------------------------------------------


def to_bool3(v: Any) -> Any:
    """Value → (True|False|null) for WHERE-clause semantics."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (NullValue, EmptyValue)):
        return NULL
    return NULL_BAD_TYPE


def logical_and(a: Any, b: Any) -> Any:
    a3, b3 = to_bool3(a), to_bool3(b)
    if a3 is False or b3 is False:
        return False
    if is_null(a3) or is_null(b3):
        return NULL
    return True


def logical_or(a: Any, b: Any) -> Any:
    a3, b3 = to_bool3(a), to_bool3(b)
    if a3 is True or b3 is True:
        return True
    if is_null(a3) or is_null(b3):
        return NULL
    return False


def logical_xor(a: Any, b: Any) -> Any:
    a3, b3 = to_bool3(a), to_bool3(b)
    if is_null(a3) or is_null(b3):
        return NULL
    return a3 != b3


def logical_not(a: Any) -> Any:
    a3 = to_bool3(a)
    if is_null(a3):
        return NULL
    return not a3


# --------------------------------------------------------------------------
# Arithmetic
# --------------------------------------------------------------------------


def _both_numeric(a, b) -> bool:
    return (isinstance(a, (int, float)) and not isinstance(a, bool)
            and isinstance(b, (int, float)) and not isinstance(b, bool))


def _int_result(x: int) -> Any:
    if x < INT64_MIN or x > INT64_MAX:
        return NULL_OVERFLOW
    return x


def v_add(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b):
        return a if is_null(a) else b
    if isinstance(a, str) and isinstance(b, str):
        return a + b
    # string + primitive concatenation (nGQL allows str+int etc.)
    if isinstance(a, str) and isinstance(b, (int, float, bool)):
        return a + value_to_string(b).strip('"')
    if isinstance(b, str) and isinstance(a, (int, float, bool)):
        return value_to_string(a).strip('"') + b
    if isinstance(a, list) and isinstance(b, list):
        return a + b
    if isinstance(a, list):
        return a + [b]
    if isinstance(b, list):
        return [a] + b
    if _both_numeric(a, b):
        r = a + b
        if isinstance(a, int) and isinstance(b, int):
            return _int_result(r)
        return r
    if isinstance(a, Date) and isinstance(b, Duration):
        return _date_plus_duration(a, b)
    if isinstance(a, DateTime) and isinstance(b, Duration):
        return _datetime_plus_duration(a, b)
    if isinstance(a, Duration) and isinstance(b, Duration):
        return Duration(a.seconds + b.seconds,
                        a.microseconds + b.microseconds,
                        a.months + b.months)
    if isinstance(a, Time) and isinstance(b, Duration):
        return _time_plus_duration(a, b)
    return NULL_BAD_TYPE


def v_sub(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b):
        return a if is_null(a) else b
    if _both_numeric(a, b):
        r = a - b
        if isinstance(a, int) and isinstance(b, int):
            return _int_result(r)
        return r
    if isinstance(a, (Date, DateTime, Duration, Time)) \
            and isinstance(b, Duration):
        return v_add(a, _neg_duration(b))
    return NULL_BAD_TYPE


def v_mul(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b):
        return a if is_null(a) else b
    if _both_numeric(a, b):
        r = a * b
        if isinstance(a, int) and isinstance(b, int):
            return _int_result(r)
        return r
    return NULL_BAD_TYPE


def v_div(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b):
        return a if is_null(a) else b
    if _both_numeric(a, b):
        if isinstance(a, int) and isinstance(b, int):
            if b == 0:
                return NULL_DIV_BY_ZERO
            q = abs(a) // abs(b)
            return _int_result(q if (a >= 0) == (b >= 0) else -q)  # trunc toward 0
        if b == 0:
            return NULL_DIV_BY_ZERO
        return a / b
    return NULL_BAD_TYPE


def v_mod(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b):
        return a if is_null(a) else b
    if _both_numeric(a, b):
        if b == 0:
            return NULL_DIV_BY_ZERO
        if isinstance(a, int) and isinstance(b, int):
            return a - b * (abs(a) // abs(b)) * (1 if (a >= 0) == (b >= 0) else -1)
        return math.fmod(a, b)
    return NULL_BAD_TYPE


def v_neg(a: Any) -> Any:
    if is_null(a):
        return a
    if isinstance(a, bool) or not isinstance(a, (int, float)):
        return NULL_BAD_TYPE
    if isinstance(a, int):
        return _int_result(-a)
    return -a


def _neg_duration(d: Duration) -> Duration:
    return Duration(-d.seconds, -d.microseconds, -d.months)


def _time_plus_duration(t: Time, dur: Duration) -> Any:
    """Time-of-day shifted by a duration, wrapping within 24h; month
    components don't apply to a bare time (reference semantics)."""
    if dur.months:
        return NULL_BAD_TYPE
    us = ((t.hour * 3600 + t.minute * 60 + t.sec) * 1_000_000
          + t.microsec + dur.seconds * 1_000_000 + dur.microseconds)
    us %= 24 * 3600 * 1_000_000
    sec, microsec = divmod(us, 1_000_000)
    minute, s = divmod(sec, 60)
    hour, m = divmod(minute, 60)
    return Time(int(hour % 24), int(m), int(s), int(microsec))


def _date_plus_duration(d: Date, dur: Duration) -> Date:
    base = d.to_py()
    m = d.month - 1 + dur.months
    y = d.year + m // 12
    m = m % 12 + 1
    try:
        base = base.replace(year=y, month=m)
    except ValueError:
        # clamp day to month end
        import calendar
        base = base.replace(year=y, month=m, day=calendar.monthrange(y, m)[1])
    base = base + _dt.timedelta(seconds=dur.seconds, microseconds=dur.microseconds)
    return Date(base.year, base.month, base.day)


def _datetime_plus_duration(d: DateTime, dur: Duration) -> DateTime:
    base = _dt.datetime(d.year, d.month, d.day, d.hour, d.minute, d.sec, d.microsec)
    m = d.month - 1 + dur.months
    y = d.year + m // 12
    m = m % 12 + 1
    try:
        base = base.replace(year=y, month=m)
    except ValueError:
        import calendar
        base = base.replace(year=y, month=m, day=calendar.monthrange(y, m)[1])
    base = base + _dt.timedelta(seconds=dur.seconds, microseconds=dur.microseconds)
    return DateTime(base.year, base.month, base.day, base.hour, base.minute,
                    base.second, base.microsecond)


# --------------------------------------------------------------------------
# Comparison
# --------------------------------------------------------------------------

_KIND_ORDER = {
    "__EMPTY__": 0, "bool": 1, "int": 2, "float": 2, "string": 3, "date": 4,
    "time": 5, "datetime": 6, "vertex": 7, "edge": 8, "path": 9, "list": 10,
    "map": 11, "set": 12, "dataset": 13, "duration": 14, "__NULL__": 15,
}


def _comparable(a: Any, b: Any) -> bool:
    if _both_numeric(a, b):
        return True
    ta, tb = type_name(a), type_name(b)
    return ta == tb


def v_eq(a: Any, b: Any) -> Any:
    """nGQL ==: null-propagating equality."""
    if is_null(a) or is_null(b):
        return NULL
    if is_empty(a) or is_empty(b):
        return is_empty(a) and is_empty(b)
    if _both_numeric(a, b):
        return float(a) == float(b)
    if type_name(a) != type_name(b):
        return False
    if isinstance(a, list):
        if len(a) != len(b):
            return False
        for x, y in zip(a, b):
            e = v_eq(x, y)
            if e is not True:
                return e
        return True
    return a == b


def v_ne(a: Any, b: Any) -> Any:
    e = v_eq(a, b)
    if is_null(e):
        return e
    return not e


def _lt_raw(a: Any, b: Any) -> bool:
    try:
        return a < b
    except TypeError:
        return _KIND_ORDER.get(type_name(a), 99) < _KIND_ORDER.get(type_name(b), 99)


def v_lt(a: Any, b: Any) -> Any:
    if is_null(a) or is_null(b) or is_empty(a) or is_empty(b):
        return NULL
    if _both_numeric(a, b):
        return float(a) < float(b)
    if not _comparable(a, b):
        return NULL_BAD_TYPE
    if isinstance(a, list):
        for x, y in zip(a, b):
            lt = v_lt(x, y)
            if lt is True:
                return True
            if is_null(lt):
                return lt
            gt = v_lt(y, x)
            if gt is True:
                return False
        return len(a) < len(b)
    try:
        return bool(a < b)
    except TypeError:
        return NULL_BAD_TYPE


def v_le(a: Any, b: Any) -> Any:
    lt = v_lt(a, b)
    if lt is True:
        return True
    if is_null(lt):
        return lt
    return v_eq(a, b)


def v_gt(a: Any, b: Any) -> Any:
    return v_lt(b, a)


def v_ge(a: Any, b: Any) -> Any:
    return v_le(b, a)


def total_order_key(v: Any):
    """A total-order sort key across heterogeneous values (ORDER BY).

    Empty < numerics < string < ... < NULL (nulls last, matching the
    reference's ORDER BY placement of null/empty).
    """
    tn = type_name(v)
    k = _KIND_ORDER.get(tn, 98)
    if tn in ("int", "float"):
        return (k, float(v))
    if tn in ("__NULL__", "__EMPTY__"):
        return (k, 0)
    if tn == "bool":
        return (k, int(v))
    if tn == "string":
        return (k, v)
    if tn == "list":
        return (k, tuple(total_order_key(x) for x in v))
    if tn == "vertex":
        return (k, total_order_key(v.vid))
    if tn == "edge":
        return (k, tuple(total_order_key(x) for x in v.key()))
    if tn == "path":
        return (k, tuple(total_order_key(x.vid) for x in v.nodes()))
    if tn == "map":
        return (k, tuple((mk, total_order_key(mv)) for mk, mv in sorted(v.items())))
    if tn in ("date", "time", "datetime", "duration"):
        return (k, v._key())
    return (k, str(v))


def hashable_key(v: Any):
    """A hashable identity for DEDUP / GROUP BY / set membership."""
    if isinstance(v, list):
        return ("__list__",) + tuple(hashable_key(x) for x in v)
    if isinstance(v, dict):
        return ("__map__",) + tuple((k, hashable_key(x)) for k, x in sorted(v.items()))
    if isinstance(v, set):
        return ("__set__",) + tuple(sorted((hashable_key(x) for x in v), key=str))
    if isinstance(v, DataSet):
        return ("__ds__", tuple(v.column_names),
                tuple(tuple(hashable_key(c) for c in r) for r in v.rows))
    return v

_register_geo_type()
