"""Geography value type + spatial predicates.

The reference's GEOGRAPHY type wraps S2 geometry with WKT input/output
(reference: src/common/datatypes/Geography + src/common/geo
[UNVERIFIED — empty mount, SURVEY §2 row 3]).  This implementation
keeps the same surface — WKT POINT/LINESTRING/POLYGON values, the ST_*
function family, spherical distance — with documented simplifications:
great-circle math is haversine on a spherical Earth (S2 uses an
ellipsoid-free sphere too), and polygon containment is planar ray
casting on lng/lat (exact for the small-extent regions queries use;
S2's geodesic edges diverge only over continental-scale polygons).
"""
from __future__ import annotations

import math
import re
from typing import List, Optional, Tuple

EARTH_RADIUS_M = 6371010.0          # mean radius, matches S2's constant


class GeoError(Exception):
    pass


class Geography:
    """kind: 'point' | 'linestring' | 'polygon'.

    point:      coords = (lng, lat)
    linestring: coords = [(lng, lat), ...]
    polygon:    coords = [ring, ...]; ring = [(lng, lat), ...] closed
                (first == last), ring 0 is the shell, rest are holes.
    """
    __slots__ = ("kind", "coords")

    def __init__(self, kind: str, coords):
        self.kind = kind
        self.coords = coords

    # -- WKT ---------------------------------------------------------------

    def wkt(self) -> str:
        def pt(c):
            return f"{_fmt(c[0])} {_fmt(c[1])}"
        if self.kind == "point":
            return f"POINT({pt(self.coords)})"
        if self.kind == "linestring":
            return ("LINESTRING(" +
                    ", ".join(pt(c) for c in self.coords) + ")")
        rings = ", ".join(
            "(" + ", ".join(pt(c) for c in ring) + ")"
            for ring in self.coords)
        return f"POLYGON({rings})"

    def __repr__(self):
        return self.wkt()

    def __eq__(self, o):
        return (isinstance(o, Geography) and self.kind == o.kind
                and self.coords == o.coords)

    def __hash__(self):
        if self.kind == "point":
            return hash(("geo", self.kind, self.coords))
        if self.kind == "linestring":
            return hash(("geo", self.kind, tuple(self.coords)))
        return hash(("geo", self.kind,
                     tuple(tuple(r) for r in self.coords)))

    def __lt__(self, o):
        return self.wkt() < (o.wkt() if isinstance(o, Geography) else "")

    # -- derived -----------------------------------------------------------

    def points(self) -> List[Tuple[float, float]]:
        if self.kind == "point":
            return [self.coords]
        if self.kind == "linestring":
            return list(self.coords)
        return [c for ring in self.coords for c in ring]

    def centroid(self) -> "Geography":
        pts = self.points()
        if self.kind == "polygon":
            pts = self.coords[0][:-1]   # shell without the closing repeat
        lng = sum(p[0] for p in pts) / len(pts)
        lat = sum(p[1] for p in pts) / len(pts)
        return Geography("point", (lng, lat))

    def is_valid(self) -> bool:
        try:
            for (lng, lat) in self.points():
                if not (-180.0 <= lng <= 180.0 and -90.0 <= lat <= 90.0):
                    return False
            if self.kind == "linestring" and len(self.coords) < 2:
                return False
            if self.kind == "polygon":
                for ring in self.coords:
                    if len(ring) < 4 or ring[0] != ring[-1]:
                        return False
            return True
        except (TypeError, IndexError):
            return False


def _fmt(x: float) -> str:
    return repr(int(x)) if float(x).is_integer() else repr(float(x))


_NUM = r"[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?"
_PT = re.compile(rf"\s*({_NUM})\s+({_NUM})\s*")


def _parse_pts(body: str) -> List[Tuple[float, float]]:
    pts = []
    for part in body.split(","):
        m = _PT.fullmatch(part)
        if m is None:
            raise GeoError(f"bad coordinate {part!r}")
        pts.append((float(m.group(1)), float(m.group(2))))
    return pts


def from_wkt(text: str) -> Geography:
    s = text.strip()
    up = s.upper()
    if "(" not in s or ")" not in s:
        raise GeoError(f"malformed WKT {text[:24]!r}")
    if up.startswith("POINT"):
        body = s[s.index("(") + 1:s.rindex(")")]
        pts = _parse_pts(body)
        if len(pts) != 1:
            raise GeoError("POINT takes one coordinate")
        return Geography("point", pts[0])
    if up.startswith("LINESTRING"):
        body = s[s.index("(") + 1:s.rindex(")")]
        pts = _parse_pts(body)
        if len(pts) < 2:
            raise GeoError("LINESTRING needs >= 2 points")
        return Geography("linestring", pts)
    if up.startswith("POLYGON"):
        body = s[s.index("(") + 1:s.rindex(")")]
        rings = []
        for rm in re.finditer(r"\(([^()]*)\)", body):
            ring = _parse_pts(rm.group(1))
            if len(ring) >= 3 and ring[0] != ring[-1]:
                ring.append(ring[0])
            if len(ring) < 4:
                raise GeoError("POLYGON ring needs >= 3 distinct points")
            rings.append(ring)
        if not rings:
            raise GeoError("POLYGON needs a shell ring")
        return Geography("polygon", rings)
    raise GeoError(f"unsupported WKT {text[:24]!r}")


# -- spherical math ---------------------------------------------------------


def _haversine_m(a: Tuple[float, float], b: Tuple[float, float]) -> float:
    lng1, lat1, lng2, lat2 = map(math.radians,
                                 (a[0], a[1], b[0], b[1]))
    dlat, dlng = lat2 - lat1, lng2 - lng1
    h = (math.sin(dlat / 2) ** 2
         + math.cos(lat1) * math.cos(lat2) * math.sin(dlng / 2) ** 2)
    return 2 * EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(h)))


def _pt_seg_m(p, a, b, samples: int = 32) -> float:
    """Distance point→segment: haversine against sampled points of the
    segment (documented approximation of the geodesic cross-track)."""
    best = min(_haversine_m(p, a), _haversine_m(p, b))
    for i in range(1, samples):
        t = i / samples
        q = (a[0] + (b[0] - a[0]) * t, a[1] + (b[1] - a[1]) * t)
        d = _haversine_m(p, q)
        if d < best:
            best = d
    return best


def _segments(g: Geography):
    if g.kind == "linestring":
        yield from zip(g.coords, g.coords[1:])
    elif g.kind == "polygon":
        for ring in g.coords:
            yield from zip(ring, ring[1:])


def _pt_in_polygon(p: Tuple[float, float], g: Geography) -> bool:
    """Planar even-odd ray cast over (lng, lat); holes handled by parity."""
    x, y = p
    inside = False
    for ring in g.coords:
        for (x1, y1), (x2, y2) in zip(ring, ring[1:]):
            if (y1 > y) != (y2 > y):
                xi = x1 + (y - y1) * (x2 - x1) / (y2 - y1)
                if x < xi:
                    inside = not inside
    return inside


def _seg_intersect(a, b, c, d) -> bool:
    def ccw(p, q, r):
        return (r[1] - p[1]) * (q[0] - p[0]) > (q[1] - p[1]) * (r[0] - p[0])
    return (ccw(a, c, d) != ccw(b, c, d)) and (ccw(a, b, c) != ccw(a, b, d))


def distance_m(a: Geography, b: Geography) -> float:
    if a.kind != "point" and b.kind == "point":
        return distance_m(b, a)
    if a.kind == "point" and b.kind == "point":
        return _haversine_m(a.coords, b.coords)
    if a.kind == "point":
        if b.kind == "polygon" and _pt_in_polygon(a.coords, b):
            return 0.0
        return min(_pt_seg_m(a.coords, s, e) for (s, e) in _segments(b))
    if intersects(a, b):
        return 0.0
    return min(_pt_seg_m(p, s, e)
               for p in a.points() for (s, e) in _segments(b))


def intersects(a: Geography, b: Geography) -> bool:
    if a.kind == "point" and b.kind == "point":
        return a.coords == b.coords
    if a.kind == "point":
        if b.kind == "polygon":
            return _pt_in_polygon(a.coords, b)
        return any(_pt_seg_m(a.coords, s, e) < 0.5
                   for (s, e) in _segments(b))
    if b.kind == "point":
        return intersects(b, a)
    for (s1, e1) in _segments(a):
        for (s2, e2) in _segments(b):
            if _seg_intersect(s1, e1, s2, e2):
                return True
    if a.kind == "polygon" and any(_pt_in_polygon(p, a)
                                   for p in b.points()):
        return True
    if b.kind == "polygon" and any(_pt_in_polygon(p, b)
                                   for p in a.points()):
        return True
    return False


def covers(a: Geography, b: Geography) -> bool:
    """a covers b: every point of b lies within a."""
    if a.kind == "point":
        return b.kind == "point" and a.coords == b.coords
    if a.kind == "linestring":
        return (b.kind == "point"
                and any(_pt_seg_m(b.coords, s, e) < 0.5
                        for (s, e) in _segments(a)))
    # a is polygon: all of b's points inside, no boundary crossing.
    # Segments that merely SHARE an endpoint (adjacent ring segments,
    # b's boundary touching a's) are not crossings — without the skip,
    # covers(g, g) would be false for every polygon.
    if not all(_pt_in_polygon(p, a) or _on_boundary(p, a)
               for p in b.points()):
        return False
    if b.kind != "point":
        for (s1, e1) in _segments(b):
            for (s2, e2) in _segments(a):
                if s1 in (s2, e2) or e1 in (s2, e2):
                    continue
                if _seg_intersect(s1, e1, s2, e2):
                    return False
    return True


def _on_boundary(p, g: Geography, eps_m: float = 0.5) -> bool:
    return any(_pt_seg_m(p, s, e) < eps_m for (s, e) in _segments(g))


def _interleave31(x: int, y: int) -> int:
    out = 0
    for i in range(31):
        out |= ((x >> i) & 1) << (2 * i)
        out |= ((y >> i) & 1) << (2 * i + 1)
    return out


# meters per degree of great-circle arc, derived from the SAME radius
# distance_m uses — the old hardcoded 111320 (WGS84 equatorial
# circumference / 360) exceeds it by ~0.11%, so the padded bbox
# under-covered and ST_DWithin points near the boundary got cell tokens
# OUTSIDE covering_ranges (ADVICE high: 44 misses in a 3000-trial
# fuzz); the residual filter can't recover rows the cover never
# surfaces.  A small safety factor over-covers instead — extra cells
# only cost re-checks of the exact predicate.
_M_PER_DEG = math.radians(1.0) * EARTH_RADIUS_M
_PAD_SAFETY = 1.005


def _pad_boxes(g: Geography, pad_m: float) -> List[Tuple[float, float,
                                                         float, float]]:
    """(lng_lo, lng_hi, lat_lo, lat_hi) boxes covering `g`'s bbox padded
    by pad_m meters — split in two when the pad crosses the antimeridian,
    widened to the full longitude band when it crosses a pole or the
    longitude pad degenerates near one (cos→0)."""
    pts = g.points()
    lngs = [p[0] for p in pts]
    lats = [p[1] for p in pts]
    pad_m = pad_m * _PAD_SAFETY if pad_m else 0.0
    dlat = pad_m / _M_PER_DEG if pad_m else 0.0
    lat_lo_raw, lat_hi_raw = min(lats) - dlat, max(lats) + dlat
    lat_lo, lat_hi = max(-90.0, lat_lo_raw), min(90.0, lat_hi_raw)
    dlng = 0.0
    full_lng = lat_hi_raw > 90.0 or lat_lo_raw < -90.0
    if pad_m and not full_lng:
        max_abs_lat = min(89.999, max(abs(lat_lo), abs(lat_hi)))
        dlng = pad_m / (_M_PER_DEG * math.cos(math.radians(max_abs_lat)))
        if dlng >= 180.0:
            full_lng = True
    lng_lo_raw, lng_hi_raw = min(lngs) - dlng, max(lngs) + dlng
    if full_lng or lng_hi_raw - lng_lo_raw >= 360.0:
        return [(-180.0, 180.0, lat_lo, lat_hi)]
    if lng_lo_raw < -180.0:
        return [(-180.0, lng_hi_raw, lat_lo, lat_hi),
                (lng_lo_raw + 360.0, 180.0, lat_lo, lat_hi)]
    if lng_hi_raw > 180.0:
        return [(lng_lo_raw, 180.0, lat_lo, lat_hi),
                (-180.0, lng_hi_raw - 360.0, lat_lo, lat_hi)]
    return [(lng_lo_raw, lng_hi_raw, lat_lo, lat_hi)]


def covering_cells(g: Geography, pad_m: float = 0.0,
                   max_cells: int = 64) -> List[Tuple[int, int]]:
    """Level-aligned Morton cells covering `g`'s (padded) bounding box —
    the S2RegionCoverer analog (reference: storage geo index cover
    computation [UNVERIFIED — empty mount, SURVEY §0 row 15]).

    Works in the same quantized lng/lat space as cell_token(): a level-L
    cell fixes the top L bits of both 31-bit axes, so each cell is one
    contiguous token interval [base, base + 4^(31-L)).  The level is
    coarsened until the boxes need <= max_cells cells; the cover is a
    bbox superset of the region, so consumers must re-check the exact
    predicate.  Returns [(base_token, level)].
    """
    boxes = _pad_boxes(g, pad_m)
    q = (1 << 31) - 1

    def qbox(b):
        lng_lo, lng_hi, lat_lo, lat_hi = b
        return (int((lng_lo + 180.0) / 360.0 * q),
                int((lng_hi + 180.0) / 360.0 * q),
                int((lat_lo + 90.0) / 180.0 * q),
                int((lat_hi + 90.0) / 180.0 * q))

    qboxes = [qbox(b) for b in boxes]
    level = 30
    while level > 0:
        shift = 31 - level
        n = sum(((xh >> shift) - (xl >> shift) + 1)
                * ((yh >> shift) - (yl >> shift) + 1)
                for xl, xh, yl, yh in qboxes)
        if n <= max_cells:
            break
        level -= 1
    shift = 31 - level
    cells = set()
    for xl, xh, yl, yh in qboxes:
        for cx in range((xl >> shift), (xh >> shift) + 1):
            for cy in range((yl >> shift), (yh >> shift) + 1):
                cells.add(_interleave31(cx << shift, cy << shift))
    return sorted((base, level) for base in cells)


def cell_width(level: int) -> int:
    """Token-interval width of one level-`level` cell."""
    return 1 << (2 * (31 - level))


def covering_ranges(g: Geography, pad_m: float = 0.0,
                    max_cells: int = 64) -> List[Tuple[int, int]]:
    """covering_cells flattened to sorted, merged, INCLUSIVE (lo, hi)
    token ranges — the query-side shape the geo index scans."""
    ranges = sorted((base, base + cell_width(level) - 1)
                    for base, level in covering_cells(g, pad_m, max_cells))
    merged = [list(ranges[0])]
    for lo, hi in ranges[1:]:
        if lo <= merged[-1][1] + 1:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [(lo, hi) for lo, hi in merged]


def cell_token(g: Geography, level: int = 30) -> int:
    """64-bit Morton cell id of a point (lng/lat quantization) — the
    S2_CellIdFromPoint analog: equal points share ids and nearby points
    share prefixes.  NOT bit-identical to real S2 ids (no cube-face
    projection); documented as the locality-token surface."""
    if g.kind != "point":
        g = g.centroid()
    lng, lat = g.coords
    qx = int((lng + 180.0) / 360.0 * ((1 << 31) - 1))
    qy = int((lat + 90.0) / 180.0 * ((1 << 31) - 1))
    out = 0
    for i in range(31):
        out |= ((qx >> i) & 1) << (2 * i)
        out |= ((qy >> i) & 1) << (2 * i + 1)
    keep = 2 * min(level, 30)
    if keep < 62:
        out &= ~((1 << (62 - keep)) - 1)
    return out
