"""Expression AST + reference interpreter.

Redesign of the reference's visitor-based expression engine
(reference: src/common/expression/*.h [UNVERIFIED — empty mount, SURVEY §0])
as a compact Python AST.  ~30 node kinds covering arithmetic, logical,
relational (incl. IN/CONTAINS/STARTS WITH/ENDS WITH/=~), property access
($^.tag.p, $$.tag.p, $-.p, $var.p, edge.p, v.tag.p), subscript/slice, CASE,
list comprehension / predicate (all/any/single/none) / reduce, function and
aggregate calls, type casting and path-build.

Evaluation goes through an :class:`ExprContext`, the analog of the
reference's ``ExpressionContext``.  This interpreter is the row-at-a-time
*oracle*; the vectorized/TPU compiler for predicate subtrees lives in
``nebula_tpu.tpu.predicate``.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .value import (EMPTY, NULL, NULL_BAD_TYPE, NULL_UNKNOWN_PROP, DataSet,
                    Edge, EmptyValue, NullValue, Path, Vertex, is_empty,
                    is_null, logical_and, logical_not, logical_or, logical_xor,
                    to_bool3, type_name, v_add, v_div, v_eq, v_ge, v_gt, v_le,
                    v_lt, v_mod, v_mul, v_ne, v_neg, v_sub)


class ExprContext:
    """Evaluation context: where property/variable references resolve."""

    def get_input_prop(self, name: str) -> Any:        # $-.name
        return NULL_UNKNOWN_PROP

    def get_var(self, name: str) -> Any:               # $var
        return NULL_UNKNOWN_PROP

    def get_var_prop(self, var: str, name: str) -> Any:  # $var.name
        return NULL_UNKNOWN_PROP

    def get_src_prop(self, tag: str, name: str) -> Any:  # $^.tag.name
        return NULL_UNKNOWN_PROP

    def get_dst_prop(self, tag: str, name: str) -> Any:  # $$.tag.name
        return NULL_UNKNOWN_PROP

    def get_edge_prop(self, edge: str, name: str) -> Any:  # edgename.name / edge-reserved
        return NULL_UNKNOWN_PROP

    def get_vertex(self, which: str = "") -> Any:      # $^ / $$ / vertex
        return NULL_BAD_TYPE

    def get_edge(self) -> Any:                          # edge  (current edge)
        return NULL_BAD_TYPE

    def get_column(self, index: int) -> Any:            # COLUMN[i]
        return NULL_BAD_TYPE


class DictContext(ExprContext):
    """Context backed by plain dicts — used by tests and MATCH row eval."""

    def __init__(self, input_props: Optional[Dict[str, Any]] = None,
                 variables: Optional[Dict[str, Any]] = None,
                 src_props: Optional[Dict[str, Dict[str, Any]]] = None,
                 dst_props: Optional[Dict[str, Dict[str, Any]]] = None,
                 edge_props: Optional[Dict[str, Any]] = None,
                 vertex: Any = None, dst_vertex: Any = None, edge: Any = None):
        self.input_props = input_props or {}
        self.variables = variables or {}
        self.src_props = src_props or {}
        self.dst_props = dst_props or {}
        self.edge_props = edge_props or {}
        self.vertex = vertex
        self.dst_vertex = dst_vertex
        self.edge = edge

    def get_input_prop(self, name):
        return self.input_props.get(name, NULL_UNKNOWN_PROP)

    def get_var(self, name):
        if name in self.variables:
            return self.variables[name]
        return self.input_props.get(name, NULL_UNKNOWN_PROP)

    def get_var_prop(self, var, name):
        v = self.variables.get(var, NULL_UNKNOWN_PROP)
        if isinstance(v, dict):
            return v.get(name, NULL_UNKNOWN_PROP)
        return NULL_UNKNOWN_PROP

    def get_src_prop(self, tag, name):
        return self.src_props.get(tag, {}).get(name, NULL_UNKNOWN_PROP)

    def get_dst_prop(self, tag, name):
        return self.dst_props.get(tag, {}).get(name, NULL_UNKNOWN_PROP)

    def get_edge_prop(self, edge, name):
        return self.edge_props.get(name, NULL_UNKNOWN_PROP)

    def get_vertex(self, which=""):
        if which == "$$" and self.dst_vertex is not None:
            return self.dst_vertex
        return self.vertex if self.vertex is not None else NULL_BAD_TYPE

    def get_edge(self):
        return self.edge if self.edge is not None else NULL_BAD_TYPE


# --------------------------------------------------------------------------
# AST nodes
# --------------------------------------------------------------------------


class Expr:
    __slots__ = ()
    kind = "expr"

    def eval(self, ctx: ExprContext) -> Any:
        raise NotImplementedError

    def children(self) -> Sequence["Expr"]:
        return ()

    def __repr__(self):
        return to_text(self)

    def __eq__(self, other):
        return isinstance(other, Expr) and to_text(self) == to_text(other)

    def __hash__(self):
        return hash(to_text(self))


class Literal(Expr):
    __slots__ = ("value",)
    kind = "literal"

    def __init__(self, value: Any):
        self.value = value

    def eval(self, ctx):
        return self.value


class ListExpr(Expr):
    __slots__ = ("items",)
    kind = "list"

    def __init__(self, items: List[Expr]):
        self.items = items

    def eval(self, ctx):
        return [e.eval(ctx) for e in self.items]

    def children(self):
        return self.items


class SetExpr(Expr):
    __slots__ = ("items",)
    kind = "set"

    def __init__(self, items: List[Expr]):
        self.items = items

    def eval(self, ctx):
        out = set()
        for e in self.items:
            v = e.eval(ctx)
            try:
                out.add(v)
            except TypeError:
                return NULL_BAD_TYPE
        return out

    def children(self):
        return self.items


class MapExpr(Expr):
    __slots__ = ("items",)
    kind = "map"

    def __init__(self, items: List[Tuple[str, Expr]]):
        self.items = items

    def eval(self, ctx):
        return {k: e.eval(ctx) for k, e in self.items}

    def children(self):
        return [e for _, e in self.items]


class InputProp(Expr):
    __slots__ = ("name",)
    kind = "input_prop"

    def __init__(self, name: str):
        self.name = name

    def eval(self, ctx):
        return ctx.get_input_prop(self.name)


class VarExpr(Expr):
    __slots__ = ("name",)
    kind = "var"

    def __init__(self, name: str):
        self.name = name

    def eval(self, ctx):
        return ctx.get_var(self.name)


class VarProp(Expr):
    __slots__ = ("var", "name")
    kind = "var_prop"

    def __init__(self, var: str, name: str):
        self.var, self.name = var, name

    def eval(self, ctx):
        return ctx.get_var_prop(self.var, self.name)


class SrcProp(Expr):
    __slots__ = ("tag", "name")
    kind = "src_prop"

    def __init__(self, tag: str, name: str):
        self.tag, self.name = tag, name

    def eval(self, ctx):
        return ctx.get_src_prop(self.tag, self.name)


class DstProp(Expr):
    __slots__ = ("tag", "name")
    kind = "dst_prop"

    def __init__(self, tag: str, name: str):
        self.tag, self.name = tag, name

    def eval(self, ctx):
        return ctx.get_dst_prop(self.tag, self.name)


class EdgeProp(Expr):
    __slots__ = ("edge", "name")
    kind = "edge_prop"

    def __init__(self, edge: str, name: str):
        self.edge, self.name = edge, name

    def eval(self, ctx):
        # Reserved props route through the edge object when present.
        if self.name in ("_src", "_dst", "_rank", "_type"):
            e = ctx.get_edge()
            if isinstance(e, Edge):
                return {"_src": e.src, "_dst": e.dst, "_rank": e.ranking,
                        "_type": e.name}[self.name]
        return ctx.get_edge_prop(self.edge, self.name)


class VertexExpr(Expr):
    """``$^`` / ``$$`` / ``vertex`` — the whole vertex value."""
    __slots__ = ("which",)
    kind = "vertex"

    def __init__(self, which: str = ""):
        self.which = which  # "" | "$^" | "$$" | "vertex"

    def eval(self, ctx):
        return ctx.get_vertex(self.which)


class EdgeExpr(Expr):
    __slots__ = ()
    kind = "edge"

    def eval(self, ctx):
        return ctx.get_edge()


class LabelExpr(Expr):
    """A bare identifier — resolved as a variable in MATCH/YIELD contexts."""
    __slots__ = ("name",)
    kind = "label"

    def __init__(self, name: str):
        self.name = name

    def eval(self, ctx):
        return ctx.get_var(self.name)


class AttributeExpr(Expr):
    """``x.y`` where x is an arbitrary expression (map/vertex/edge/date)."""
    __slots__ = ("obj", "attr")
    kind = "attribute"

    def __init__(self, obj: Expr, attr: str):
        self.obj, self.attr = obj, attr

    def eval(self, ctx):
        o = self.obj.eval(ctx)
        return get_attribute(o, self.attr)

    def children(self):
        return (self.obj,)


class LabelTagProp(Expr):
    """``v.tag.prop`` in MATCH — variable, then tag, then prop."""
    __slots__ = ("var", "tag", "prop")
    kind = "label_tag_prop"

    def __init__(self, var: str, tag: str, prop: str):
        self.var, self.tag, self.prop = var, tag, prop

    def eval(self, ctx):
        v = ctx.get_var(self.var)
        if isinstance(v, Vertex):
            return v.prop(self.tag, self.prop)
        if is_null(v):
            # property access on a NULL variable (OPTIONAL MATCH miss)
            # is NULL, not a type error (openCypher)
            return NULL
        return NULL_BAD_TYPE


def get_attribute(o: Any, attr: str) -> Any:
    from .value import Date, DateTime, Time
    if is_null(o) or is_empty(o):
        return NULL if is_null(o) else NULL_UNKNOWN_PROP
    if isinstance(o, dict):
        return o.get(attr, NULL_UNKNOWN_PROP)
    if isinstance(o, Vertex):
        props = o.properties()
        if attr in props:
            return props[attr]
        return NULL_UNKNOWN_PROP
    if isinstance(o, Edge):
        if attr in o.props:
            return o.props[attr]
        return NULL_UNKNOWN_PROP
    if isinstance(o, (Date, DateTime, Time)):
        if attr in ("year", "month", "day", "hour", "minute", "microsec"):
            return getattr(o, attr, NULL_UNKNOWN_PROP)
        if attr == "second":
            return getattr(o, "sec", NULL_UNKNOWN_PROP)
        return NULL_UNKNOWN_PROP
    return NULL_BAD_TYPE


class Unary(Expr):
    __slots__ = ("op", "operand")
    kind = "unary"

    def __init__(self, op: str, operand: Expr):
        self.op, self.operand = op, operand

    def eval(self, ctx):
        if self.op == "IS_NULL":
            return is_null(self.operand.eval(ctx))
        if self.op == "IS_NOT_NULL":
            return not is_null(self.operand.eval(ctx))
        if self.op == "IS_EMPTY":
            return is_empty(self.operand.eval(ctx))
        if self.op == "IS_NOT_EMPTY":
            return not is_empty(self.operand.eval(ctx))
        v = self.operand.eval(ctx)
        if self.op == "-":
            return v_neg(v)
        if self.op == "+":
            if is_null(v) or isinstance(v, (int, float)):
                return v
            return NULL_BAD_TYPE
        if self.op == "NOT":
            return logical_not(v)
        if self.op == "++":  # increment (rare)
            return v_add(v, 1)
        if self.op == "--":
            return v_sub(v, 1)
        raise ValueError(f"unknown unary op {self.op}")

    def children(self):
        return (self.operand,)


def _bitop(fn):
    def op(a, b):
        if is_null(a) or is_null(b):
            return NULL
        if isinstance(a, bool) or isinstance(b, bool) \
                or not isinstance(a, int) or not isinstance(b, int):
            return NULL_BAD_TYPE
        return fn(a, b)
    return op


_ARITH = {"+": v_add, "-": v_sub, "*": v_mul, "/": v_div, "%": v_mod,
          "&": _bitop(lambda a, b: a & b),
          "|": _bitop(lambda a, b: a | b),
          "^": _bitop(lambda a, b: a ^ b)}
_REL = {"==": v_eq, "!=": v_ne, "<": v_lt, "<=": v_le, ">": v_gt, ">=": v_ge}


class Binary(Expr):
    __slots__ = ("op", "lhs", "rhs")
    kind = "binary"

    def __init__(self, op: str, lhs: Expr, rhs: Expr):
        self.op, self.lhs, self.rhs = op, lhs, rhs

    def eval(self, ctx):
        op = self.op
        if op == "AND":
            # short-circuit: false AND x == false without evaluating x
            a = self.lhs.eval(ctx)
            if to_bool3(a) is False:
                return False
            return logical_and(a, self.rhs.eval(ctx))
        if op == "OR":
            a = self.lhs.eval(ctx)
            if to_bool3(a) is True:
                return True
            return logical_or(a, self.rhs.eval(ctx))
        if op == "XOR":
            return logical_xor(self.lhs.eval(ctx), self.rhs.eval(ctx))
        a = self.lhs.eval(ctx)
        b = self.rhs.eval(ctx)
        if op in _ARITH:
            return _ARITH[op](a, b)
        if op in _REL:
            return _REL[op](a, b)
        if op in ("IN", "NOT IN"):
            r = _in(a, b)
            if op == "NOT IN":
                return logical_not(r)
            return r
        if op in ("CONTAINS", "NOT CONTAINS"):
            r = _str_rel(a, b, lambda x, y: y in x)
            return logical_not(r) if op.startswith("NOT") else r
        if op in ("STARTS WITH", "NOT STARTS WITH"):
            r = _str_rel(a, b, lambda x, y: x.startswith(y))
            return logical_not(r) if op.startswith("NOT") else r
        if op in ("ENDS WITH", "NOT ENDS WITH"):
            r = _str_rel(a, b, lambda x, y: x.endswith(y))
            return logical_not(r) if op.startswith("NOT") else r
        if op == "=~":
            if is_null(a) or is_null(b):
                return NULL
            if not isinstance(a, str) or not isinstance(b, str):
                return NULL_BAD_TYPE
            try:
                return re.fullmatch(b, a) is not None
            except re.error:
                return NULL_BAD_TYPE
        raise ValueError(f"unknown binary op {op}")

    def children(self):
        return (self.lhs, self.rhs)


def _in(a: Any, b: Any) -> Any:
    if is_null(b):
        return NULL
    if isinstance(b, (list, set)):
        saw_null = is_null(a)
        for x in b:
            e = v_eq(a, x)
            if e is True:
                return True
            if is_null(e):
                saw_null = True
        return NULL if saw_null else False
    if isinstance(b, dict):
        if is_null(a):
            return NULL
        return a in b
    return NULL_BAD_TYPE


def _str_rel(a, b, f) -> Any:
    if is_null(a) or is_null(b):
        return NULL
    if not isinstance(a, str) or not isinstance(b, str):
        return NULL_BAD_TYPE
    return f(a, b)


class Subscript(Expr):
    __slots__ = ("obj", "index")
    kind = "subscript"

    def __init__(self, obj: Expr, index: Expr):
        self.obj, self.index = obj, index

    def eval(self, ctx):
        o = self.obj.eval(ctx)
        i = self.index.eval(ctx)
        if is_null(o) or is_null(i):
            return NULL
        if isinstance(o, list):
            if isinstance(i, bool) or not isinstance(i, int):
                return NULL_BAD_TYPE
            if -len(o) <= i < len(o):
                return o[i]
            return NULL_OUT_OF_RANGE_
        if isinstance(o, dict):
            if not isinstance(i, str):
                return NULL_BAD_TYPE
            return o.get(i, NULL_UNKNOWN_PROP)
        if isinstance(o, (Vertex, Edge)):
            if not isinstance(i, str):
                return NULL_BAD_TYPE
            return get_attribute(o, i)
        return NULL_BAD_TYPE

    def children(self):
        return (self.obj, self.index)


from .value import NULL_OUT_OF_RANGE as NULL_OUT_OF_RANGE_  # noqa: E402


class Slice(Expr):
    __slots__ = ("obj", "lo", "hi")
    kind = "slice"

    def __init__(self, obj: Expr, lo: Optional[Expr], hi: Optional[Expr]):
        self.obj, self.lo, self.hi = obj, lo, hi

    def eval(self, ctx):
        o = self.obj.eval(ctx)
        if is_null(o):
            return NULL
        if not isinstance(o, list):
            return NULL_BAD_TYPE
        lo = self.lo.eval(ctx) if self.lo is not None else 0
        hi = self.hi.eval(ctx) if self.hi is not None else len(o)
        if is_null(lo) or is_null(hi):
            return NULL
        if not isinstance(lo, int) or not isinstance(hi, int):
            return NULL_BAD_TYPE
        return o[lo:hi]

    def children(self):
        return tuple(x for x in (self.obj, self.lo, self.hi) if x is not None)


class Case(Expr):
    """Both generic CASE WHEN c THEN v ... and CASE x WHEN m THEN v ..."""
    __slots__ = ("condition", "whens", "default")
    kind = "case"

    def __init__(self, whens: List[Tuple[Expr, Expr]],
                 default: Optional[Expr] = None, condition: Optional[Expr] = None):
        self.condition, self.whens, self.default = condition, whens, default

    def eval(self, ctx):
        if self.condition is not None:
            cv = self.condition.eval(ctx)
            for w, t in self.whens:
                if v_eq(cv, w.eval(ctx)) is True:
                    return t.eval(ctx)
        else:
            for w, t in self.whens:
                if to_bool3(w.eval(ctx)) is True:
                    return t.eval(ctx)
        return self.default.eval(ctx) if self.default is not None else NULL

    def children(self):
        out = []
        if self.condition is not None:
            out.append(self.condition)
        for w, t in self.whens:
            out += [w, t]
        if self.default is not None:
            out.append(self.default)
        return out


class _ScopedCtx(ExprContext):
    """Wraps a parent context adding one local binding (comprehensions)."""

    def __init__(self, parent: ExprContext, bindings: Dict[str, Any]):
        self.parent = parent
        self.bindings = bindings

    def get_var(self, name):
        if name in self.bindings:
            return self.bindings[name]
        return self.parent.get_var(name)

    def get_var_prop(self, var, name):
        if var in self.bindings:
            return get_attribute(self.bindings[var], name)
        return self.parent.get_var_prop(var, name)

    def __getattr__(self, item):
        return getattr(self.parent, item)


class ListComprehension(Expr):
    """[x IN list WHERE pred | mapExpr]"""
    __slots__ = ("var", "collection", "where", "mapping")
    kind = "list_comprehension"

    def __init__(self, var: str, collection: Expr,
                 where: Optional[Expr] = None, mapping: Optional[Expr] = None):
        self.var, self.collection = var, collection
        self.where, self.mapping = where, mapping

    def eval(self, ctx):
        coll = self.collection.eval(ctx)
        if is_null(coll):
            return NULL
        if not isinstance(coll, list):
            return NULL_BAD_TYPE
        out = []
        for x in coll:
            sub = _ScopedCtx(ctx, {self.var: x})
            if self.where is not None and to_bool3(self.where.eval(sub)) is not True:
                continue
            out.append(self.mapping.eval(sub) if self.mapping is not None else x)
        return out

    def children(self):
        return tuple(x for x in (self.collection, self.where, self.mapping) if x is not None)


class PredicateExpr(Expr):
    """all/any/single/none(x IN list WHERE pred) and exists()."""
    __slots__ = ("name", "var", "collection", "where")
    kind = "predicate"

    def __init__(self, name: str, var: str, collection: Expr, where: Expr):
        self.name, self.var = name.lower(), var
        self.collection, self.where = collection, where

    def eval(self, ctx):
        coll = self.collection.eval(ctx)
        if is_null(coll):
            return NULL
        if isinstance(coll, Path):
            coll = coll.nodes()
        if not isinstance(coll, list):
            return NULL_BAD_TYPE
        count, saw_null = 0, False
        for x in coll:
            r = to_bool3(self.where.eval(_ScopedCtx(ctx, {self.var: x})))
            if r is True:
                count += 1
            elif is_null(r):
                saw_null = True
        if self.name == "all":
            if count == len(coll):
                return NULL if saw_null else True
            return NULL if saw_null and count + 1 >= len(coll) else False
        if self.name == "any":
            return True if count > 0 else (NULL if saw_null else False)
        if self.name == "none":
            return False if count > 0 else (NULL if saw_null else True)
        if self.name == "single":
            return count == 1 if not saw_null else NULL
        raise ValueError(self.name)

    def children(self):
        return (self.collection, self.where)


class Reduce(Expr):
    """reduce(acc = init, x IN list | expr)"""
    __slots__ = ("acc", "init", "var", "collection", "mapping")
    kind = "reduce"

    def __init__(self, acc: str, init: Expr, var: str, collection: Expr, mapping: Expr):
        self.acc, self.init, self.var = acc, init, var
        self.collection, self.mapping = collection, mapping

    def eval(self, ctx):
        coll = self.collection.eval(ctx)
        if is_null(coll):
            return NULL
        if not isinstance(coll, list):
            return NULL_BAD_TYPE
        acc = self.init.eval(ctx)
        for x in coll:
            acc = self.mapping.eval(_ScopedCtx(ctx, {self.acc: acc, self.var: x}))
        return acc

    def children(self):
        return (self.init, self.collection, self.mapping)


class FunctionCall(Expr):
    __slots__ = ("name", "args")
    kind = "function"

    def __init__(self, name: str, args: List[Expr]):
        self.name, self.args = name.lower(), args

    def eval(self, ctx):
        from .functions import FUNCTIONS
        fn = FUNCTIONS.get(self.name)
        if fn is None:
            raise ExprEvalError(f"unknown function `{self.name}'")
        return fn(ctx, [a.eval(ctx) for a in self.args])

    def children(self):
        return self.args


AGG_NAMES = ("count", "sum", "avg", "min", "max", "collect", "collect_set",
             "std", "bit_and", "bit_or", "bit_xor")


class AggExpr(Expr):
    """An aggregate call site; evaluated by AggregateExecutor, not row-eval.

    Row-eval returns the inner expression value (used to feed the
    aggregator); `apply` folds a list of values.
    """
    __slots__ = ("func", "arg", "distinct")
    kind = "aggregate"

    def __init__(self, func: str, arg: Optional[Expr], distinct: bool = False):
        self.func, self.arg, self.distinct = func.lower(), arg, distinct

    def eval(self, ctx):
        if self.arg is None:  # COUNT(*)
            return 1
        return self.arg.eval(ctx)

    def children(self):
        return (self.arg,) if self.arg is not None else ()

    def apply(self, values: List[Any]) -> Any:
        from .aggregates import apply_aggregate
        return apply_aggregate(self.func, values, self.distinct, star=self.arg is None)


class TypeCast(Expr):
    __slots__ = ("target", "operand")
    kind = "cast"

    def __init__(self, target: str, operand: Expr):
        self.target, self.operand = target.lower(), operand

    def eval(self, ctx):
        from .functions import cast_value
        return cast_value(self.target, self.operand.eval(ctx))

    def children(self):
        return (self.operand,)


class PathBuild(Expr):
    __slots__ = ("items",)
    kind = "path_build"

    def __init__(self, items: List[Expr]):
        self.items = items

    def eval(self, ctx):
        from .value import Step
        vals = [e.eval(ctx) for e in self.items]
        if not vals or not isinstance(vals[0], Vertex):
            return NULL_BAD_TYPE
        p = Path(vals[0])
        i = 1
        while i < len(vals):
            e = vals[i]
            if not isinstance(e, Edge) or i + 1 >= len(vals):
                return NULL_BAD_TYPE
            v = vals[i + 1]
            if not isinstance(v, Vertex):
                return NULL_BAD_TYPE
            p.steps.append(Step(v, e.name, e.ranking, e.props, e.etype))
            i += 2
        return p

    def children(self):
        return self.items


class PatternPredExpr(Expr):
    """A boolean pattern predicate — `WHERE (a)-[:knows]->()` (reference:
    MatchValidator's PatternExpression / RollUpApply planning [UNVERIFIED
    — empty mount, SURVEY §0]).  Exists-semantics: true iff at least one
    expansion of the pattern matches with the bound aliases fixed.

    Carries the parsed `ast.PathPattern` opaquely (core stays independent
    of the query AST) plus its canonical source text for to_text/equality.
    The MATCH planner rewrites every occurrence into a deduplicated
    semi-join marker column before execution, so eval() is unreachable in
    a planned query; reaching it means a validator failed to reject a
    pattern predicate outside MATCH/WITH WHERE.
    """
    __slots__ = ("pattern", "text")
    kind = "pattern_pred"

    def __init__(self, pattern: Any, text: str):
        self.pattern, self.text = pattern, text

    def eval(self, ctx):
        raise ExprEvalError(
            "pattern predicate is only supported in a MATCH WHERE clause")


class ExprEvalError(Exception):
    pass


# --------------------------------------------------------------------------
# Traversal / analysis helpers (replaces the reference's visitor zoo)
# --------------------------------------------------------------------------


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def find_kinds(e: Expr, kinds: Tuple[str, ...]) -> List[Expr]:
    return [x for x in walk(e) if x.kind in kinds]


def has_aggregate(e: Expr) -> bool:
    return any(x.kind == "aggregate" for x in walk(e))


def collect_aggregates(e: Expr) -> List[AggExpr]:
    return [x for x in walk(e) if isinstance(x, AggExpr)]


def split_conjuncts(e: Expr) -> List[Expr]:
    """a AND b AND c → [a, b, c] (for filter pushdown)."""
    if isinstance(e, Binary) and e.op == "AND":
        return split_conjuncts(e.lhs) + split_conjuncts(e.rhs)
    return [e]


def join_conjuncts(parts: List[Expr]) -> Optional[Expr]:
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        out = Binary("AND", out, p)
    return out


def rewrite(e: Expr, fn) -> Expr:
    """Bottom-up rewrite: fn(node) returns replacement or None to keep."""
    cls = type(e)
    if isinstance(e, Binary):
        e2 = cls(e.op, rewrite(e.lhs, fn), rewrite(e.rhs, fn))
    elif isinstance(e, Unary):
        e2 = cls(e.op, rewrite(e.operand, fn))
    elif isinstance(e, ListExpr):
        e2 = cls([rewrite(x, fn) for x in e.items])
    elif isinstance(e, MapExpr):
        e2 = cls([(k, rewrite(x, fn)) for k, x in e.items])
    elif isinstance(e, FunctionCall):
        e2 = cls(e.name, [rewrite(x, fn) for x in e.args])
    elif isinstance(e, AggExpr):
        e2 = cls(e.func, rewrite(e.arg, fn) if e.arg else None, e.distinct)
    elif isinstance(e, Subscript):
        e2 = cls(rewrite(e.obj, fn), rewrite(e.index, fn))
    elif isinstance(e, AttributeExpr):
        e2 = cls(rewrite(e.obj, fn), e.attr)
    elif isinstance(e, TypeCast):
        e2 = cls(e.target, rewrite(e.operand, fn))
    elif isinstance(e, Case):
        e2 = cls([(rewrite(w, fn), rewrite(t, fn)) for w, t in e.whens],
                 rewrite(e.default, fn) if e.default else None,
                 rewrite(e.condition, fn) if e.condition else None)
    elif isinstance(e, SetExpr):
        e2 = cls([rewrite(x, fn) for x in e.items])
    elif isinstance(e, Slice):
        e2 = cls(rewrite(e.obj, fn),
                 rewrite(e.lo, fn) if e.lo is not None else None,
                 rewrite(e.hi, fn) if e.hi is not None else None)
    elif isinstance(e, ListComprehension):
        e2 = cls(e.var, rewrite(e.collection, fn),
                 rewrite(e.where, fn) if e.where is not None else None,
                 rewrite(e.mapping, fn) if e.mapping is not None else None)
    elif isinstance(e, PredicateExpr):
        e2 = cls(e.name, e.var, rewrite(e.collection, fn),
                 rewrite(e.where, fn))
    elif isinstance(e, Reduce):
        e2 = cls(e.acc, rewrite(e.init, fn), e.var,
                 rewrite(e.collection, fn), rewrite(e.mapping, fn))
    elif isinstance(e, PathBuild):
        e2 = cls([rewrite(x, fn) for x in e.items])
    else:
        e2 = e
    r = fn(e2)
    return r if r is not None else e2


# --------------------------------------------------------------------------
# Pretty printing (EXPLAIN output / golden plan tests)
# --------------------------------------------------------------------------


def to_text(e: Expr) -> str:
    from .value import value_to_string
    k = e.kind
    if k == "literal":
        return value_to_string(e.value)
    if k == "list":
        return "[" + ", ".join(to_text(x) for x in e.items) + "]"
    if k == "set":
        return "{" + ", ".join(to_text(x) for x in e.items) + "}"
    if k == "map":
        return "{" + ", ".join(f"{n}: {to_text(x)}" for n, x in e.items) + "}"
    if k == "input_prop":
        return f"$-.{e.name}"
    if k == "var":
        return f"${e.name}"
    if k == "var_prop":
        return f"${e.var}.{e.name}"
    if k == "src_prop":
        return f"$^.{e.tag}.{e.name}"
    if k == "dst_prop":
        return f"$$.{e.tag}.{e.name}"
    if k == "edge_prop":
        return f"{e.edge}.{e.name}"
    if k == "vertex":
        return e.which or "vertex"
    if k == "edge":
        return "edge"
    if k == "label":
        return e.name
    if k == "label_tag_prop":
        return f"{e.var}.{e.tag}.{e.prop}"
    if k == "attribute":
        return f"{to_text(e.obj)}.{e.attr}"
    if k == "unary":
        if e.op in ("IS_NULL", "IS_NOT_NULL", "IS_EMPTY", "IS_NOT_EMPTY"):
            return f"({to_text(e.operand)} {e.op.replace('_', ' ')})"
        if e.op == "NOT":
            return f"(NOT {to_text(e.operand)})"
        return f"({e.op}{to_text(e.operand)})"
    if k == "binary":
        return f"({to_text(e.lhs)} {e.op} {to_text(e.rhs)})"
    if k == "subscript":
        return f"{to_text(e.obj)}[{to_text(e.index)}]"
    if k == "slice":
        lo = to_text(e.lo) if e.lo else ""
        hi = to_text(e.hi) if e.hi else ""
        return f"{to_text(e.obj)}[{lo}..{hi}]"
    if k == "case":
        parts = ["CASE"]
        if e.condition is not None:
            parts.append(to_text(e.condition))
        for w, t in e.whens:
            parts.append(f"WHEN {to_text(w)} THEN {to_text(t)}")
        if e.default is not None:
            parts.append(f"ELSE {to_text(e.default)}")
        parts.append("END")
        return " ".join(parts)
    if k == "list_comprehension":
        s = f"[{e.var} IN {to_text(e.collection)}"
        if e.where is not None:
            s += f" WHERE {to_text(e.where)}"
        if e.mapping is not None:
            s += f" | {to_text(e.mapping)}"
        return s + "]"
    if k == "predicate":
        return f"{e.name}({e.var} IN {to_text(e.collection)} WHERE {to_text(e.where)})"
    if k == "reduce":
        return (f"reduce({e.acc} = {to_text(e.init)}, {e.var} IN "
                f"{to_text(e.collection)} | {to_text(e.mapping)})")
    if k == "function":
        return f"{e.name}(" + ", ".join(to_text(a) for a in e.args) + ")"
    if k == "aggregate":
        inner = "*" if e.arg is None else to_text(e.arg)
        d = "distinct " if e.distinct else ""
        return f"{e.func}({d}{inner})"
    if k == "cast":
        return f"({e.target}){to_text(e.operand)}"
    if k == "path_build":
        return " <JOIN> ".join(to_text(x) for x in e.items)
    if k == "pattern_pred":
        return e.text
    return f"<{k}>"
