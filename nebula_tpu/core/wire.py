"""Wire (de)serialization of the Value model — the thrift-struct analog.

Every nGQL value maps to a JSON-safe form and back, losslessly (null
kinds, temporal types, vertex/edge/path, sets, DataSet).  This is the
process-boundary encoding used by cluster RPC (reference: the thrift
serialization of src/common/datatypes [UNVERIFIED — empty mount,
SURVEY §0]).

Plain JSON scalars pass through untouched; composite/typed values become
{"@t": tag, ...} dicts (plain maps are tagged too, so user maps whose
keys include "@t" round-trip safely).
"""
from __future__ import annotations

import json
from typing import Any, Dict

from .value import (ColumnarDataSet, DataSet, Date, DateTime, Duration,
                    Edge, EmptyValue, NullKind, NullValue, Path, Step, Tag,
                    Time, Vertex)


# row-form DataSets at/above this size probe for columnar encoding;
# below it the type scan costs more than per-cell JSON saves
COLUMNAR_MIN_ROWS = 64

_SCALAR_DTYPES = {int: "<i8", float: "<f8", bool: "|b1"}

# transport narrowing probes columns at/above this size; below it the
# min/max scan costs more than the saved bytes
_NARROW_MIN = 4096


def _narrow_dtype(arr):
    """Smallest signed int dtype that holds arr losslessly, when it is
    strictly narrower than arr's own — else None.  Transport-only: the
    declared dtype (`dt`) is restored on decode, so int64 semantics
    survive; at loopback/NIC throughputs the width scan + astype copy
    is far cheaper than shipping the spare bytes."""
    import numpy as np
    if arr.size < _NARROW_MIN or arr.dtype.kind not in "iu":
        return None
    lo, hi = int(arr.min()), int(arr.max())
    for dt in (np.int8, np.int16, np.int32):
        if np.dtype(dt).itemsize >= arr.dtype.itemsize:
            return None
        info = np.iinfo(dt)
        if lo >= info.min and hi <= info.max:
            return np.dtype(dt)
    return None


def encode_array(arr) -> Any:
    """Typed-blob wire entry for a 1-D numeric numpy array: the numpy
    buffer itself as a memoryview (zero copy), narrowed for transport
    when the value range allows."""
    import numpy as np
    arr = np.ascontiguousarray(arr)
    entry: Dict[str, Any] = {"dt": arr.dtype.str}
    nd = _narrow_dtype(arr)
    if nd is not None:
        entry["wdt"] = nd.str
        entry["b"] = memoryview(arr.astype(nd))
    else:
        entry["b"] = memoryview(arr)
    return entry


def encode_column(col) -> Any:
    """Typed-blob encoding of one column of plain scalars, or None.

    Exact by construction: the column is accepted only when EVERY cell
    is the same plain scalar type (set(map(type, ...)) — C-level scan),
    so int/float/bool identity survives the round trip (a numpy
    dtype-inference coercion like [1, 2.5] → float64 can never happen).
    """
    ts = set(map(type, col))
    if len(ts) != 1:
        return None
    dt = _SCALAR_DTYPES.get(next(iter(ts)))
    if dt is None:
        return None
    import numpy as np
    try:
        arr = np.array(col, dtype=np.dtype(dt))
    except (OverflowError, ValueError):   # >int64 Python ints
        return None
    return encode_array(arr)


def decode_column(cj: Any):
    """Inverse of encode_column/encode_array → 1-D numpy array
    (zero-copy over RPC blob views; base64 fallback for file/raft
    serialization).  Transport-narrowed int columns STAY narrow —
    value-exact (int8/32 cells materialize to identical Python ints),
    and widening 100MB eagerly was measured to cost more than the
    narrowing saved; only ints are ever narrowed (_narrow_dtype), so
    no lossy float path exists."""
    import numpy as np
    b = cj["b"]
    if isinstance(b, dict):               # {"@t":"b64",...} fallback
        b = from_wire(b)
    return np.frombuffer(b, dtype=np.dtype(cj.get("wdt") or cj["dt"]))


def _dataset_columnar(v: "DataSet") -> Any:
    """Columnar wire form of a row DataSet when at least one column is
    a homogeneous plain-scalar column; None → keep the row encoding."""
    cols = list(zip(*v.rows))
    if len(cols) != len(v.column_names):
        return None                        # ragged rows: stay row-form
    data = []
    hit = False
    for col in cols:
        enc = encode_column(col)
        if enc is not None:
            hit = True
            data.append(enc)
        else:
            data.append({"v": [to_wire(x) for x in col]})
    if not hit:
        return None
    return {"@t": "coldataset", "cols": list(v.column_names),
            "data": data}


def to_wire(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, NullValue):
        return {"@t": "null", "k": v.kind.name}
    if isinstance(v, EmptyValue):
        return {"@t": "empty"}
    if isinstance(v, Geography):
        return {"@t": "geo", "v": v.wkt()}
    if isinstance(v, Date):
        return {"@t": "date", "v": [v.year, v.month, v.day]}
    if isinstance(v, Time):
        return {"@t": "time", "v": [v.hour, v.minute, v.sec, v.microsec]}
    if isinstance(v, DateTime):
        return {"@t": "datetime", "v": [v.year, v.month, v.day, v.hour,
                                        v.minute, v.sec, v.microsec]}
    if isinstance(v, Duration):
        return {"@t": "duration", "v": [v.seconds, v.microseconds, v.months]}
    if isinstance(v, Tag):
        return {"@t": "tag", "n": v.name,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Vertex):
        return {"@t": "vertex", "vid": to_wire(v.vid),
                "tags": [to_wire(t) for t in v.tags]}
    if isinstance(v, Edge):
        return {"@t": "edge", "src": to_wire(v.src), "dst": to_wire(v.dst),
                "n": v.name, "r": v.ranking, "et": v.etype,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Step):
        return {"@t": "step", "dst": to_wire(v.dst), "n": v.name,
                "r": v.ranking, "et": v.etype,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Path):
        return {"@t": "path", "src": to_wire(v.src),
                "steps": [to_wire(s) for s in v.steps]}
    if isinstance(v, ColumnarDataSet) and v._cols is not None:
        # device-plane results stay columnar THROUGH the wire (SURVEY §2
        # row 25 / VERDICT r4 item 2): numeric columns ship as RAW
        # buffers — the RPC layer hoists the bytes into out-of-band
        # binary frames (ZERO copy: the numpy column's own buffer rides
        # to sendall as a memoryview), file/raft serialization falls
        # back to base64 — and the client decodes straight back into
        # numpy with no per-row object cost; object columns (strings,
        # vertices) use per-value encoding.  Materialized ones
        # (something already touched .rows) ship as a plain dataset.
        import numpy as np
        data = []
        for c in v._cols:
            c = np.asarray(c)
            if c.dtype.kind in "biuf":
                data.append(encode_array(c))
            else:
                data.append({"v": [to_wire(x) for x in c.tolist()]})
        return {"@t": "coldataset", "cols": list(v.column_names),
                "data": data}
    if isinstance(v, DataSet):
        # the GO/MATCH bulk result path (ISSUE 2): a row-form result
        # whose columns are homogeneous plain scalars ships columnar
        # too — typed blobs instead of one JSON token per cell — and
        # decodes into a lazy ColumnarDataSet (no per-row boxing until
        # a consumer actually crosses the row boundary)
        if len(v.rows) >= COLUMNAR_MIN_ROWS and v.column_names:
            enc = _dataset_columnar(v)
            if enc is not None:
                return enc
        return {"@t": "dataset", "cols": list(v.column_names),
                "rows": [[to_wire(c) for c in r] for r in v.rows]}
    if isinstance(v, list):
        return {"@t": "list", "v": [to_wire(x) for x in v]}
    if isinstance(v, tuple):
        return {"@t": "list", "v": [to_wire(x) for x in v]}
    if isinstance(v, set):
        return {"@t": "set", "v": [to_wire(x) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            return {"@t": "map", "v": {k: to_wire(x) for k, x in v.items()}}
        # non-string keys (int vids, (rank,dst) tuples): JSON objects
        # would silently coerce them to strings, so ship pairs instead
        return {"@t": "kvmap",
                "v": [[to_wire(k), to_wire(x)] for k, x in v.items()]}
    raise TypeError(f"not wire-serializable: {type(v).__name__}")


def from_wire(j: Any) -> Any:
    if j is None or isinstance(j, (bool, int, float, str)):
        return j
    if isinstance(j, list):            # bare JSON list (rpc params etc.)
        return [from_wire(x) for x in j]
    if not isinstance(j, dict):
        raise TypeError(f"bad wire value: {type(j).__name__}")
    t = j.get("@t")
    if t is None:                      # bare JSON object
        return {k: from_wire(x) for k, x in j.items()}
    if t == "geo":
        return from_wkt(j["v"])
    if t == "null":
        return NullValue(NullKind[j["k"]])
    if t == "empty":
        return EmptyValue()
    if t == "date":
        return Date(*j["v"])
    if t == "time":
        return Time(*j["v"])
    if t == "datetime":
        return DateTime(*j["v"])
    if t == "duration":
        return Duration(*j["v"])
    if t == "tag":
        return Tag(j["n"], {k: from_wire(x) for k, x in j["p"].items()})
    if t == "vertex":
        return Vertex(from_wire(j["vid"]), [from_wire(x) for x in j["tags"]])
    if t == "edge":
        return Edge(from_wire(j["src"]), from_wire(j["dst"]), j["n"],
                    j["r"], {k: from_wire(x) for k, x in j["p"].items()},
                    etype=j["et"])
    if t == "step":
        return Step(from_wire(j["dst"]), j["n"], j["r"],
                    {k: from_wire(x) for k, x in j["p"].items()},
                    etype=j["et"])
    if t == "path":
        return Path(from_wire(j["src"]), [from_wire(s) for s in j["steps"]])
    if t == "dataset":
        return DataSet(list(j["cols"]),
                       [[from_wire(c) for c in r] for r in j["rows"]])
    if t == "coldataset":
        import numpy as np
        arrs = []
        for cj in j["data"]:
            if cj.get("b") is not None:
                arrs.append(decode_column(cj))
            else:
                vals = [from_wire(x) for x in cj["v"]]
                # element-wise fill: np.array() would collapse a column
                # of equal-length lists into a 2-D array
                a = np.empty(len(vals), dtype=object)
                for i, x in enumerate(vals):
                    a[i] = x
                arrs.append(a)
        return ColumnarDataSet(list(j["cols"]), arrs)
    if t == "b64":
        import base64
        return base64.b64decode(j["v"])
    if t == "list":
        return [from_wire(x) for x in j["v"]]
    if t == "set":
        return {from_wire(x) for x in j["v"]}
    if t == "map":
        return {k: from_wire(x) for k, x in j["v"].items()}
    if t == "kvmap":
        out = {}
        for kj, xj in j["v"]:
            k = from_wire(kj)
            if isinstance(k, list):      # tuple keys decode as lists
                k = tuple(k)
            out[k] = from_wire(xj)
        return out
    raise TypeError(f"unknown wire tag {t!r}")


def b64_default(o):
    """json.dumps default for wire objects: raw bytes (columnar buffers)
    degrade to tagged base64 when no binary framing is available."""
    if isinstance(o, (bytes, bytearray, memoryview)):
        import base64
        return {"@t": "b64", "v": base64.b64encode(bytes(o)).decode()}
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def dumps(v: Any) -> bytes:
    """Wire-encode + JSON-serialize (raft entries, snapshots, files)."""
    return json.dumps(to_wire(v), separators=(",", ":"),
                      default=b64_default).encode()


def loads(data: bytes) -> Any:
    return from_wire(json.loads(data.decode()))
from .geo import Geography, from_wkt
