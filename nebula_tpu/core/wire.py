"""Wire (de)serialization of the Value model — the thrift-struct analog.

Every nGQL value maps to a JSON-safe form and back, losslessly (null
kinds, temporal types, vertex/edge/path, sets, DataSet).  This is the
process-boundary encoding used by cluster RPC (reference: the thrift
serialization of src/common/datatypes [UNVERIFIED — empty mount,
SURVEY §0]).

Plain JSON scalars pass through untouched; composite/typed values become
{"@t": tag, ...} dicts (plain maps are tagged too, so user maps whose
keys include "@t" round-trip safely).
"""
from __future__ import annotations

import json
from typing import Any

from .value import (ColumnarDataSet, DataSet, Date, DateTime, Duration,
                    Edge, EmptyValue, NullKind, NullValue, Path, Step, Tag,
                    Time, Vertex)


def to_wire(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, NullValue):
        return {"@t": "null", "k": v.kind.name}
    if isinstance(v, EmptyValue):
        return {"@t": "empty"}
    if isinstance(v, Geography):
        return {"@t": "geo", "v": v.wkt()}
    if isinstance(v, Date):
        return {"@t": "date", "v": [v.year, v.month, v.day]}
    if isinstance(v, Time):
        return {"@t": "time", "v": [v.hour, v.minute, v.sec, v.microsec]}
    if isinstance(v, DateTime):
        return {"@t": "datetime", "v": [v.year, v.month, v.day, v.hour,
                                        v.minute, v.sec, v.microsec]}
    if isinstance(v, Duration):
        return {"@t": "duration", "v": [v.seconds, v.microseconds, v.months]}
    if isinstance(v, Tag):
        return {"@t": "tag", "n": v.name,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Vertex):
        return {"@t": "vertex", "vid": to_wire(v.vid),
                "tags": [to_wire(t) for t in v.tags]}
    if isinstance(v, Edge):
        return {"@t": "edge", "src": to_wire(v.src), "dst": to_wire(v.dst),
                "n": v.name, "r": v.ranking, "et": v.etype,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Step):
        return {"@t": "step", "dst": to_wire(v.dst), "n": v.name,
                "r": v.ranking, "et": v.etype,
                "p": {k: to_wire(x) for k, x in v.props.items()}}
    if isinstance(v, Path):
        return {"@t": "path", "src": to_wire(v.src),
                "steps": [to_wire(s) for s in v.steps]}
    if isinstance(v, ColumnarDataSet) and v._cols is not None:
        # device-plane results stay columnar THROUGH the wire (SURVEY §2
        # row 25 / VERDICT r4 item 2): numeric columns ship as RAW
        # buffers — the RPC layer hoists the bytes into out-of-band
        # binary frames (zero copy into JSON), file/raft serialization
        # falls back to base64 — and the client decodes straight back
        # into numpy with no per-row object cost; object columns
        # (strings, vertices) use per-value encoding.  Materialized ones
        # (something already touched .rows) ship as a plain dataset.
        import numpy as np
        data = []
        for c in v._cols:
            c = np.asarray(c)
            if c.dtype.kind in "biuf":
                data.append({"dt": c.dtype.str,
                             "b": np.ascontiguousarray(c).tobytes()})
            else:
                data.append({"v": [to_wire(x) for x in c.tolist()]})
        return {"@t": "coldataset", "cols": list(v.column_names),
                "data": data}
    if isinstance(v, DataSet):
        return {"@t": "dataset", "cols": list(v.column_names),
                "rows": [[to_wire(c) for c in r] for r in v.rows]}
    if isinstance(v, list):
        return {"@t": "list", "v": [to_wire(x) for x in v]}
    if isinstance(v, tuple):
        return {"@t": "list", "v": [to_wire(x) for x in v]}
    if isinstance(v, set):
        return {"@t": "set", "v": [to_wire(x) for x in sorted(v, key=repr)]}
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            return {"@t": "map", "v": {k: to_wire(x) for k, x in v.items()}}
        # non-string keys (int vids, (rank,dst) tuples): JSON objects
        # would silently coerce them to strings, so ship pairs instead
        return {"@t": "kvmap",
                "v": [[to_wire(k), to_wire(x)] for k, x in v.items()]}
    raise TypeError(f"not wire-serializable: {type(v).__name__}")


def from_wire(j: Any) -> Any:
    if j is None or isinstance(j, (bool, int, float, str)):
        return j
    if isinstance(j, list):            # bare JSON list (rpc params etc.)
        return [from_wire(x) for x in j]
    if not isinstance(j, dict):
        raise TypeError(f"bad wire value: {type(j).__name__}")
    t = j.get("@t")
    if t is None:                      # bare JSON object
        return {k: from_wire(x) for k, x in j.items()}
    if t == "geo":
        return from_wkt(j["v"])
    if t == "null":
        return NullValue(NullKind[j["k"]])
    if t == "empty":
        return EmptyValue()
    if t == "date":
        return Date(*j["v"])
    if t == "time":
        return Time(*j["v"])
    if t == "datetime":
        return DateTime(*j["v"])
    if t == "duration":
        return Duration(*j["v"])
    if t == "tag":
        return Tag(j["n"], {k: from_wire(x) for k, x in j["p"].items()})
    if t == "vertex":
        return Vertex(from_wire(j["vid"]), [from_wire(x) for x in j["tags"]])
    if t == "edge":
        return Edge(from_wire(j["src"]), from_wire(j["dst"]), j["n"],
                    j["r"], {k: from_wire(x) for k, x in j["p"].items()},
                    etype=j["et"])
    if t == "step":
        return Step(from_wire(j["dst"]), j["n"], j["r"],
                    {k: from_wire(x) for k, x in j["p"].items()},
                    etype=j["et"])
    if t == "path":
        return Path(from_wire(j["src"]), [from_wire(s) for s in j["steps"]])
    if t == "dataset":
        return DataSet(list(j["cols"]),
                       [[from_wire(c) for c in r] for r in j["rows"]])
    if t == "coldataset":
        import numpy as np
        arrs = []
        for cj in j["data"]:
            b = cj.get("b")
            if isinstance(b, dict):          # base64 fallback (files)
                b = from_wire(b)
            if b is not None:
                arrs.append(np.frombuffer(b, dtype=np.dtype(cj["dt"])))
            else:
                arrs.append(np.array([from_wire(x) for x in cj["v"]],
                                     dtype=object))
        return ColumnarDataSet(list(j["cols"]), arrs)
    if t == "b64":
        import base64
        return base64.b64decode(j["v"])
    if t == "list":
        return [from_wire(x) for x in j["v"]]
    if t == "set":
        return {from_wire(x) for x in j["v"]}
    if t == "map":
        return {k: from_wire(x) for k, x in j["v"].items()}
    if t == "kvmap":
        out = {}
        for kj, xj in j["v"]:
            k = from_wire(kj)
            if isinstance(k, list):      # tuple keys decode as lists
                k = tuple(k)
            out[k] = from_wire(xj)
        return out
    raise TypeError(f"unknown wire tag {t!r}")


def b64_default(o):
    """json.dumps default for wire objects: raw bytes (columnar buffers)
    degrade to tagged base64 when no binary framing is available."""
    if isinstance(o, (bytes, bytearray, memoryview)):
        import base64
        return {"@t": "b64", "v": base64.b64encode(bytes(o)).decode()}
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def dumps(v: Any) -> bytes:
    """Wire-encode + JSON-serialize (raft entries, snapshots, files)."""
    return json.dumps(to_wire(v), separators=(",", ":"),
                      default=b64_default).encode()


def loads(data: bytes) -> Any:
    return from_wire(json.loads(data.decode()))
from .geo import Geography, from_wkt
