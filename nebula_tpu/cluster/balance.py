"""BALANCE DATA / BALANCE LEADER — the part-migration orchestrator.

The reference runs balance as a metad job executing a plan of
BalanceTasks (add learner → catch up → member change → remove;
reference: src/meta/processors/job/BalancePlan+BalanceTask [UNVERIFIED —
empty mount, SURVEY §2 row 17]).  Same protocol here, driven from the
graphd job manager through meta + storage RPCs — and since ISSUE 14 the
per-part mechanics live in the SHARED resumable membership task engine
(cluster/repair.py), the same one the metad PartSupervisor drives for
automatic replica repair:

  BALANCE DATA, per part (run_membership_change):
    add_learner:  the target joins as a non-voting learner (or as a
                  voter when the part already lost its quorum — the
                  only way to restore electability), storageds
                  reconcile, and the new member catches up from the
                  leader (snapshot install)
    catchup:      poll until its applied index reaches the leader's
                  commit index (`balance_catchup_timeout_secs`)
    promote:      learner → voter (one meta propose)
    remove:       drop the old replica (leadership handed off first
                  when the leader is the one leaving); its storaged
                  stops the raft member and releases the part state

  Every map change is serialized through the metad raft group, and each
  step adds OR removes (never both), so consecutive raft configurations
  always share a quorum.

  BALANCE LEADER: greedy leader spreading — count leaders per alive
  host, transfer from over- to under-loaded replicas.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

from .repair import (ClientPartOps, MembershipError, find_leader,
                     run_membership_change, transfer_leader_away)


class BalanceError(Exception):
    pass


def _alive_storage(meta) -> List[str]:
    return sorted(h["addr"] for h in meta.list_hosts()
                  if h["role"] == "storage" and h["alive"])


def _zone_map(meta, alive: List[str]) -> Dict[str, str]:
    """host → zone (unzoned alive hosts form singleton zones), matching
    rpc_create_space's placement model."""
    try:
        zones = meta.list_zones()
    except Exception:  # noqa: BLE001 — old metad without zones
        zones = {}
    out: Dict[str, str] = {}
    for z, hs in zones.items():
        for h in hs:
            out[h] = z
    for h in alive:
        out.setdefault(h, f"__host_{h}")
    return out


def _spaces(meta, space: Optional[str]) -> List[str]:
    if space:
        return [space]
    return sorted(n for n in meta.catalog.spaces)


def _ensure_replica(ops, space: str, pid: int, tgt: str,
                    alive: List[str]):
    """Grow the part onto `tgt` via the shared engine (learner →
    catch-up → promote); wraps engine errors in BalanceError so the
    job surface stays stable."""
    try:
        run_membership_change(ops, space, pid, add=tgt, alive=alive)
    except MembershipError as ex:
        raise BalanceError(str(ex)) from None


def _drop_replica(ops, space: str, pid: int, drop: str,
                  alive: List[str]):
    try:
        run_membership_change(ops, space, pid, remove=drop, alive=alive)
    except MembershipError as ex:
        raise BalanceError(str(ex)) from None


def balance_data(store, space: Optional[str] = None,
                 exclude: Optional[List[str]] = None) -> Dict[str, Any]:
    """Heal under-replication (dead hosts), spread parts over new hosts,
    drop dead replicas.  Returns the executed plan.

    `exclude` (BALANCE DATA REMOVE "host"): drain — the listed hosts are
    treated as gone, so their replicas re-home onto the remaining alive
    hosts and the drained copies are dropped; afterwards DROP HOSTS can
    remove them from the cluster.

    Placement is replica-COUNT balanced today.  A load-aware variant
    has its signal ready: per-part heat (read/write QPS EWMAs) from
    `utils.insights.PartHeatTable.heat_of` rides every storaged
    heartbeat and is merged/ranked at metad (`meta.hotspots`, SHOW
    HOTSPOTS) — weigh `load` by part heat instead of part count to
    split hot parts from each other (ISSUE 16)."""
    meta, sc = store.meta, store.sc
    ops = ClientPartOps(meta, sc)
    alive = [h for h in _alive_storage(meta)
             if not exclude or h not in exclude]
    if not alive:
        raise BalanceError("no alive storage hosts")
    plan: List[Dict[str, Any]] = []
    host_zone = _zone_map(meta, alive)
    for sp_name in _spaces(meta, space):
        pm = meta.parts_of(sp_name)
        rf = min(meta.catalog.spaces[sp_name].replica_factor, len(alive))
        load = Counter(r for reps in pm for r in reps if r in alive)
        for h in alive:
            load.setdefault(h, 0)
        # target replicas per host for an even spread
        total = len(pm) * rf
        cap = -(-total // len(alive))       # ceil
        for pid in range(len(pm)):
            replicas = list(meta.parts_of(sp_name)[pid])
            keep = [r for r in replicas if r in alive]
            # ---- heal: fill to rf on least-loaded hosts, preserving
            # the one-replica-per-zone invariant CREATE SPACE set up
            # (healing into an already-covered zone would let a single
            # zone loss take every replica of the part); zone isolation
            # relaxes only when no uncovered zone has a host left
            while len(keep) < rf:
                covered = {host_zone.get(h) for h in keep}
                cands = [h for h in alive if h not in keep
                         and host_zone.get(h) not in covered]
                if not cands:
                    cands = [h for h in alive if h not in keep]
                if not cands:
                    break
                tgt = min(cands, key=lambda h: load[h])
                _ensure_replica(ops, sp_name, pid, tgt, alive)
                keep.append(tgt)
                replicas.append(tgt)
                load[tgt] += 1
                plan.append({"space": sp_name, "part": pid, "op": "add",
                             "host": tgt})
            # ---- migrate off overloaded hosts: same-zone targets
            # first; a cross-zone move is allowed ONLY into a zone the
            # part's other replicas don't already cover — otherwise a
            # degraded zone's load imbalance is tolerated rather than
            # collapsing the one-replica-per-zone invariant
            for src in [r for r in keep if load[r] > cap]:
                same_zone = [h for h in alive
                             if h not in keep and load[h] < cap
                             and host_zone.get(h) == host_zone.get(src)]
                covered_wo_src = {host_zone.get(h) for h in keep
                                  if h != src}
                other = [h for h in alive if h not in keep
                         and load[h] < cap
                         and host_zone.get(h) not in covered_wo_src]
                cands = same_zone or other
                if not cands:
                    continue
                tgt = min(cands, key=lambda h: load[h])
                _ensure_replica(ops, sp_name, pid, tgt, alive)
                replicas.append(tgt)
                keep = [h for h in keep if h != src] + [tgt]
                load[tgt] += 1
                load[src] -= 1
                plan.append({"space": sp_name, "part": pid, "op": "move",
                             "from": src, "to": tgt})
            # ---- remove dead + migrated-away replicas, ONE per step:
            # the raft safety argument (update_peers) needs every pair of
            # consecutive configurations to share a quorum, which single
            # removals guarantee and batch removals do not
            for drop in [r for r in replicas if r not in keep]:
                _drop_replica(ops, sp_name, pid, drop, alive)
                plan.append({"space": sp_name, "part": pid,
                             "op": "shrink", "dropped": drop,
                             "replicas":
                             list(meta.parts_of(sp_name)[pid])})
    return {"plan": plan, "alive_hosts": alive}


def balance_leader(store, space: Optional[str] = None) -> Dict[str, Any]:
    """Spread raft leadership evenly over alive hosts."""
    meta, sc = store.meta, store.sc
    ops = ClientPartOps(meta, sc)
    alive = set(_alive_storage(meta))
    if not alive:
        raise BalanceError("no alive storage hosts")
    transfers: List[Dict[str, Any]] = []
    for sp_name in _spaces(meta, space):
        pm = meta.parts_of(sp_name)
        lead_count: Counter = Counter()
        leaders: Dict[int, Optional[str]] = {}
        for pid, replicas in enumerate(pm):
            cands = [r for r in replicas if r in alive]
            ld = find_leader(ops, cands, sp_name, pid)
            leaders[pid] = ld
            if ld:
                lead_count[ld] += 1
        for h in alive:
            lead_count.setdefault(h, 0)
        cap = -(-len(pm) // len(alive))     # ceil
        for pid, replicas in enumerate(pm):
            ld = leaders[pid]
            cands = [r for r in replicas if r in alive]
            if not cands:
                continue
            if ld is not None and lead_count[ld] <= cap:
                continue
            under = [c for c in cands if c != ld
                     and lead_count[c] < cap]
            if not under:
                continue
            tgt = min(under, key=lambda h: lead_count[h])
            if transfer_leader_away(ops, sp_name, pid, cands, tgt):
                if ld:
                    lead_count[ld] -= 1
                lead_count[tgt] += 1
                transfers.append({"space": sp_name, "part": pid,
                                  "from": ld, "to": tgt})
    return {"transfers": transfers}
