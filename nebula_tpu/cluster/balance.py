"""BALANCE DATA / BALANCE LEADER — the part-migration orchestrator.

The reference runs balance as a metad job executing a plan of
BalanceTasks (add learner → catch up → member change → remove;
reference: src/meta/processors/job/BalancePlan+BalanceTask [UNVERIFIED —
empty mount, SURVEY §2 row 17]).  Same protocol here, driven from the
graphd job manager through meta + storage RPCs:

  BALANCE DATA, per part:
    phase A (add):    part map gains the new replica → storageds
                      reconcile → the new member joins the raft group and
                      catches up from the leader (snapshot install)
    phase B (lead):   if the leader is being removed, transfer
                      leadership to a surviving replica (TimeoutNow)
    phase C (remove): part map drops the old replica → its storaged
                      stops the raft member and releases the part state

  Every map change is serialized through the metad raft group, and each
  step adds OR removes (never both), so consecutive raft configurations
  always share a quorum.

  BALANCE LEADER: greedy leader spreading — count leaders per alive
  host, transfer from over- to under-loaded replicas.
"""
from __future__ import annotations

import time
from collections import Counter
from typing import Any, Dict, List, Optional

CATCHUP_TIMEOUT_S = 30.0


class BalanceError(Exception):
    pass


def _alive_storage(meta) -> List[str]:
    return sorted(h["addr"] for h in meta.list_hosts()
                  if h["role"] == "storage" and h["alive"])


def _reconcile(sc, hosts: List[str]):
    for h in hosts:
        try:
            sc._client(h).call("storage.reconcile")
        except Exception:  # noqa: BLE001 — host may be mid-death
            pass


def _raft_info(sc, host: str, space: str, pid: int) -> Optional[Dict]:
    try:
        return sc._client(host).call("storage.part_raft_info",
                                     space=space, part=pid)
    except Exception:  # noqa: BLE001
        return None


def _find_leader(sc, hosts: List[str], space: str, pid: int
                 ) -> Optional[str]:
    for h in hosts:
        info = _raft_info(sc, h, space, pid)
        if info and info["is_leader"]:
            return h
    return None


def _wait_caught_up(sc, host: str, leader: str, space: str, pid: int,
                    timeout: float = CATCHUP_TIMEOUT_S,
                    hosts: Optional[List[str]] = None):
    """Poll the new replica until its applied index reaches the leader's
    commit index as of entry.  The leader's index MUST be known — a
    transient RPC failure must not degrade the target to 0, or an empty
    replica reads as caught up and the shrink phase drops the only full
    copy.

    The leader may DIE mid-catchup (ISSUE 5 satellite): instead of
    aborting the data move, re-discover the new leader among `hosts`
    and resume — a freshly elected leader's commit index covers every
    entry the dead one had committed, so re-anchoring the target on it
    never lowers the bar below already-committed state."""
    dl = time.monotonic() + timeout
    # the catch-up target itself stays a candidate: raft log-
    # completeness can make the NEW replica win the post-crash
    # election, and anchoring on its own commit index is equally safe
    cands = list(hosts or []) or [leader]
    cur: Optional[str] = leader
    target = None
    while target is None and time.monotonic() < dl:
        li = _raft_info(sc, cur, space, pid) if cur else None
        if li is not None and li.get("is_leader", True):
            target = li["commit_index"]
            break
        # named leader dead/deposed: walk the replica set for its
        # successor (an election in flight keeps returning None — poll)
        cur = _find_leader(sc, cands, space, pid)
        if cur is None:
            time.sleep(0.05)
    if target is None:
        raise BalanceError(
            f"no reachable leader for {space}/{pid} (last tried "
            f"{cur or leader}); cannot establish a catch-up target")
    while time.monotonic() < dl:
        info = _raft_info(sc, host, space, pid)
        if info and info["last_applied"] >= target:
            return
        time.sleep(0.05)
    raise BalanceError(
        f"replica {host} of {space}/{pid} did not catch up to {target}")


def _transfer_leader(meta, sc, space: str, pid: int, hosts: List[str],
                     to: str, timeout: float = 10.0) -> bool:
    cur = _find_leader(sc, hosts, space, pid)
    if cur == to:
        meta.transfer_leader(space, pid, to)
        return True
    if cur is None:
        return False
    try:
        r = sc._client(cur).call("storage.transfer_part_leader",
                                 space=space, part=pid, to=to)
    except Exception:  # noqa: BLE001
        return False
    if not (isinstance(r, dict) and r.get("ok")):
        return False        # definitive refusal — don't poll the timeout
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        info = _raft_info(sc, to, space, pid)
        if info and info["is_leader"]:
            meta.transfer_leader(space, pid, to)
            return True
        time.sleep(0.05)
    return False


def _zone_map(meta, alive: List[str]) -> Dict[str, str]:
    """host → zone (unzoned alive hosts form singleton zones), matching
    rpc_create_space's placement model."""
    try:
        zones = meta.list_zones()
    except Exception:  # noqa: BLE001 — old metad without zones
        zones = {}
    out: Dict[str, str] = {}
    for z, hs in zones.items():
        for h in hs:
            out[h] = z
    for h in alive:
        out.setdefault(h, f"__host_{h}")
    return out


def _spaces(meta, space: Optional[str]) -> List[str]:
    if space:
        return [space]
    return sorted(n for n in meta.catalog.spaces)


def balance_data(store, space: Optional[str] = None,
                 exclude: Optional[List[str]] = None) -> Dict[str, Any]:
    """Heal under-replication (dead hosts), spread parts over new hosts,
    drop dead replicas.  Returns the executed plan.

    `exclude` (BALANCE DATA REMOVE "host"): drain — the listed hosts are
    treated as gone, so their replicas re-home onto the remaining alive
    hosts and the drained copies are dropped; afterwards DROP HOSTS can
    remove them from the cluster."""
    meta, sc = store.meta, store.sc
    alive = [h for h in _alive_storage(meta)
             if not exclude or h not in exclude]
    if not alive:
        raise BalanceError("no alive storage hosts")
    plan: List[Dict[str, Any]] = []
    host_zone = _zone_map(meta, alive)
    for sp_name in _spaces(meta, space):
        pm = meta.parts_of(sp_name)
        rf = min(meta.catalog.spaces[sp_name].replica_factor, len(alive))
        load = Counter(r for reps in pm for r in reps if r in alive)
        for h in alive:
            load.setdefault(h, 0)
        # target replicas per host for an even spread
        total = len(pm) * rf
        cap = -(-total // len(alive))       # ceil
        for pid in range(len(pm)):
            replicas = list(meta.parts_of(sp_name)[pid])
            keep = [r for r in replicas if r in alive]
            # ---- heal: fill to rf on least-loaded hosts, preserving
            # the one-replica-per-zone invariant CREATE SPACE set up
            # (healing into an already-covered zone would let a single
            # zone loss take every replica of the part); zone isolation
            # relaxes only when no uncovered zone has a host left
            while len(keep) < rf:
                covered = {host_zone.get(h) for h in keep}
                cands = [h for h in alive if h not in keep
                         and host_zone.get(h) not in covered]
                if not cands:
                    cands = [h for h in alive if h not in keep]
                if not cands:
                    break
                tgt = min(cands, key=lambda h: load[h])
                _add_replica(meta, sc, sp_name, pid, replicas, tgt, alive)
                keep.append(tgt)
                replicas.append(tgt)
                load[tgt] += 1
                plan.append({"space": sp_name, "part": pid, "op": "add",
                             "host": tgt})
            # ---- migrate off overloaded hosts: same-zone targets
            # first; a cross-zone move is allowed ONLY into a zone the
            # part's other replicas don't already cover — otherwise a
            # degraded zone's load imbalance is tolerated rather than
            # collapsing the one-replica-per-zone invariant
            for src in [r for r in keep if load[r] > cap]:
                same_zone = [h for h in alive
                             if h not in keep and load[h] < cap
                             and host_zone.get(h) == host_zone.get(src)]
                covered_wo_src = {host_zone.get(h) for h in keep
                                  if h != src}
                other = [h for h in alive if h not in keep
                         and load[h] < cap
                         and host_zone.get(h) not in covered_wo_src]
                cands = same_zone or other
                if not cands:
                    continue
                tgt = min(cands, key=lambda h: load[h])
                _add_replica(meta, sc, sp_name, pid, replicas, tgt, alive)
                replicas.append(tgt)
                keep = [h for h in keep if h != src] + [tgt]
                load[tgt] += 1
                load[src] -= 1
                plan.append({"space": sp_name, "part": pid, "op": "move",
                             "from": src, "to": tgt})
            # ---- remove dead + migrated-away replicas, ONE per step:
            # the raft safety argument (update_peers) needs every pair of
            # consecutive configurations to share a quorum, which single
            # removals guarantee and batch removals do not
            current = list(replicas)
            for drop in [r for r in replicas if r not in keep]:
                leader = _find_leader(sc, keep, sp_name, pid)
                if leader is None:
                    # leader is being removed (or died): hand off first
                    if not _transfer_leader(meta, sc, sp_name, pid,
                                            current, keep[0]):
                        raise BalanceError(
                            f"cannot move leadership of {sp_name}/{pid} "
                            f"into the surviving set {keep}")
                    leader = keep[0]
                current = [h for h in current if h != drop]
                ordered = [leader] + [h for h in current if h != leader]
                meta.set_part_replicas(sp_name, pid, ordered)
                _reconcile(sc, sorted(set(alive + [drop])))
                current = ordered
                plan.append({"space": sp_name, "part": pid, "op": "shrink",
                             "dropped": drop, "replicas": ordered})
    return {"plan": plan, "alive_hosts": alive}


def _add_replica(meta, sc, space: str, pid: int, replicas: List[str],
                 tgt: str, alive: List[str]):
    meta.set_part_replicas(space, pid, list(replicas) + [tgt])
    _reconcile(sc, alive)
    live = [r for r in replicas if r in alive] + [tgt]
    leader = _find_leader(sc, live, space, pid)
    dl = time.monotonic() + CATCHUP_TIMEOUT_S
    while leader is None and time.monotonic() < dl:
        time.sleep(0.05)            # election in flight
        leader = _find_leader(sc, live, space, pid)
    if leader is None:
        raise BalanceError(f"no leader for {space}/{pid} during add")
    _wait_caught_up(sc, tgt, leader, space, pid, hosts=live)


def balance_leader(store, space: Optional[str] = None) -> Dict[str, Any]:
    """Spread raft leadership evenly over alive hosts."""
    meta, sc = store.meta, store.sc
    alive = set(_alive_storage(meta))
    if not alive:
        raise BalanceError("no alive storage hosts")
    transfers: List[Dict[str, Any]] = []
    for sp_name in _spaces(meta, space):
        pm = meta.parts_of(sp_name)
        lead_count: Counter = Counter()
        leaders: Dict[int, Optional[str]] = {}
        for pid, replicas in enumerate(pm):
            cands = [r for r in replicas if r in alive]
            ld = _find_leader(sc, cands, sp_name, pid)
            leaders[pid] = ld
            if ld:
                lead_count[ld] += 1
        for h in alive:
            lead_count.setdefault(h, 0)
        cap = -(-len(pm) // len(alive))     # ceil
        for pid, replicas in enumerate(pm):
            ld = leaders[pid]
            cands = [r for r in replicas if r in alive]
            if not cands:
                continue
            if ld is not None and lead_count[ld] <= cap:
                continue
            under = [c for c in cands if c != ld
                     and lead_count[c] < cap]
            if not under:
                continue
            tgt = min(under, key=lambda h: lead_count[h])
            if _transfer_leader(meta, sc, sp_name, pid, cands, tgt):
                if ld:
                    lead_count[ld] -= 1
                lead_count[tgt] += 1
                transfers.append({"space": sp_name, "part": pid,
                                  "from": ld, "to": tgt})
    return {"transfers": transfers}
