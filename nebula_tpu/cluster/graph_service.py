"""Graph service — the stateless query frontend (graphd).

Authenticate → session (registered in metad so any graphd can list/kill
it) → execute (the full parse→plan→optimize→schedule pipeline of
exec.engine over a DistributedStore) → wire-encoded ResultSet.  Analog
of the reference's GraphService/QueryInstance/GraphSessionManager
(reference: src/graph/service + src/graph/session [UNVERIFIED — empty
mount, SURVEY §0]).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..core.wire import to_wire
from ..exec.engine import QueryEngine, Session
from .dstore import DistributedStore
from .meta_client import MetaClient
from .rpc import RpcError, RpcServer



class GraphService:
    def __init__(self, my_addr: str, meta: MetaClient, server: RpcServer,
                 tpu_runtime=None, users: Optional[Dict[str, str]] = None):
        self.my_addr = my_addr
        self.meta = meta
        self.store = DistributedStore(meta)
        self.engine = QueryEngine(self.store, tpu_runtime=tpu_runtime)
        # SHOW HOSTS / SHOW SESSIONS read live cluster state through meta
        self.engine.qctx.cluster = meta
        self.sessions: Dict[int, Session] = {}
        from ..utils.racecheck import make_lock
        self.lock = make_lock("graph_sessions")
        # password auth; default open root (the reference ships
        # enable_authorize=false with root/nebula)
        self.users = users if users is not None else {"root": "nebula"}
        self._users_explicit = users is not None
        server.service_role = "graphd"
        server.register_service(self, prefix="graph.")
        self._reaper = threading.Thread(target=self._reap_idle, daemon=True)
        self._reaper_stop = threading.Event()
        self._reaper.start()

    def start(self):
        self.meta.start_heartbeat()

    def stop(self):
        self._reaper_stop.set()
        self.meta.stop_heartbeat()

    def _reap_idle(self):
        from ..utils.config import get_config
        while not self._reaper_stop.wait(5.0):
            now = time.time()
            idle_s = float(get_config().get("session_idle_timeout_secs"))
            with self.lock:
                dead = [sid for sid, s in self.sessions.items()
                        if now - s.last_used > idle_s]
            for sid in dead:
                self._drop_session(sid)

    def _drop_session(self, sid: int):
        with self.lock:
            self.sessions.pop(sid, None)
        self.engine.sessions.pop(sid, None)
        try:
            self.meta.remove_session(sid)
        except Exception:  # noqa: BLE001 — metad may be down; reap anyway
            pass

    # -- RPC --------------------------------------------------------------

    def _check_password(self, user: str, pwd: str) -> bool:
        """An EXPLICITLY injected users map (constructor arg, the test
        harness / static-config path) wins for the accounts it names —
        the catalog always contains a default root, which must not
        override an operator-configured root password.  Every other
        account is checked against the meta-replicated user catalog
        (CREATE USER / ALTER USER), with NO static fallback — a rotated
        password's predecessor stays dead."""
        if self._users_explicit and user in self.users:
            return self.users[user] == pwd
        from ..graphstore.schema import SchemaError
        try:
            udesc = self.store.catalog.get_user(user)
        except (SchemaError, KeyError):
            udesc = None
        except Exception:  # noqa: BLE001 — meta unreachable: fail closed
            return False
        if udesc is not None:
            return udesc.check_password(pwd)
        return self.users.get(user) == pwd

    @property
    def auth_required(self) -> bool:
        # live: UPDATE CONFIGS enable_authorize must take effect on a
        # running graphd, not only after restart
        from ..utils.config import get_config
        return self._users_explicit or bool(
            get_config().get("enable_authorize"))

    def rpc_authenticate(self, p):
        user = p.get("user", "root")
        pwd = p.get("password", "")
        if self.auth_required and not self._check_password(user, pwd):
            raise RpcError("Bad username/password")
        sid = self.meta.create_session(user, self.my_addr)
        sess = Session(user)
        sess.id = sid
        with self.lock:
            self.sessions[sid] = sess
        # the engine's registry serves SHOW QUERIES / KILL QUERY — a
        # cluster session must be visible there too (same object, metad
        # session id)
        self.engine.sessions[sid] = sess
        return {"session_id": sid}

    def rpc_signout(self, p):
        self._drop_session(p["session_id"])
        return True

    def rpc_execute(self, p):
        with self.lock:
            sess = self.sessions.get(p["session_id"])
        if sess is None:
            raise RpcError("Session invalid or expired")
        rs = self.engine.execute(sess, p["stmt"])
        if sess.space:
            try:
                self.meta.update_session(sess.id, space=sess.space)
            except Exception:  # noqa: BLE001
                pass
        return {
            "error": rs.error,
            "space": rs.space,
            "latency_us": rs.latency_us,
            # bulk numeric results leave here as typed column blobs
            # (core/wire.py columnar fast path) — the RPC layer ships
            # them out-of-band of the JSON, zero-copy
            "data": to_wire(rs.data) if rs.data is not None else None,
            "plan_desc": rs.plan_desc,
        }

    def rpc_list_sessions(self, p):
        return self.meta.list_sessions()

    def rpc_kill_session(self, p):
        self._drop_session(p["session_id"])
        return True

    def rpc_list_queries(self, p):
        """This graphd's RUNNING queries with live per-operator
        progress (SHOW [ALL] QUERIES fans out over every graphd named
        in metad's session table) — row shape documented at
        QueryEngine.list_running_queries."""
        return self.engine.list_running_queries()

    def rpc_list_statements(self, p):
        """This graphd's insights registry snapshot (ISSUE 16): per-
        fingerprint mergeable aggregate dicts — SHOW STATEMENTS fans
        out over every registered graph host and sums them exactly
        (shared fixed latency buckets)."""
        return self.engine.insights.snapshot()

    def rpc_session_live(self, p):
        """The live half of SHOW SESSIONS (ISSUE 9): metad's replicated
        table knows user/space/created, but last-used time and the
        in-flight statement count only exist on the owning graphd."""
        with self.lock:
            items = list(self.sessions.items())
        return {sid: [s.last_used, len(s.queries)] for sid, s in items}

    def rpc_stop_job(self, p):
        """STOP JOB routed from another graphd: this one is the
        executor named in metad's job table — stop it in the LOCAL
        worker pool and report the resulting status."""
        from ..exec.jobs import job_manager
        mgr = job_manager(self.engine.qctx.store)
        job = mgr.jobs.get(p["job_id"])
        if job is None:
            return None
        if job.status != "FINISHED":
            mgr.stop(job)
        return job.status

    def rpc_kill_query(self, p):
        """Set the kill event of a RUNNING query on THIS graphd; returns
        whether anything matched (the issuing graphd raises if no owner
        matched anywhere)."""
        return self.engine.kill_running(p.get("session_id"),
                                        p.get("plan_id"))
