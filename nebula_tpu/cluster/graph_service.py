"""Graph service — the stateless query frontend (graphd).

Authenticate → session (registered in metad so any graphd can list/kill
it) → execute (the full parse→plan→optimize→schedule pipeline of
exec.engine over a DistributedStore) → wire-encoded ResultSet.  Analog
of the reference's GraphService/QueryInstance/GraphSessionManager
(reference: src/graph/service + src/graph/session [UNVERIFIED — empty
mount, SURVEY §0]).
"""
from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, Optional

from ..core.wire import to_wire
from ..exec.engine import QueryEngine, Session
from ..utils.admission import overload_error
from ..utils.config import get_config
from ..utils.stats import stats
from .dstore import DistributedStore
from .meta_client import MetaClient
from .rpc import RpcError, RpcServer
from .storage_service import _ReadBucket

#: statements that bypass the per-coordinator capacity bucket — the
#: diagnosis/repair lane (SHOW QUERIES, KILL, session plumbing) must
#: keep answering on the very coordinator whose overload is being
#: diagnosed (the admission controller's control-lane rule, applied
#: at the capacity gate too)
_CONTROL_LEAD = re.compile(r"[\s(]*(SHOW|KILL|DESC|DESCRIBE|USE)\b",
                           re.IGNORECASE)


class GraphService:
    def __init__(self, my_addr: str, meta: MetaClient, server: RpcServer,
                 tpu_runtime=None, users: Optional[Dict[str, str]] = None):
        self.my_addr = my_addr
        self.meta = meta
        self.store = DistributedStore(meta)
        self.engine = QueryEngine(self.store, tpu_runtime=tpu_runtime)
        # SHOW HOSTS / SHOW SESSIONS read live cluster state through meta
        self.engine.qctx.cluster = meta
        self.sessions: Dict[int, Session] = {}
        from ..utils.racecheck import make_lock
        self.lock = make_lock("graph_sessions")
        # fleet fault tolerance (ISSUE 20): peers' write epochs fold in
        # from two directions — every metad heartbeat reply (bounded
        # window) and this graphd's own storaged write acks (immediate)
        self.meta.on_epochs = self.engine.cluster_epochs.fold_table
        self.store.on_epoch_ack = self.engine.cluster_epochs.note_ack
        self.engine.epoch_sync = self._epoch_sync
        # graceful drain: once set, new statements are refused with a
        # structured E_SESSION_MOVED + sibling hint; in-flight ones
        # finish inside their deadline budget
        self._draining = False
        self._sibling_cache: tuple = (0.0, None)   # (monotonic ts, addr)
        self._server = server
        # per-COORDINATOR statement capacity (graph_statement_capacity_qps):
        # one bucket per GraphService instance — the unit that scales
        # when a deployment adds graphds (admission slots are process-
        # global and model the shared engine, not the coordinator)
        self._stmt_bucket = _ReadBucket()
        # password auth; default open root (the reference ships
        # enable_authorize=false with root/nebula)
        self.users = users if users is not None else {"root": "nebula"}
        self._users_explicit = users is not None
        server.service_role = "graphd"
        server.register_service(self, prefix="graph.")
        self._reaper = threading.Thread(target=self._reap_idle, daemon=True)
        self._reaper_stop = threading.Event()
        self._reaper.start()

    def start(self):
        self.meta.start_heartbeat()

    def stop(self):
        self._reaper_stop.set()
        self.meta.stop_heartbeat()

    # -- fleet fault tolerance (ISSUE 20) ---------------------------------

    def _epoch_sync(self):
        """Strict check-at-admission: pull metad's merged epoch table
        and fold it, so a leader-consistency cached read observes every
        write acked anywhere in the fleet that reached metad."""
        self.engine.cluster_epochs.fold_table(self.meta.cluster_epochs())

    def _sibling_hint(self) -> Optional[str]:
        """Another ONLINE graphd to hand sessions to (1 s cached — the
        drain path must not hammer metad once per refused statement)."""
        ts, addr = self._sibling_cache
        now = time.monotonic()
        if now - ts < 1.0:
            return addr
        addr = None
        try:
            for h in self.meta.list_hosts():
                if h.get("role") == "graph" and h.get("addr") != self.my_addr \
                        and h.get("status") == "ONLINE":
                    addr = h["addr"]
                    break
        except Exception:  # noqa: BLE001 — metad down: no hint, client ranks
            addr = None
        self._sibling_cache = (now, addr)
        return addr

    def _session_moved(self) -> RpcError:
        sib = self._sibling_hint()
        return RpcError(f"E_SESSION_MOVED: graphd {self.my_addr} draining; "
                        f"sibling={sib or '-'}")

    def drain(self, timeout_s: Optional[float] = None) -> int:
        """Graceful drain: stop admitting, wait for in-flight statements
        to finish inside their deadline budget, leave the metad session
        rows for siblings to adopt.  Returns the number of sessions
        handed off.  A planned restart through here sheds ZERO acked
        statements — every refused statement gets a structured
        E_SESSION_MOVED (provably not executed → any-statement retry is
        safe), never a raw connection reset."""
        self._draining = True
        if timeout_s is None:
            try:
                timeout_s = max(float(get_config().get(
                    "query_timeout_secs")) or 30.0, 1.0)
            except Exception:  # noqa: BLE001
                timeout_s = 30.0
        deadline = time.monotonic() + timeout_s

        def busy() -> bool:
            # the engine registry alone is not enough: a statement that
            # arrived before _draining was set may still be in
            # parse/plan (not yet in s.queries) or writing its reply —
            # the server's dispatch inbox counts a request from receive
            # until its reply frame is WRITTEN, so inbox==0 means every
            # admitted statement's outcome reached the wire.  (drain()
            # is an in-process call — launcher/ops — so it never holds
            # an inbox slot itself.)
            if getattr(self._server, "_inbox", 0) > 0:
                return True
            with self.lock:
                return any(s.queries for s in self.sessions.values())

        settled = 0
        while time.monotonic() < deadline:
            if not busy():
                # require two consecutive idle observations a beat
                # apart: a statement between socket receive and inbox
                # admission is invisible for a few instructions
                settled += 1
                if settled >= 2:
                    break
            else:
                settled = 0
            time.sleep(0.02)
        with self.lock:
            n = len(self.sessions)
        stats().inc("graphd_drains")
        return n

    def rpc_adopt_session(self, p):
        """Re-home a session on THIS graphd after its owner died or
        drained.  The session row is metad-replicated, so identity
        (user, space) survives the owner; credentials are re-checked —
        a sid alone must never be enough to steal a session.  $var
        state was owner-local and is gone (documented in ROBUSTNESS
        §10); space is restored from the replicated row."""
        if self._draining:
            raise self._session_moved()
        sid = p["session_id"]
        user = p.get("user", "root")
        if self.auth_required and not self._check_password(
                user, p.get("password", "")):
            raise RpcError("Bad username/password")
        row = None
        try:
            for s in self.meta.list_sessions():
                if s["sid"] == sid:
                    row = s
                    break
        except Exception as ex:  # noqa: BLE001
            raise RpcError(f"metad unavailable: {ex}") from None
        if row is None:
            raise RpcError(f"E_SESSION_UNKNOWN: session {sid} not in "
                           "metad table (expired or killed)")
        if row.get("user") != user:
            raise RpcError("session user mismatch")
        with self.lock:
            sess = self.sessions.get(sid)
            if sess is None:
                sess = Session(user)
                sess.id = sid
                sess.space = row.get("space") or None
                self.sessions[sid] = sess
                self.engine.sessions[sid] = sess
        try:
            self.meta.update_session(sid, graphd=self.my_addr)
        except Exception:  # noqa: BLE001 — row update is advisory
            pass
        self._note_sessions()
        stats().inc("session_moves")
        return {"session_id": sid, "space": sess.space}

    def rpc_tenant_snapshot(self, p):
        """This graphd's per-tenant admission view (SHOW TENANTS fans
        out over every graph host and merges)."""
        from ..utils.admission import admission
        return admission().tenant_snapshot()

    def _reap_idle(self):
        from ..utils.config import get_config
        while not self._reaper_stop.wait(5.0):
            now = time.time()
            idle_s = float(get_config().get("session_idle_timeout_secs"))
            with self.lock:
                dead = [sid for sid, s in self.sessions.items()
                        if now - s.last_used > idle_s]
            for sid in dead:
                self._drop_session(sid)

    def _drop_session(self, sid: int):
        with self.lock:
            self.sessions.pop(sid, None)
        self.engine.sessions.pop(sid, None)
        self._note_sessions()
        try:
            self.meta.remove_session(sid)
        except Exception:  # noqa: BLE001 — metad may be down; reap anyway
            pass

    def _note_sessions(self):
        """Refresh the per-coordinator session gauge (`graph_sessions`)
        — the fleet view's per-host load signal (metrics_dump --fleet)."""
        with self.lock:
            n = len(self.sessions)
        stats().gauge("graph_sessions", float(n))

    # -- RPC --------------------------------------------------------------

    def _check_password(self, user: str, pwd: str) -> bool:
        """An EXPLICITLY injected users map (constructor arg, the test
        harness / static-config path) wins for the accounts it names —
        the catalog always contains a default root, which must not
        override an operator-configured root password.  Every other
        account is checked against the meta-replicated user catalog
        (CREATE USER / ALTER USER), with NO static fallback — a rotated
        password's predecessor stays dead."""
        if self._users_explicit and user in self.users:
            return self.users[user] == pwd
        from ..graphstore.schema import SchemaError
        try:
            udesc = self.store.catalog.get_user(user)
        except (SchemaError, KeyError):
            udesc = None
        except Exception:  # noqa: BLE001 — meta unreachable: fail closed
            return False
        if udesc is not None:
            return udesc.check_password(pwd)
        return self.users.get(user) == pwd

    @property
    def auth_required(self) -> bool:
        # live: UPDATE CONFIGS enable_authorize must take effect on a
        # running graphd, not only after restart
        from ..utils.config import get_config
        return self._users_explicit or bool(
            get_config().get("enable_authorize"))

    def rpc_authenticate(self, p):
        if self._draining:
            raise self._session_moved()
        user = p.get("user", "root")
        pwd = p.get("password", "")
        if self.auth_required and not self._check_password(user, pwd):
            raise RpcError("Bad username/password")
        sid = self.meta.create_session(user, self.my_addr)
        sess = Session(user)
        sess.id = sid
        with self.lock:
            self.sessions[sid] = sess
        # the engine's registry serves SHOW QUERIES / KILL QUERY — a
        # cluster session must be visible there too (same object, metad
        # session id)
        self.engine.sessions[sid] = sess
        self._note_sessions()
        return {"session_id": sid}

    def rpc_signout(self, p):
        self._drop_session(p["session_id"])
        return True

    def rpc_execute(self, p):
        if self._draining:
            # refused BEFORE execution: the client may retry ANY
            # statement (including writes) on the sibling — nothing ran
            raise self._session_moved()
        cap = float(get_config().get("graph_statement_capacity_qps") or 0)
        if cap > 0 and not _CONTROL_LEAD.match(p.get("stmt", "")):
            retry = self._stmt_bucket.take(cap)
            if retry is not None:
                # shed BEFORE execution: same structured contract as a
                # storaged read-capacity shed — a fleet client walks to
                # a sibling coordinator with spare tokens
                stats().inc_labeled(
                    "overload_server_rejections",
                    {"op": "graph.statement_capacity", "role": "graphd"})
                raise RpcError(overload_error(
                    retry, "graphd:statement_capacity",
                    f"statement capacity {cap:g}/s exhausted"))
        with self.lock:
            sess = self.sessions.get(p["session_id"])
        if sess is None:
            raise RpcError("Session invalid or expired")
        rs = self.engine.execute(sess, p["stmt"])
        if sess.space:
            try:
                self.meta.update_session(sess.id, space=sess.space)
            except Exception:  # noqa: BLE001
                pass
        return {
            "error": rs.error,
            "space": rs.space,
            "latency_us": rs.latency_us,
            # bulk numeric results leave here as typed column blobs
            # (core/wire.py columnar fast path) — the RPC layer ships
            # them out-of-band of the JSON, zero-copy
            "data": to_wire(rs.data) if rs.data is not None else None,
            "plan_desc": rs.plan_desc,
        }

    def rpc_list_sessions(self, p):
        return self.meta.list_sessions()

    def rpc_kill_session(self, p):
        self._drop_session(p["session_id"])
        return True

    def rpc_list_queries(self, p):
        """This graphd's RUNNING queries with live per-operator
        progress (SHOW [ALL] QUERIES fans out over every graphd named
        in metad's session table) — row shape documented at
        QueryEngine.list_running_queries."""
        return self.engine.list_running_queries()

    def rpc_list_statements(self, p):
        """This graphd's insights registry snapshot (ISSUE 16): per-
        fingerprint mergeable aggregate dicts — SHOW STATEMENTS fans
        out over every registered graph host and sums them exactly
        (shared fixed latency buckets)."""
        return self.engine.insights.snapshot()

    def rpc_session_live(self, p):
        """The live half of SHOW SESSIONS (ISSUE 9): metad's replicated
        table knows user/space/created, but last-used time and the
        in-flight statement count only exist on the owning graphd."""
        with self.lock:
            items = list(self.sessions.items())
        return {sid: [s.last_used, len(s.queries)] for sid, s in items}

    def rpc_stop_job(self, p):
        """STOP JOB routed from another graphd: this one is the
        executor named in metad's job table — stop it in the LOCAL
        worker pool and report the resulting status."""
        from ..exec.jobs import job_manager
        mgr = job_manager(self.engine.qctx.store)
        job = mgr.jobs.get(p["job_id"])
        if job is None:
            return None
        if job.status != "FINISHED":
            mgr.stop(job)
        return job.status

    def rpc_kill_query(self, p):
        """Set the kill event of a RUNNING query on THIS graphd; returns
        whether anything matched (the issuing graphd raises if no owner
        matched anywhere)."""
        return self.engine.kill_running(p.get("session_id"),
                                        p.get("plan_id"))
