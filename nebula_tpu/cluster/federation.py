"""Metric federation — metad scrapes the cluster into one view (ISSUE 8).

One cluster, one metric surface: each daemon already serves its own
Prometheus `/metrics`, but operating a 3-replica × N-graphd cluster
means N+M+K scrape targets and no single place to ask "what is the
cluster doing".  The `MetricFederator` runs on metad (the one daemon
that already knows every host — heartbeats carry each daemon's
webservice address), periodically scrapes every alive graphd/storaged
`/metrics`, injects `instance`/`role` labels into every sample, and
serves the merged text at `GET /cluster_metrics` — point ONE Prometheus
scrape (or a human) at metad and see the whole cluster.

Scrape failures are non-fatal: a dead host's samples age out of the
merged view and `federation_scrape_errors` counts the misses.  Every
daemon refreshes its OWN `slo_burn_*` gauges inside its /metrics
handler (webservice.py), so each federation round pulls burn rates
computed from that daemon's real traffic — no per-process poller
needed.
"""
from __future__ import annotations

import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..utils.config import define_flag, get_config
from ..utils.slo import slo_engine
from ..utils.stats import stats

define_flag("metric_federation_interval_secs", 5.0,
            "how often metad re-scrapes every daemon's /metrics into "
            "/cluster_metrics (0 disables the background loop; the "
            "endpoint then scrapes on demand)")
define_flag("metric_federation_timeout_secs", 3.0,
            "per-target HTTP timeout for federation scrapes")


def _inject_labels(text: str, instance: str, role: str) -> List[str]:
    """Rewrite one exposition payload: every sample line gains
    instance/role labels; TYPE comments pass through for dedup by the
    merger."""
    extra = (f'instance="{instance}",role="{role}"')
    out: List[str] = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            out.append(ln)
            continue
        # sample grammar: name[{labels}] value [timestamp]
        brace = ln.find("{")
        space = ln.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            close = ln.rfind("}")
            if close == -1:
                continue                   # malformed: drop the line
            body = ln[brace + 1:close]
            sep = "," if body else ""
            out.append(ln[:brace + 1] + body + sep + extra + ln[close:])
        elif space != -1:
            out.append(ln[:space] + "{" + extra + "}" + ln[space:])
    return out


class MetricFederator:
    """Scrape-and-merge loop over the meta service's active hosts."""

    def __init__(self, meta_service, self_ws: str = ""):
        self.meta = meta_service
        # metad's own webservice (scraped too, so its raft/meta metrics
        # land in the same view); empty = skip self
        self.self_ws = self_ws
        self._lock = threading.Lock()
        self._merged = ""
        self._last_scrape = 0.0
        self._status: Dict[str, Dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- targets ----------------------------------------------------------

    def targets(self) -> List[Tuple[str, str, str]]:
        """[(instance addr, role, ws addr)] for every alive daemon that
        reported a webservice address, plus metad itself."""
        out: List[Tuple[str, str, str]] = []
        if self.self_ws:
            out.append((self.meta.my_addr, "metad", self.self_ws))
        now = time.monotonic()
        from .meta_service import _hb_expire_s
        exp = _hb_expire_s()
        # .copy() is atomic under the GIL; iterating the live dict
        # would race a first-heartbeat insert from an RPC thread
        # ("dictionary changed size during iteration") exactly when
        # membership changes — the moment the federated view matters
        for addr, h in sorted(self.meta.active_hosts.copy().items()):
            ws = h.get("ws")
            if not ws or now - h["last_hb"] >= exp:
                continue
            role = {"graph": "graphd", "storage": "storaged"}.get(
                h["role"], h["role"])
            out.append((addr, role, ws))
        return out

    # -- scraping ---------------------------------------------------------

    def _fetch(self, ws: str, path: str = "/metrics") -> str:
        try:
            timeout = float(get_config().get(
                "metric_federation_timeout_secs"))
        except Exception:  # noqa: BLE001
            timeout = 3.0
        with urllib.request.urlopen(f"http://{ws}{path}",
                                    timeout=timeout) as r:
            return r.read().decode()

    def _fan_out(self, path: str):
        """Concurrently fetch `path` from every alive target — the ONE
        fan-out used by both /cluster_metrics and /cluster_queries, so
        timeout/error handling cannot diverge between them.  Targets
        are fetched concurrently: a rolling restart can leave several
        heartbeat-alive-but-unreachable daemons, and a serial walk
        would stack their timeouts into a tens-of-seconds round
        exactly when the cluster view matters most.  Returns
        [(target, body-or-OSError, seconds)]."""
        from concurrent.futures import ThreadPoolExecutor
        targets = self.targets()

        def fetch_one(tgt):
            t0 = time.monotonic()
            try:
                return tgt, self._fetch(tgt[2], path), \
                    time.monotonic() - t0
            except OSError as ex:
                return tgt, ex, time.monotonic() - t0

        if not targets:
            return []
        with ThreadPoolExecutor(max_workers=min(len(targets), 8),
                                thread_name_prefix="fed-scrape") as pool:
            return list(pool.map(fetch_one, targets))

    def scrape_once(self) -> str:
        """One full scrape round → the merged labeled exposition text.
        (metad's own SLO gauges refresh via its /metrics handler like
        every daemon's — see webservice.py.)"""
        slo_engine().burn_rates()
        lines: List[str] = []
        seen_types: set = set()
        status: Dict[str, Dict] = {}
        for (addr, role, ws), text, dt in self._fan_out("/metrics"):
            if isinstance(text, OSError):
                stats().inc("federation_scrape_errors")
                status[addr] = {"role": role, "ws": ws, "ok": False,
                                "error": str(text)}
                continue
            n = 0
            for ln in _inject_labels(text, addr, role):
                if ln.startswith("# TYPE"):
                    if ln in seen_types:
                        continue
                    seen_types.add(ln)
                elif ln and not ln.startswith("#"):
                    n += 1
                lines.append(ln)
            status[addr] = {"role": role, "ws": ws, "ok": True,
                            "samples": n,
                            "scrape_s": round(dt, 4)}
        stats().inc("federation_scrapes")
        stats().gauge("federation_targets", float(len(status)))
        merged = "\n".join(lines) + ("\n" if lines else "")
        with self._lock:
            self._merged = merged
            self._status = status
            self._last_scrape = time.monotonic()
        return merged

    def cluster_queries(self) -> Dict[str, Dict]:
        """Live workload federation (ISSUE 9): fan /queries out over
        every alive daemon and return the per-instance in-flight
        statements + dispatch tables, instance/role attached — served
        at metad's GET /cluster_queries.  Always scraped on demand
        (live state is worthless stale), through the same fan-out as
        /cluster_metrics."""
        import json as _json
        out: Dict[str, Dict] = {}
        for (addr, role, ws), body, _dt in self._fan_out("/queries"):
            if not isinstance(body, OSError):
                try:
                    out[addr] = {"role": role, "ok": True,
                                 **_json.loads(body)}
                    continue
                except ValueError as ex:
                    body = ex
            stats().inc("federation_scrape_errors")
            out[addr] = {"role": role, "ok": False, "error": str(body)}
        return out

    def cluster_statements(self) -> Dict:
        """Workload insights federation (ISSUE 16): fan /statements out
        over every alive graphd and return both the per-instance
        fingerprint tables and ONE exactly-merged view (the fixed
        shared latency buckets make the cross-host histogram sum
        lossless) — served at metad's GET /cluster_statements."""
        import json as _json

        from ..utils.insights import merge_statement_snapshots
        hosts: Dict[str, Dict] = {}
        snaps = []
        for (addr, role, ws), body, _dt in self._fan_out("/statements"):
            if role != "graphd":
                continue
            if not isinstance(body, OSError):
                try:
                    rows = _json.loads(body)
                    hosts[addr] = {"ok": True, "statements": rows}
                    snaps.append(rows)
                    continue
                except ValueError as ex:
                    body = ex
            stats().inc("federation_scrape_errors")
            hosts[addr] = {"ok": False, "error": str(body)}
        return {"hosts": hosts,
                "merged": merge_statement_snapshots(snaps)}

    def render(self) -> str:
        """The merged view, re-scraped on demand when stale (covers the
        interval=0 / no-background-loop configuration)."""
        try:
            interval = float(get_config().get(
                "metric_federation_interval_secs"))
        except Exception:  # noqa: BLE001
            interval = 5.0
        with self._lock:
            fresh = (time.monotonic() - self._last_scrape) < \
                max(interval, 1.0) and self._merged
            if fresh:
                return self._merged
        return self.scrape_once()

    def scrape_status(self) -> Dict[str, Dict]:
        with self._lock:
            return {a: dict(s) for a, s in self._status.items()}

    # -- lifecycle --------------------------------------------------------

    def start(self):
        try:
            interval = float(get_config().get(
                "metric_federation_interval_secs"))
        except Exception:  # noqa: BLE001
            interval = 5.0
        if interval <= 0:
            return                         # on-demand only
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scrape_once()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    pass
        self._thread = threading.Thread(
            target=loop, daemon=True, name="metric-federation")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
