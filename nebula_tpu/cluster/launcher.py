"""LocalCluster — a whole cluster in one process.

The MockCluster analog (reference: src/mock/MockCluster + the pytest
launcher tests/common/nebula_service.py [UNVERIFIED — empty mount,
SURVEY §4]): real RpcServers on ephemeral localhost ports, real raft
between them, N metad × M storaged × K graphd, used by integration
tests, the console (--addr), and as the template for real deployments
(daemons.py runs the same services standalone).
"""
from __future__ import annotations

import os
import tempfile
import time
from typing import List, Optional

from .client import GraphClient
from .graph_service import GraphService
from .meta_client import MetaClient
from .meta_service import MetaService
from .rpc import RpcServer, serve_raft_parts
from .storage_service import StorageService


class LocalCluster:
    def __init__(self, n_meta: int = 1, n_storage: int = 2, n_graph: int = 1,
                 data_dir: Optional[str] = None, tpu_runtime=None):
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="nebula_tpu_")
        self.meta_servers: List[RpcServer] = []
        self.metads: List[MetaService] = []
        self.storage_servers: List[RpcServer] = []
        self.storageds: List[StorageService] = []
        self.graph_servers: List[RpcServer] = []
        self.graphds: List[GraphService] = []
        self.meta_clients: List[MetaClient] = []

        # -- metad quorum --
        servers = [RpcServer() for _ in range(n_meta)]
        meta_addrs = [s.addr for s in servers]
        for i, srv in enumerate(servers):
            ms = MetaService(srv.addr, meta_addrs,
                             os.path.join(self.data_dir, f"meta{i}"),
                             server=srv)
            serve_raft_parts(srv, {"meta": ms.raft})
            srv.start()
            ms.start()
            self.meta_servers.append(srv)
            self.metads.append(ms)
        self.meta_addrs = meta_addrs
        self._wait_meta_leader()

        # -- storaged --
        for i in range(n_storage):
            srv = RpcServer()
            mc = MetaClient(meta_addrs, my_addr=srv.addr, role="storage",
                            heartbeat_interval=0.2)
            mc.wait_ready()
            mc.refresh(force=True)
            ss = StorageService(srv.addr, mc,
                                os.path.join(self.data_dir, f"storage{i}"),
                                server=srv)
            srv.start()
            ss.start()
            mc.heartbeat_once()
            self.storage_servers.append(srv)
            self.storageds.append(ss)
            self.meta_clients.append(mc)

        # -- graphd --
        for i in range(n_graph):
            srv = RpcServer()
            mc = MetaClient(meta_addrs, my_addr=srv.addr, role="graph",
                            heartbeat_interval=0.2)
            mc.wait_ready()
            mc.refresh(force=True)
            gs = GraphService(srv.addr, mc, server=srv,
                              tpu_runtime=tpu_runtime)
            srv.start()
            gs.start()
            self.graph_servers.append(srv)
            self.graphds.append(gs)
            self.meta_clients.append(mc)

    def _wait_meta_leader(self, timeout: float = 10.0):
        dl = time.monotonic() + timeout
        while time.monotonic() < dl:
            if any(m.raft.is_leader() for m in self.metads):
                return
            time.sleep(0.02)
        raise RuntimeError("metad leader election timed out")

    @property
    def graph_addr(self) -> str:
        return self.graph_servers[0].addr

    @property
    def graph_addrs(self) -> List[str]:
        return [s.addr for s in self.graph_servers]

    def client(self, user: str = "root", password: str = "nebula",
               graphd: int = 0) -> GraphClient:
        host, port = self.graph_servers[graphd].addr.rsplit(":", 1)
        c = GraphClient(host, int(port))
        c.authenticate(user, password)
        return c

    def fleet_client(self, user: str = "root", password: str = "nebula"
                     ) -> GraphClient:
        """A failover-capable client holding EVERY graphd endpoint
        (ISSUE 20): coordinator selection + transparent E_SESSION_MOVED
        / crash failover per the GraphClient fleet contract."""
        c = GraphClient(self.graph_addrs)
        c.authenticate(user, password)
        return c

    def stop_graphd(self, i: int):
        """Hard-stop one graphd (coordinator-crash injection): raw
        connection resets for its clients, sessions adoptable by
        siblings from the metad-replicated table."""
        self.graphds[i].stop()
        self.graph_servers[i].stop()

    def drain_graphd(self, i: int, timeout_s: Optional[float] = None) -> int:
        """Graceful stop of one graphd (planned restart): refuse new
        statements with E_SESSION_MOVED + sibling hint, let in-flight
        ones finish, then stop.  Returns sessions handed off."""
        n = self.graphds[i].drain(timeout_s)
        self.stop_graphd(i)
        return n

    def add_storaged(self) -> StorageService:
        """Join a new storage host to the running cluster (the balance
        test's expansion scenario)."""
        i = len(self.storageds)
        srv = RpcServer()
        mc = MetaClient(self.meta_addrs, my_addr=srv.addr, role="storage",
                        heartbeat_interval=0.2)
        mc.wait_ready()
        mc.refresh(force=True)
        ss = StorageService(srv.addr, mc,
                            os.path.join(self.data_dir, f"storage{i}"),
                            server=srv)
        srv.start()
        ss.start()
        mc.heartbeat_once()
        self.storage_servers.append(srv)
        self.storageds.append(ss)
        self.meta_clients.append(mc)
        return ss

    def stop_storaged(self, i: int):
        """Hard-stop one storage host (crash injection for balance /
        failover tests)."""
        self.storageds[i].stop()
        self.storage_servers[i].stop()

    def stop_metad(self, i: int):
        """Hard-stop one metad (leader-kill injection for the repair /
        failover tests — the surviving quorum elects a successor)."""
        self.metads[i].stop()
        self.meta_servers[i].stop()

    def meta_leader_index(self) -> int:
        """Index of the metad currently leading the meta group (-1 when
        the group is mid-election)."""
        for i, m in enumerate(self.metads):
            if m.raft.is_leader():
                return i
        return -1

    def reconcile_storage(self):
        """Force every storaged to (re)create raft groups for its parts —
        tests call this right after CREATE SPACE instead of waiting a
        heartbeat round."""
        for mc in self.meta_clients:
            mc.refresh(force=True)
        for ss in self.storageds:
            ss.reconcile_parts()

    def stop(self):
        for gs in self.graphds:
            gs.stop()
        for ss in self.storageds:
            ss.stop()
        for ms in self.metads:
            ms.stop()
        for srv in (self.graph_servers + self.storage_servers
                    + self.meta_servers):
            srv.stop()
