"""File-based write-ahead log, one per Raft part.

Analog of the reference's FileBasedWal + AtomicLogBuffer (reference:
src/kvstore/wal [UNVERIFIED — empty mount, SURVEY §0]): an append-only
record log with (term, index, payload) entries, CRC-checked, truncatable
from the tail (log rollback after leader change) and from the head
(snapshot GC).

Group commit (ISSUE 3): `append_batch` writes a whole request's frames
with ONE buffered write, one flush, one fsync; and the fsync itself is
a *group sync* — `sync_to(index)` is a coalescing point where the
first caller's fsync covers every entry flushed before it started, so
concurrent proposers on one part share a single durability round
instead of queueing one fsync each.

Record format (little-endian):
    u32 crc32(payload_len..payload) | u32 payload_len | u64 index |
    u64 term | payload bytes
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

_HDR = struct.Struct("<IIQQ")          # crc, len, index, term


class WalError(Exception):
    pass


class Wal:
    """Append-only (term, index, data) log with in-memory index."""

    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        from ..utils.racecheck import make_lock
        self.lock = make_lock("wal")
        # serializes fsyncs (the group-sync coalescing point) and file
        # close/reopen against an in-flight fsync.  Lock order is ALWAYS
        # _sync_mu → lock; nothing takes _sync_mu while holding lock.
        self._sync_mu = make_lock("wal_sync")
        # last index known durable (covered by an fsync).  Meaningful
        # only when sync=True; async logs report last_index() as synced.
        self._synced_upto = 0
        self._entries: List[Tuple[int, int, int]] = []  # (index, term, offset)
        self._first_index = 1
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._recover()
        self._synced_upto = (self._entries[-1][0] if self._entries
                             else self._first_index - 1)
        self._f = open(self.path, "ab")
        # cached read handle: the apply/replication paths read entries
        # one at a time — an open() per read turns a 512-entry batch
        # apply into 512 file opens per node (measured ~300ms); the
        # shared handle is seek/read under `lock` and invalidated on
        # any file swap (truncate/reset/compact)
        self._rf = None

    # -- recovery ---------------------------------------------------------

    def _recover(self):
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HDR.size <= len(data):
            crc, ln, idx, term = _HDR.unpack_from(data, off)
            end = off + _HDR.size + ln
            if end > len(data):
                break
            payload = data[off + _HDR.size:end]
            calc = zlib.crc32(_HDR.pack(0, ln, idx, term)[4:] + payload)
            if calc != crc:
                break                   # torn tail write — truncate here
            self._entries.append((idx, term, off))
            good_end = end
            off = end
        if good_end < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        if self._entries:
            self._first_index = self._entries[0][0]

    # -- append / read ----------------------------------------------------

    def append(self, index: int, term: int, data: bytes):
        self.append_batch([(index, term, data)])

    def append_batch(self, entries: List[Tuple[int, int, bytes]],
                     sync: Optional[bool] = None):
        """Append contiguous (index, term, data) entries with ONE
        buffered write, one flush, and — when the log is synchronous —
        one fsync for the whole batch (the single-fsync leg of group
        commit; the reference's per-entry fsync is the cost this
        amortizes).

        sync=False defers durability: the caller later invokes
        `sync_to(last_index)` OUTSIDE its own locks so concurrent
        appenders can coalesce onto one fsync."""
        if not entries:
            return
        with self.lock:
            if self._entries:
                last = self._entries[-1][0]
            else:
                # first entry anchors the index base (e.g. the log
                # restarts at snap_index+1 after a snapshot install)
                self._first_index = entries[0][0]
                last = entries[0][0] - 1
            buf = bytearray()
            off = self._f.tell()
            new = []
            for index, term, data in entries:
                if index != last + 1:
                    raise WalError(
                        f"non-contiguous append {index} after {last}")
                last = index
                hdr_rest = _HDR.pack(0, len(data), index, term)[4:]
                crc = zlib.crc32(hdr_rest + data)
                new.append((index, term, off + len(buf)))
                buf += _HDR.pack(crc, len(data), index, term)
                buf += data
            self._f.write(buf)
            self._f.flush()
            self._entries.extend(new)
        if (self.sync if sync is None else sync) and self.sync:
            self.sync_to(last)

    def synced_index(self) -> int:
        """Last index covered by an fsync (== last_index() for async
        logs).  The raft leader only replicates entries it has made
        durable locally, preserving the pre-group-commit invariant that
        a follower never holds an entry the leader could lose."""
        if not self.sync:
            return self.last_index()
        return self._synced_upto

    def sync_to(self, index: int):
        """Make all entries up to `index` durable.  Group sync: callers
        pile up on `_sync_mu`; whoever holds it fsyncs once, covering
        every entry flushed before the fsync started, and the waiters
        find their index already covered when they get the lock."""
        if not self.sync or self._synced_upto >= index:
            return
        with self._sync_mu:
            if self._synced_upto >= index:
                return                 # a sibling's fsync covered us
            # armed `delay` = an fsync stall (held under _sync_mu, so it
            # stalls the whole group-commit sync like a slow disk does);
            # armed `raise` = a disk fault — propagates like a real
            # fsync error would
            from ..utils.failpoints import fail as _fail
            _fail.hit("wal:pre_fsync", key=self.path)
            with self.lock:
                flushed = (self._entries[-1][0] if self._entries
                           else self._first_index - 1)
                f = self._f
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):
                with self.lock:
                    swapped = f is not self._f or f.closed
                if swapped:
                    # file swapped under us (truncate/reset on
                    # step-down): the entry's fate belongs to the new
                    # leader anyway
                    return
                # genuine disk fault (EIO/ENOSPC): must PROPAGATE like
                # the old per-entry fsync did — swallowing it would
                # leave the proposer timing out against a healthy-
                # looking leader while the fault goes unreported (and
                # a later fsync could falsely mark the lost pages
                # durable)
                from ..utils.stats import stats
                stats().inc("wal_fsync_errors")
                raise
            covered = flushed - self._synced_upto
            self._synced_upto = flushed
            from ..utils.stats import current_cost, stats
            stats().inc("wal_fsync_total")
            if covered > 0:
                stats().inc("wal_fsync_batch_entries", covered)
            # cost attribution (ISSUE 8): the request whose thread ran
            # the group fsync carries it in its reply cost record
            # (coalesced siblings ride free — documented approximation)
            cc = current_cost()
            if cc is not None:
                cc.add("wal_fsyncs", 1)

    def last_index(self) -> int:
        with self.lock:
            return self._entries[-1][0] if self._entries else self._first_index - 1

    def last_term(self) -> int:
        with self.lock:
            return self._entries[-1][1] if self._entries else 0

    def first_index(self) -> int:
        return self._first_index

    def term_of(self, index: int) -> Optional[int]:
        with self.lock:
            i = index - self._first_index
            if 0 <= i < len(self._entries):
                return self._entries[i][1]
            return None

    def read(self, index: int) -> Optional[Tuple[int, bytes]]:
        """-> (term, data) or None."""
        with self.lock:
            i = index - self._first_index
            if not (0 <= i < len(self._entries)):
                return None
            _, term, off = self._entries[i]
            if self._rf is None or self._rf.closed:
                self._rf = open(self.path, "rb")
            f = self._rf
            f.seek(off)
            hdr = f.read(_HDR.size)
            _, ln, idx, t = _HDR.unpack(hdr)
            return t, f.read(ln)

    def _drop_read_handle(self):
        """Called (under lock) whenever the underlying file is swapped."""
        if self._rf is not None:
            try:
                self._rf.close()
            except OSError:
                pass
            self._rf = None

    def read_range(self, start: int, end: int) -> Iterator[Tuple[int, int, bytes]]:
        """Yield (index, term, data) for start <= index <= end."""
        for idx in range(max(start, self._first_index),
                         min(end, self.last_index()) + 1):
            r = self.read(idx)
            if r is None:
                return
            yield idx, r[0], r[1]

    # -- truncation -------------------------------------------------------

    def truncate_from(self, index: int):
        """Drop entries >= index (conflicting suffix after leader change)."""
        with self._sync_mu, self.lock:
            i = index - self._first_index
            if i < 0:
                i = 0
            if i >= len(self._entries):
                return
            off = self._entries[i][2]
            self._f.close()
            self._drop_read_handle()
            with open(self.path, "r+b") as f:
                f.truncate(off)
            self._f = open(self.path, "ab")
            del self._entries[i:]
            self._synced_upto = min(self._synced_upto, index - 1)

    def reset(self, first_index: int):
        """Clear the log and restart it at first_index (after a snapshot
        install replaces all local state)."""
        with self._sync_mu, self.lock:
            self._f.close()
            self._drop_read_handle()
            with open(self.path, "wb"):
                pass
            self._f = open(self.path, "ab")
            self._entries = []
            self._first_index = first_index
            self._synced_upto = first_index - 1

    def compact_to(self, index: int):
        """Drop entries <= index (after snapshot). Rewrites the file."""
        with self._sync_mu, self.lock:
            keep = [(i, t, o) for (i, t, o) in self._entries if i > index]
            self._f.close()
            self._drop_read_handle()
            tmp = self.path + ".compact"
            with open(tmp, "wb") as out, open(self.path, "rb") as src:
                new_entries = []
                for idx, term, off in keep:
                    src.seek(off)
                    hdr = src.read(_HDR.size)
                    _, ln, _, _ = _HDR.unpack(hdr)
                    new_off = out.tell()
                    out.write(hdr)
                    out.write(src.read(ln))
                    new_entries.append((idx, term, new_off))
            os.replace(tmp, self.path)
            self._entries = new_entries
            self._first_index = index + 1 if not new_entries else new_entries[0][0]
            self._f = open(self.path, "ab")
            # compacted entries were applied state — at least as durable
            # as the snapshot that subsumed them
            self._synced_upto = max(self._synced_upto, index)

    def close(self):
        with self._sync_mu, self.lock:
            self._f.close()
            self._drop_read_handle()
