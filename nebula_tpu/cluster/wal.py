"""File-based write-ahead log, one per Raft part.

Analog of the reference's FileBasedWal + AtomicLogBuffer (reference:
src/kvstore/wal [UNVERIFIED — empty mount, SURVEY §0]): an append-only
record log with (term, index, payload) entries, CRC-checked, truncatable
from the tail (log rollback after leader change) and from the head
(snapshot GC).

Record format (little-endian):
    u32 crc32(payload_len..payload) | u32 payload_len | u64 index |
    u64 term | payload bytes
"""
from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

_HDR = struct.Struct("<IIQQ")          # crc, len, index, term


class WalError(Exception):
    pass


class Wal:
    """Append-only (term, index, data) log with in-memory index."""

    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync = sync
        from ..utils.racecheck import make_lock
        self.lock = make_lock("wal")
        self._entries: List[Tuple[int, int, int]] = []  # (index, term, offset)
        self._first_index = 1
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._recover()
        self._f = open(self.path, "ab")

    # -- recovery ---------------------------------------------------------

    def _recover(self):
        if not os.path.exists(self.path):
            return
        good_end = 0
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off + _HDR.size <= len(data):
            crc, ln, idx, term = _HDR.unpack_from(data, off)
            end = off + _HDR.size + ln
            if end > len(data):
                break
            payload = data[off + _HDR.size:end]
            calc = zlib.crc32(_HDR.pack(0, ln, idx, term)[4:] + payload)
            if calc != crc:
                break                   # torn tail write — truncate here
            self._entries.append((idx, term, off))
            good_end = end
            off = end
        if good_end < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        if self._entries:
            self._first_index = self._entries[0][0]

    # -- append / read ----------------------------------------------------

    def append(self, index: int, term: int, data: bytes):
        with self.lock:
            if self._entries:
                last = self._entries[-1][0]
                if index != last + 1:
                    raise WalError(
                        f"non-contiguous append {index} after {last}")
            else:
                # first entry anchors the index base (e.g. the log restarts
                # at snap_index+1 after a snapshot install)
                self._first_index = index
            off = self._f.tell()
            hdr_rest = _HDR.pack(0, len(data), index, term)[4:]
            crc = zlib.crc32(hdr_rest + data)
            self._f.write(_HDR.pack(crc, len(data), index, term))
            self._f.write(data)
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            self._entries.append((index, term, off))

    def last_index(self) -> int:
        with self.lock:
            return self._entries[-1][0] if self._entries else self._first_index - 1

    def last_term(self) -> int:
        with self.lock:
            return self._entries[-1][1] if self._entries else 0

    def first_index(self) -> int:
        return self._first_index

    def term_of(self, index: int) -> Optional[int]:
        with self.lock:
            i = index - self._first_index
            if 0 <= i < len(self._entries):
                return self._entries[i][1]
            return None

    def read(self, index: int) -> Optional[Tuple[int, bytes]]:
        """-> (term, data) or None."""
        with self.lock:
            i = index - self._first_index
            if not (0 <= i < len(self._entries)):
                return None
            _, term, off = self._entries[i]
        with open(self.path, "rb") as f:
            f.seek(off)
            hdr = f.read(_HDR.size)
            _, ln, idx, t = _HDR.unpack(hdr)
            return t, f.read(ln)

    def read_range(self, start: int, end: int) -> Iterator[Tuple[int, int, bytes]]:
        """Yield (index, term, data) for start <= index <= end."""
        for idx in range(max(start, self._first_index),
                         min(end, self.last_index()) + 1):
            r = self.read(idx)
            if r is None:
                return
            yield idx, r[0], r[1]

    # -- truncation -------------------------------------------------------

    def truncate_from(self, index: int):
        """Drop entries >= index (conflicting suffix after leader change)."""
        with self.lock:
            i = index - self._first_index
            if i < 0:
                i = 0
            if i >= len(self._entries):
                return
            off = self._entries[i][2]
            self._f.close()
            with open(self.path, "r+b") as f:
                f.truncate(off)
            self._f = open(self.path, "ab")
            del self._entries[i:]

    def reset(self, first_index: int):
        """Clear the log and restart it at first_index (after a snapshot
        install replaces all local state)."""
        with self.lock:
            self._f.close()
            with open(self.path, "wb"):
                pass
            self._f = open(self.path, "ab")
            self._entries = []
            self._first_index = first_index

    def compact_to(self, index: int):
        """Drop entries <= index (after snapshot). Rewrites the file."""
        with self.lock:
            keep = [(i, t, o) for (i, t, o) in self._entries if i > index]
            self._f.close()
            tmp = self.path + ".compact"
            with open(tmp, "wb") as out, open(self.path, "rb") as src:
                new_entries = []
                for idx, term, off in keep:
                    src.seek(off)
                    hdr = src.read(_HDR.size)
                    _, ln, _, _ = _HDR.unpack(hdr)
                    new_off = out.tell()
                    out.write(hdr)
                    out.write(src.read(ln))
                    new_entries.append((idx, term, new_off))
            os.replace(tmp, self.path)
            self._entries = new_entries
            self._first_index = index + 1 if not new_entries else new_entries[0][0]
            self._f = open(self.path, "ab")

    def close(self):
        with self.lock:
            self._f.close()
