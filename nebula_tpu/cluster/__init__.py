"""Cluster plane: meta/storage/graph services, RPC, Raft consensus.

The distributed deployment form of the framework (single-process mode in
nebula_tpu.exec stays first-class for tests). Maps to the reference's
metad/storaged/graphd split with fbthrift RPC and raftex consensus
(reference: src/meta, src/storage, src/graph, src/kvstore/raftex
[UNVERIFIED — empty mount, SURVEY §0]); here the control plane is a
JSON-over-TCP RPC and the data plane is either host fan-out (CPU path)
or the TPU mesh (tpu/ package) — per SURVEY §5's two-plane rule.
"""
