"""JSON-over-TCP RPC — the wire layer of the control plane.

Replaces the reference's fbthrift services (graph.thrift / meta.thrift /
storage.thrift / raftex.thrift; reference: src/interface +
src/common/thrift [UNVERIFIED — empty mount, SURVEY §0]) with a
dependency-free length-prefixed JSON protocol.  The OPERATION SET of
those IDLs is preserved by the services built on top (SURVEY §2 row 6);
only the encoding differs.  Data-plane traffic (frontier exchange) never
rides this — it's XLA collectives (SURVEY §5, two-plane rule).

Frame: u32 length | utf-8 JSON {"method": str, "params": {...}}
Reply: u32 length | utf-8 JSON {"ok": bool, "result"|"error": ...}

Values use the JSON-safe encoding of core.value (value_to_json /
value_from_json) at the service layer.

Observability (ISSUE 1): when the calling thread has an active trace,
the request frame carries `"trace": [trace_id, parent_span_id]`; the
server adopts it around the handler, and the spans produced while
handling come back in the reply's `"spans"` list, which the client
grafts into its trace — the coordinator ends up holding one stitched
tree across processes.  Every call also feeds the per-op latency
histograms (`rpc_client_latency_us` / `rpc_server_latency_us`,
labeled by op) and — when a WorkCounters target is installed via
utils.stats.use_work — the deterministic call/byte work counters.
"""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils import trace as _trace
from ..utils.stats import current_work, stats as _stats

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


class RpcError(Exception):
    """Remote raised an application error."""


class RpcConnError(Exception):
    """Transport failure (connect/timeout/framing)."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise RpcConnError("connection closed")
        buf += chunk
    return bytes(buf)


def _send_frame(sock: socket.socket, obj: Any) -> int:
    """One frame: 4-byte length + payload.  Returns bytes written
    (wire-byte work counters).

    Payload is plain JSON, or — when the object carries raw byte
    buffers (columnar result columns, SURVEY §2 row 25) — the binary
    form: NUL + u32 blob-count + u32 blob-lengths + u32 json-length +
    json (buffers replaced by {"@t":"blobref","bi":i}) + blob bytes.
    JSON text can never start with NUL, so receivers distinguish the
    two without version negotiation."""
    blobs: list = []

    def default(o):
        if isinstance(o, (bytes, bytearray, memoryview)):
            blobs.append(o if isinstance(o, bytes) else bytes(o))
            return {"@t": "blobref", "bi": len(blobs) - 1}
        raise TypeError(f"not JSON-serializable: {type(o).__name__}")

    data = json.dumps(obj, separators=(",", ":"), default=default).encode()
    if not blobs:
        sock.sendall(_LEN.pack(len(data)) + data)
        return _LEN.size + len(data)
    header = b"\x00" + _LEN.pack(len(blobs)) + b"".join(
        _LEN.pack(len(b)) for b in blobs) + _LEN.pack(len(data))
    total = len(header) + len(data) + sum(len(b) for b in blobs)
    # piecewise sendall: no 100MB+ join copy for big columnar results
    sock.sendall(_LEN.pack(total) + header + data)
    for b in blobs:
        sock.sendall(b)
    return _LEN.size + total


def _graft_blobs(j: Any, blobs: list) -> Any:
    """Replace {"@t":"blobref","bi":i} placeholders with the out-of-band
    buffers.  In blob mode the JSON part is small (bulk data IS the
    blobs), so the walk is cheap."""
    if isinstance(j, dict):
        if j.get("@t") == "blobref":
            return blobs[j["bi"]]
        return {k: _graft_blobs(v, blobs) for k, v in j.items()}
    if isinstance(j, list):
        return [_graft_blobs(x, blobs) for x in j]
    return j


def _recv_frame(sock: socket.socket) -> Tuple[Any, int]:
    """-> (decoded frame, bytes read)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise RpcConnError(f"frame too large: {n}")
    nbytes = _LEN.size + n
    payload = _recv_exact(sock, n)
    if not payload or payload[0] != 0:
        return json.loads(payload), nbytes
    mv = memoryview(payload)
    off = 1
    (nb,) = _LEN.unpack(mv[off:off + 4]); off += 4
    lens = []
    for _ in range(nb):
        (ln,) = _LEN.unpack(mv[off:off + 4]); off += 4
        lens.append(ln)
    (jn,) = _LEN.unpack(mv[off:off + 4]); off += 4
    j = json.loads(bytes(mv[off:off + jn])); off += jn
    blobs = []
    for ln in lens:
        blobs.append(mv[off:off + ln]); off += ln   # zero-copy views
    return _graft_blobs(j, blobs), nbytes


class RpcServer:
    """Threaded TCP server dispatching to registered handlers.

    handler(params: dict) -> jsonable result; raising RpcError (or any
    exception) returns an error reply instead of killing the connection.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self.hooks: list = []           # fault-injection: fn(method) -> None|Exception
        # which daemon this server fronts ("graphd"/"storaged"/"metad");
        # stamped on the spans its handlers produce
        self.service_role = "unknown"
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.settimeout(300)
                try:
                    while True:
                        req, _ = _recv_frame(sock)
                        _send_frame(sock, outer._dispatch(req))
                except (RpcConnError, socket.timeout, OSError,
                        json.JSONDecodeError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def register(self, method: str, fn: Callable[[Dict[str, Any]], Any]):
        self.handlers[method] = fn

    def register_service(self, obj: Any, prefix: str = ""):
        """Every public method rpc_* of obj becomes `prefix+name`."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    def _dispatch(self, req: Any) -> Dict[str, Any]:
        method = req.get("method") if isinstance(req, dict) else None
        if not method:
            return {"ok": False, "error": "malformed request frame"}
        params = req.get("params", {})
        wire_trace = req.get("trace")
        spans = None
        t0 = time.perf_counter()
        try:
            for hook in self.hooks:
                hook(method)
            fn = self.handlers.get(method)
            if fn is None:
                return {"ok": False, "error": f"unknown method `{method}'"}
            if wire_trace:
                # adopt the caller's trace: handler spans go to a fresh
                # sink shipped back in the reply (the coordinator owns
                # the trace; nothing is stored on this side)
                with _trace.adopt_remote(wire_trace[0], wire_trace[1],
                                         self.service_role) as rg:
                    spans = rg.spans
                    with _trace.span(f"rpc.server:{method}"):
                        result = fn(params)
                return {"ok": True, "result": result, "spans": spans}
            return {"ok": True, "result": fn(params)}
        except RpcError as ex:
            reply = {"ok": False, "error": str(ex)}
            if spans:
                # the error-path spans (incl. the rpc.server span with
                # its error attr) are precisely what a failing query's
                # trace needs — ship them like the success path does
                reply["spans"] = spans
            return reply
        except Exception as ex:  # noqa: BLE001 — server must not die
            reply = {"ok": False, "error": f"{type(ex).__name__}: {ex}"}
            if spans:
                reply["spans"] = spans
            return reply
        finally:
            # observe error-path latencies too: a histogram that only
            # sees successes understates the tail it exists to expose.
            # REGISTERED methods only — labeling by a client-supplied
            # unknown name would let garbage frames grow one permanent
            # histogram row per bogus method (unbounded cardinality)
            if method in self.handlers:
                _stats().observe("rpc_server_latency_us",
                                 (time.perf_counter() - t0) * 1e6,
                                 {"op": method,
                                  "role": self.service_role})

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"rpc-{self.port}")
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """One connection, auto-reconnect, thread-safe (serialized calls)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 2):
        self.host, self.port = host, port
        self.timeout = timeout
        self.retries = retries
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "RpcClient":
        host, port = addr.rsplit(":", 1)
        return cls(host, int(port), **kw)

    def _connect(self):
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def call(self, method: str, **params) -> Any:
        last_err: Optional[Exception] = None
        with _trace.span(f"rpc:{method}", peer=f"{self.host}:{self.port}"):
            for attempt in range(self.retries + 1):
                try:
                    # per-attempt timer: a success after a reconnect
                    # must not record the dead attempt + backoff sleep
                    # as op latency (the rpc:<method> span still covers
                    # the whole call, retries included)
                    t_call = time.perf_counter()
                    req = {"method": method, "params": params}
                    tctx = _trace.wire_context()
                    if tctx is not None:
                        req["trace"] = list(tctx)
                    with self._lock:
                        if self._sock is None:
                            self._connect()
                        sent = _send_frame(self._sock, req)
                        reply, recvd = _recv_frame(self._sock)
                    us = (time.perf_counter() - t_call) * 1e6
                    _stats().observe("rpc_client_latency_us", us,
                                     {"op": method})
                    wc = current_work()
                    if wc is not None:
                        wc.add_rpc(sent, recvd)
                    # remote spans come back on error replies too — a
                    # failing branch's storaged subtree must still land
                    # in the coordinator's trace
                    _trace.graft(reply.get("spans") or [])
                    if reply.get("ok"):
                        return reply.get("result")
                    _stats().inc_labeled("rpc_client_errors",
                                         {"op": method})
                    raise RpcError(reply.get("error", "unknown error"))
                except RpcError:
                    raise
                except (OSError, RpcConnError,
                        json.JSONDecodeError) as ex:
                    last_err = ex
                    with self._lock:
                        if self._sock is not None:
                            try:
                                self._sock.close()
                            except OSError:
                                pass
                            self._sock = None
                    if attempt < self.retries:
                        time.sleep(0.05 * (attempt + 1))
        raise RpcConnError(f"rpc to {self.host}:{self.port} failed: {last_err}")

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class RpcRaftTransport:
    """RaftTransport over RpcClient connections — raftex.thrift's role.

    peer ids ARE addresses ("host:port"); raft messages dispatch to the
    `raft` method of the peer's RpcServer, which routes to the right
    RaftPart by group.
    """

    def __init__(self):
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()

    def client(self, peer: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(peer)
            if c is None:
                c = self._clients[peer] = RpcClient.from_addr(
                    peer, timeout=2.0, retries=0)
            return c

    def send(self, peer, group, method, payload):
        try:
            return self.client(peer).call(
                "raft", group=group, rmethod=method, payload=payload)
        except (RpcError, RpcConnError):
            return None


def serve_raft_parts(server: RpcServer, parts: Dict[str, Any]):
    """Register the `raft` dispatch method for a dict group → RaftPart."""
    def handler(params):
        part = parts.get(params["group"])
        if part is None:
            raise RpcError(f"no raft group `{params['group']}' here")
        return part.handle(params["rmethod"], params["payload"])
    server.register("raft", handler)
