"""JSON-over-TCP RPC — the wire layer of the control plane.

Replaces the reference's fbthrift services (graph.thrift / meta.thrift /
storage.thrift / raftex.thrift; reference: src/interface +
src/common/thrift [UNVERIFIED — empty mount, SURVEY §0]) with a
dependency-free length-prefixed JSON protocol.  The OPERATION SET of
those IDLs is preserved by the services built on top (SURVEY §2 row 6);
only the encoding differs.  Data-plane traffic (frontier exchange) never
rides this — it's XLA collectives (SURVEY §5, two-plane rule).

Frame grammar (ISSUE 2: pipelined hot path):
  u32 length | body
  body = JSON                                  (plain request/reply)
       | 0x00 blob-layout                      (columnar payload)
       | 0x01 u32 rid (JSON | 0x00 blob-layout)  (pipelined)
  blob-layout = u32 nblobs | u32 lens[nblobs] | u32 jsonlen | json
              | blob bytes...
JSON text can never start with 0x00/0x01, so receivers distinguish the
three without version negotiation.  The request id is fixed-width and
OUTSIDE the JSON so wire-byte work counters stay deterministic across
runs (ids monotonically grow; their digit count must not leak into the
counted bytes).

Concurrency model (ISSUE 2 tentpole): `RpcClient` is a small per-peer
POOL of connections, each multiplexing concurrent in-flight requests by
request id with one reader thread; the server dispatches pipelined
requests to a per-connection worker pool and writes replies as they
finish (out-of-order).  Concurrent calls to the same peer genuinely
overlap instead of serializing on one socket.

Retry safety: automatic re-send after a connection died mid-call is
gated on a per-method idempotency registry (`is_idempotent`) — reads
and raft messages retry, writes surface `RpcConnError` to the caller
(at-least-once double-apply hazard; the caller owns the decision).
`RpcNeverSentError` marks failures that provably never reached the
wire (connect refused, connection dead at entry) so higher-level
retry loops (StorageClient's replica walk) can keep retrying those
for ANY method without risking a double apply.

MAX_FRAME is enforced SYMMETRICALLY: oversized frames are rejected on
the send path with a clear `FrameTooLarge` before any byte hits the
socket, and the receive path sanity-checks the blob header (count /
lengths must tile the frame exactly) instead of feeding garbage offsets
downstream.

Observability: spans ride the envelope as before (`"trace"` in the
request JSON, `"spans"` in the reply); per-op latency histograms
(`rpc_client_latency_us` / `rpc_server_latency_us`), labeled error
counters, deterministic call/byte work counters, and the pool gauges
`rpc_pool_size` (open client connections, process-wide) and
`rpc_inflight` (requests currently awaiting a reply).
"""
from __future__ import annotations

import itertools
import json
import random
import socket
import socketserver
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils import cancel as _cancel
from ..utils import trace as _trace
from ..utils.config import define_flag, get_config
from ..utils.failpoints import ConnectionKilled, FailpointError, fail
from ..utils.stats import (CostRecorder, current_cost, current_work,
                           stats as _stats, use_cost)

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30

define_flag("rpc_pool_size", 2,
            "connections per peer in the pipelined client pool (each "
            "multiplexes concurrent requests; >1 adds parallel byte "
            "streams for large concurrent results)")
define_flag("rpc_server_workers", 8,
            "per-connection worker threads serving pipelined requests")
define_flag("breaker_failure_threshold", 5,
            "consecutive connection failures to one peer before its "
            "circuit breaker opens (calls then fail fast instead of "
            "re-timing-out against a dead host)")
define_flag("breaker_reset_secs", 2.0,
            "how long an open breaker waits before letting ONE "
            "half-open probe through")

# rpc_server_inbox_capacity is defined in utils/admission.py with the
# rest of the overload-survival flags
from ..utils.admission import (DrainEstimator, is_overload,  # noqa: E402
                               overload_error, parse_retry_after)

#: methods the bounded server inbox may NEVER shed: raft keeps the
#: cluster consistent, meta.* keeps it discoverable, and graph.* rides
#: the engine's AdmissionController instead — graph.execute carries
#: control statements (SHOW/KILL — the operator's way back into a
#: saturated cluster) that only the engine's priority lane can tell
#: apart from data statements; the inbox shedding them blind would
#: defeat the point of shedding everything else.  The inbox is the
#: STORAGED-shaped gate (uniform read/write RPCs, all sheddable).
_INBOX_EXEMPT_METHODS = frozenset({"raft"})
_INBOX_EXEMPT_PREFIXES = ("meta.", "graph.")


def _inbox_exempt(method) -> bool:
    return not isinstance(method, str) or \
        method in _INBOX_EXEMPT_METHODS or \
        method.startswith(_INBOX_EXEMPT_PREFIXES)


class RpcError(Exception):
    """Remote raised an application error."""


class RpcConnError(Exception):
    """Transport failure (connect/timeout/framing)."""


class FrameTooLarge(RpcConnError):
    """Send-path MAX_FRAME violation — raised before any byte is sent,
    so the connection stays usable."""


class RpcTimeoutError(RpcConnError):
    """Per-request timeout on a demonstrably-ALIVE connection (frames
    arrived recently; only this request is slow).  No transport verdict
    on the peer — the circuit breaker must not count it, or a slow-but-
    healthy follower gets cut out of quorum by its own fsync stalls."""


def _nbytes(b) -> int:
    return b.nbytes if isinstance(b, memoryview) else len(b)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly n bytes into ONE preallocated buffer (recv_into —
    no per-chunk bytes objects, no quadratic joins on 100MB results)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise RpcConnError("connection closed")
        got += r
    return buf


def _encode_body(obj: Any) -> Tuple[bytes, list]:
    """-> (header+json bytes, blobs).  Raw buffers (columnar result
    columns, SURVEY §2 row 25) are hoisted out of the JSON as blob
    references and shipped out-of-band, WITHOUT copying — memoryviews
    (numpy column buffers) ride to sendall as-is."""
    blobs: list = []

    def default(o):
        if isinstance(o, (bytes, bytearray, memoryview)):
            if isinstance(o, memoryview) and o.format != "B":
                o = o.cast("B")
            blobs.append(o)
            return {"@t": "blobref", "bi": len(blobs) - 1}
        raise TypeError(f"not JSON-serializable: {type(o).__name__}")

    data = json.dumps(obj, separators=(",", ":"), default=default).encode()
    if not blobs:
        return data, blobs
    header = b"\x00" + _LEN.pack(len(blobs)) + b"".join(
        _LEN.pack(_nbytes(b)) for b in blobs) + _LEN.pack(len(data))
    return header + data, blobs


def _send_frame(sock: socket.socket, obj: Any,
                rid: Optional[int] = None) -> int:
    """One frame: 4-byte length + body (+ fixed-width request id when
    pipelined).  Returns bytes written (wire-byte work counters).
    Callers sharing a socket must hold its send lock across the WHOLE
    call — the blob loop is several sendall()s."""
    head, blobs = _encode_body(obj)
    prefix = b"" if rid is None else b"\x01" + _LEN.pack(rid)
    total = len(prefix) + len(head) + sum(_nbytes(b) for b in blobs)
    if total > MAX_FRAME:
        raise FrameTooLarge(
            f"frame too large to send: {total} > MAX_FRAME={MAX_FRAME} "
            f"(split the result or raise MAX_FRAME)")
    # piecewise sendall: no 100MB+ join copy for big columnar results
    sock.sendall(_LEN.pack(total) + prefix + head)
    for b in blobs:
        sock.sendall(b)
    return _LEN.size + total


def _graft_blobs(j: Any, blobs: list) -> Any:
    """Replace {"@t":"blobref","bi":i} placeholders with the out-of-band
    buffers.  In blob mode the JSON part is small (bulk data IS the
    blobs), so the walk is cheap."""
    if isinstance(j, dict):
        if j.get("@t") == "blobref":
            return blobs[j["bi"]]
        return {k: _graft_blobs(v, blobs) for k, v in j.items()}
    if isinstance(j, list):
        return [_graft_blobs(x, blobs) for x in j]
    return j


def _decode_body(mv: memoryview) -> Any:
    if not mv or mv[0] != 0:
        return json.loads(bytes(mv))
    n = len(mv)
    off = 1
    if n < off + 4:
        raise RpcConnError("malformed blob frame: truncated header")
    (nb,) = _LEN.unpack(mv[off:off + 4]); off += 4
    # blob-count sanity BEFORE trusting it as a loop bound: the header
    # (counts + lengths) must fit inside the frame
    if nb < 0 or off + 4 * (nb + 1) > n:
        raise RpcConnError(f"malformed blob frame: {nb} blobs cannot "
                           f"fit a {n}-byte frame")
    lens = []
    for _ in range(nb):
        (ln,) = _LEN.unpack(mv[off:off + 4]); off += 4
        lens.append(ln)
    (jn,) = _LEN.unpack(mv[off:off + 4]); off += 4
    if off + jn + sum(lens) != n:
        raise RpcConnError(
            f"malformed blob frame: declared sizes (json={jn}, "
            f"blobs={sum(lens)}) do not tile the {n}-byte frame")
    j = json.loads(bytes(mv[off:off + jn])); off += jn
    blobs = []
    for ln in lens:
        blobs.append(mv[off:off + ln]); off += ln   # zero-copy views
    return _graft_blobs(j, blobs)


def _recv_frame(sock: socket.socket
                ) -> Tuple[Any, int, Optional[int]]:
    """-> (decoded frame, bytes read, request id | None)."""
    (n,) = _LEN.unpack(bytes(_recv_exact(sock, _LEN.size)))
    if n > MAX_FRAME:
        raise RpcConnError(f"frame too large: {n}")
    nbytes = _LEN.size + n
    payload = _recv_exact(sock, n)
    mv = memoryview(payload)
    rid = None
    if mv and mv[0] == 1:
        if n < 5:
            raise RpcConnError("malformed pipelined frame: no id")
        (rid,) = _LEN.unpack(mv[1:5])
        mv = mv[5:]
    return _decode_body(mv), nbytes, rid


# -- idempotency registry (satellite: retry-unsafe writes) ------------------

# Exact method names + prefixes whose handlers are safe to re-deliver:
# pure reads, overwrite-idempotent state pushes (heartbeat), and raft
# messages (the protocol itself dedups by term/index).  Everything else
# — writes, DDL, session/id allocation — must NOT be silently re-sent
# after a connection died mid-reply: the first send may have applied.
_IDEMPOTENT_METHODS = {
    "raft", "meta.ready", "meta.heartbeat", "meta.part_map",
    "storage.reconcile",
}
_IDEMPOTENT_PREFIXES = (
    "storage.get_", "storage.scan_", "storage.index_scan",
    "storage.fulltext_search", "storage.part_", "storage.export_",
    "storage.rebuild_",   # rebuilding an index twice = rebuilding once
    "meta.get_", "meta.list_", "graph.list_",
)


def mark_idempotent(*methods: str):
    """Register additional retry-safe methods (services owning custom
    read ops call this at registration time)."""
    _IDEMPOTENT_METHODS.update(methods)


def is_idempotent(method: str) -> bool:
    return method in _IDEMPOTENT_METHODS or \
        method.startswith(_IDEMPOTENT_PREFIXES)


# -- retry backoff + per-peer circuit breakers (ISSUE 5) --------------------


def retry_backoff(attempt: int, base: float = 0.05, cap: float = 2.0,
                  rng=random) -> float:
    """Equal-jitter exponential backoff: d/2 + uniform(0, d/2) for
    d = min(cap, base·2^attempt).  The random half de-synchronizes the
    retry herd a leader crash creates; the deterministic half
    guarantees real wait time per attempt (full jitter can draw ~0
    repeatedly and burn every retry before an election settles).
    Callers clamp the sleep to their remaining deadline budget."""
    d = min(cap, base * (2.0 ** attempt))
    return d / 2.0 + rng.uniform(0.0, d / 2.0)


def deadline_sleep(delay: float):
    """Sleep `delay`, clamped so a budgeted caller never sleeps past
    its deadline; a KILL QUERY fired mid-sleep wakes it immediately
    (the caller's loop-top `_cancel.check()` turns it into QueryKilled
    instead of waiting out the full jittered backoff)."""
    rem = _cancel.remaining()
    if rem is not None:
        delay = min(delay, max(rem, 0.0))
    if delay <= 0:
        return
    ev = _cancel.current_kill()
    if ev is not None:
        ev.wait(delay)
    else:
        time.sleep(delay)


class CircuitBreaker:
    """Per-peer connection-failure breaker: closed → (K consecutive
    failures) → open → (reset_secs) → half-open, where ONE probe is
    admitted; probe success closes, failure re-opens.  Only transport
    failures count — an application error proves the peer alive."""

    def __init__(self, peer: str):
        self.peer = peer
        self.lock = threading.Lock()
        self.failures = 0
        self.state = "closed"
        self.opened_at = 0.0
        self._probing = False

    def allow(self) -> bool:
        with self.lock:
            if self.state == "closed":
                return True
            try:
                reset = float(get_config().get("breaker_reset_secs"))
            except Exception:  # noqa: BLE001 — config not initialized
                reset = 2.0
            if time.monotonic() - self.opened_at < reset:
                _stats().inc("rpc_breaker_short_circuits")
                return False
            if self._probing:
                _stats().inc("rpc_breaker_short_circuits")
                return False
            # half-open: admit exactly one probe
            self.state = "half_open"
            self._probing = True
            _stats().inc("rpc_breaker_probes")
        # trace coverage (ISSUE 8 satellite): breaker state changes land
        # in the statement's trace tree with the peer labeled
        _trace.record_phase("rpc:breaker", 0.0, peer=self.peer,
                            to="half_open")
        return True

    def record_success(self):
        with self.lock:
            reopened = self.state != "closed"
            if reopened:
                _stats().inc_labeled("rpc_breaker_transitions",
                                     {"to": "closed"})
            self.state = "closed"
            self.failures = 0
            self._probing = False
        if reopened:
            _trace.record_phase("rpc:breaker", 0.0, peer=self.peer,
                                to="closed")

    def release_probe(self):
        """Relinquish a half-open probe slot without a verdict: the
        admitted call exited via a non-transport path (killed/timed-out
        statement, oversized frame) and proved nothing about the peer.
        The breaker stays half-open, so the NEXT caller is admitted as
        a fresh probe — without this, an abandoned probe would leave
        `_probing` latched and short-circuit the peer forever."""
        with self.lock:
            if self.state == "half_open":
                self._probing = False

    def record_failure(self):
        tripped = False
        with self.lock:
            self.failures += 1
            self._probing = False
            try:
                k = int(get_config().get("breaker_failure_threshold"))
            except Exception:  # noqa: BLE001
                k = 5
            if self.state == "half_open" or \
                    (self.state == "closed" and self.failures >= k):
                if self.state != "open":
                    _stats().inc("rpc_breaker_trips")
                    _stats().inc_labeled("rpc_breaker_transitions",
                                         {"to": "open"})
                    tripped = True
                self.state = "open"
                self.opened_at = time.monotonic()
        if tripped:
            _trace.record_phase("rpc:breaker", 0.0, peer=self.peer,
                                to="open")


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(peer: str) -> CircuitBreaker:
    with _breakers_lock:
        br = _breakers.get(peer)
        if br is None:
            br = _breakers[peer] = CircuitBreaker(peer)
        return br


def reset_breakers():
    """Drop all breaker state (test isolation)."""
    with _breakers_lock:
        _breakers.clear()


# -- pool gauges ------------------------------------------------------------

_gauge_lock = threading.Lock()
_open_conns = 0
_inflight = 0


def _gauge_delta(conns: int = 0, inflight: int = 0):
    global _open_conns, _inflight
    with _gauge_lock:
        _open_conns += conns
        _inflight += inflight
        c, i = _open_conns, _inflight
    st = _stats()
    if conns:
        st.gauge("rpc_pool_size", c)
    if inflight:
        st.gauge("rpc_inflight", i)


class RpcServer:
    """Threaded TCP server dispatching to registered handlers.

    handler(params: dict) -> jsonable result; raising RpcError (or any
    exception) returns an error reply instead of killing the connection.

    Pipelined requests (frames carrying a request id) dispatch to a
    small per-connection worker pool and reply OUT OF ORDER as handlers
    finish — a slow fanout partition no longer blocks its siblings on
    the same socket.  Id-less frames keep the old serial semantics.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self.hooks: list = []           # fault-injection: fn(method) -> None|Exception
        # which daemon this server fronts ("graphd"/"storaged"/"metad");
        # stamped on the spans its handlers produce
        self.service_role = "unknown"
        # bounded dispatch inbox (ISSUE 10): pipelined requests in
        # flight across ALL this server's connections; beyond
        # rpc_server_inbox_capacity new ones are rejected with
        # E_OVERLOAD + a drain-rate-derived retry-after instead of
        # queuing unboundedly on the worker pools
        self._inbox = 0
        self._inbox_mu = threading.Lock()
        self._inbox_drain = DrainEstimator()
        # a stopped server must stop SERVING, not just accepting:
        # shutdown() only ends the accept loop, while established
        # (pooled-client) connections would keep answering from their
        # handler threads — a "killed" daemon zombie-serving stale
        # state (ISSUE 14: a dead metad kept reporting liveness, a
        # dead storaged kept claiming part leadership)
        self._stopped = threading.Event()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.settimeout(300)
                wlock = threading.Lock()
                pool: Optional[ThreadPoolExecutor] = None
                try:
                    while True:
                        req, _, rid = _recv_frame(sock)
                        if outer._stopped.is_set():
                            break       # drop the connection, no reply
                        if rid is None:
                            outer._serve_one(sock, wlock, None, req)
                            continue
                        shed = outer._inbox_enter(req)
                        if shed is not None:
                            try:
                                with wlock:
                                    _send_frame(sock, shed, rid)
                            except (OSError, RpcConnError):
                                pass
                            continue
                        if pool is None:
                            try:
                                workers = int(get_config().get(
                                    "rpc_server_workers"))
                            except Exception:  # noqa: BLE001
                                workers = 8
                            pool = ThreadPoolExecutor(
                                max_workers=max(1, workers),
                                thread_name_prefix="rpc-srv")
                        pool.submit(outer._serve_pooled, sock, wlock,
                                    rid, req)
                except (RpcConnError, socket.timeout, OSError,
                        json.JSONDecodeError, ValueError):
                    pass
                finally:
                    if pool is not None:
                        pool.shutdown(wait=False)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _inbox_enter(self, req) -> Optional[Dict[str, Any]]:
        """Admit a pipelined request into the dispatch inbox, or return
        the E_OVERLOAD reply to send instead.  Exempt methods (raft,
        meta.*, graph control ops) always enter; the `rpc:server_inbox`
        failpoint force-sheds a request (raise) or stalls the check
        (delay) for tests."""
        try:
            cap = int(get_config().get("rpc_server_inbox_capacity"))
        except Exception:  # noqa: BLE001 — config not initialized
            cap = 0
        method = req.get("method") if isinstance(req, dict) else None
        if cap <= 0 or _inbox_exempt(method):
            with self._inbox_mu:
                self._inbox += 1
            return None
        forced = False
        try:
            fail.hit("rpc:server_inbox", key=method)
        except FailpointError:
            forced = True
        with self._inbox_mu:
            depth = self._inbox
            if not forced and depth < cap:
                self._inbox += 1
                return None
        retry = self._inbox_drain.retry_after_s(max(depth - cap, 0) + 1)
        _stats().inc_labeled("overload_server_rejections",
                             {"op": str(method), "role": self.service_role})
        return {"ok": False, "error": overload_error(
            retry, f"{self.service_role}:rpc_inbox",
            f"server inbox full (inflight={depth}, capacity={cap})")}

    def _serve_pooled(self, sock, wlock, rid, req):
        try:
            self._serve_one(sock, wlock, rid, req)
        finally:
            with self._inbox_mu:
                self._inbox = max(self._inbox - 1, 0)
            method = req.get("method") if isinstance(req, dict) else None
            if not _inbox_exempt(method):
                # the retry-after hint prices how fast SHEDDABLE work
                # drains — exempt traffic (raft, heartbeats) is often
                # fast and frequent and would inflate the rate,
                # teaching shed clients to retry far too early
                self._inbox_drain.note_done()

    def _serve_one(self, sock, wlock, rid, req):
        reply = self._dispatch(req)
        try:
            # the ack-lost window: the handler HAS run (possibly a
            # committed write) but the reply never reaches the client —
            # the hazard exactly-once dedup exists for.  The key carries
            # method + reply disposition ("storage.write|ok" vs "|err")
            # so schedules can target exactly the acked-write replies
            # (killing an error reply injects a different, weaker fault)
            method = req.get("method") if isinstance(req, dict) else None
            ok = reply.get("ok") if isinstance(reply, dict) else None
            fail.hit("rpc:server_reply",
                     key=f"{method}|{'ok' if ok else 'err'}")
        except FailpointError:
            try:
                # shutdown(), not close(): the connection's read-loop
                # thread is blocked in recv() on this socket, and its
                # in-flight syscall keeps the kernel socket alive past
                # close() — no FIN would go out until that recv returns.
                # shutdown() tears the connection down immediately, so
                # the client sees the mid-call death NOW.
                sock.shutdown(socket.SHUT_RDWR)
                sock.close()
            except OSError:
                pass
            return
        try:
            try:
                with wlock:
                    _send_frame(sock, reply, rid)
            except FrameTooLarge as ex:
                # symmetric MAX_FRAME: the peer gets a diagnosable
                # application error, not an opaque disconnect
                with wlock:
                    _send_frame(sock, {"ok": False, "error": str(ex)},
                                rid)
        except (OSError, RpcConnError):
            pass                      # peer went away; nothing to tell it

    def register(self, method: str, fn: Callable[[Dict[str, Any]], Any]):
        self.handlers[method] = fn

    def register_service(self, obj: Any, prefix: str = ""):
        """Every public method rpc_* of obj becomes `prefix+name`."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    def _dispatch(self, req: Any) -> Dict[str, Any]:
        method = req.get("method") if isinstance(req, dict) else None
        if not method:
            return {"ok": False, "error": "malformed request frame"}
        params = req.get("params", {})
        wire_trace = req.get("trace")
        spans = None
        # cost attribution (ISSUE 8 tentpole): when the caller flagged
        # the request ("c"), the handler runs under a fresh CostRecorder
        # — the service layers (storage reads, WAL fsyncs, dedup hits,
        # nested RPCs) fold their per-hop costs into it, and the record
        # rides back in the reply envelope for per-plan-node
        # attribution on the coordinator.  The handler time is shipped
        # as a FIXED-WIDTH decimal so reply byte counts stay
        # deterministic for the wire-byte regression probes.
        crec = CostRecorder() if req.get("c") else None

        def _cost_of(reply: Dict[str, Any]) -> Dict[str, Any]:
            if crec is not None:
                # timing fields merged from NESTED replies (plain ints,
                # e.g. remote_us of a TOSS in-half hop) must not ship
                # upward: their digit count varies run-to-run, which
                # would break the wire-byte determinism the fixed-width
                # `us` exists for — and this handler's wall time below
                # already covers nested handler time (the nested call
                # ran inside it)
                c = {k: v for k, v in crec.as_dict().items()
                     if not k.endswith("_us")}
                c["us"] = f"{min(int((time.perf_counter() - t0) * 1e6), 10 ** 9 - 1):09d}"
                reply["cost"] = c
            return reply

        t0 = time.perf_counter()
        try:
            fail.hit("rpc:server_dispatch", key=method)
            for hook in self.hooks:
                hook(method)
            fn = self.handlers.get(method)
            if fn is None:
                return {"ok": False, "error": f"unknown method `{method}'"}
            dl = req.get("dl")
            if dl is not None:
                # deadline budget rides the envelope as REMAINING
                # seconds (fixed-width decimal string — see the client
                # side); re-anchor on this hop's clock so nested RPCs
                # issued by the handler inherit a decremented budget
                dl = float(dl)
                if dl <= 0:
                    return {"ok": False,
                            "error": "E_QUERY_TIMEOUT: deadline "
                                     "exhausted before dispatch"}
                inner, dl_abs = fn, time.monotonic() + float(dl)

                def fn(p, _inner=inner, _dl=dl_abs):
                    with _cancel.use_cancel(deadline=_dl):
                        return _inner(p)
            if wire_trace:
                # adopt the caller's trace: handler spans go to a fresh
                # sink shipped back in the reply (the coordinator owns
                # the trace; nothing is stored on this side)
                with _trace.adopt_remote(wire_trace[0], wire_trace[1],
                                         self.service_role) as rg:
                    spans = rg.spans
                    with _trace.span(f"rpc.server:{method}"), \
                            use_cost(crec):
                        result = fn(params)
                return _cost_of({"ok": True, "result": result,
                                 "spans": spans})
            with use_cost(crec):
                result = fn(params)
            return _cost_of({"ok": True, "result": result})
        except RpcError as ex:
            reply = {"ok": False, "error": str(ex)}
            if spans:
                # the error-path spans (incl. the rpc.server span with
                # its error attr) are precisely what a failing query's
                # trace needs — ship them like the success path does
                reply["spans"] = spans
            return _cost_of(reply)
        except Exception as ex:  # noqa: BLE001 — server must not die
            reply = {"ok": False, "error": f"{type(ex).__name__}: {ex}"}
            if spans:
                reply["spans"] = spans
            return _cost_of(reply)
        finally:
            # observe error-path latencies too: a histogram that only
            # sees successes understates the tail it exists to expose.
            # REGISTERED methods only — labeling by a client-supplied
            # unknown name would let garbage frames grow one permanent
            # histogram row per bogus method (unbounded cardinality)
            if method in self.handlers:
                _stats().observe("rpc_server_latency_us",
                                 (time.perf_counter() - t0) * 1e6,
                                 {"op": method,
                                  "role": self.service_role})

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"rpc-{self.port}")
        self._thread.start()

    def stop(self):
        self._stopped.set()
        self._server.shutdown()
        self._server.server_close()


class RpcNeverSentError(RpcConnError):
    """The request provably never reached the wire (connect failure or
    connection already dead at entry) — retry is safe for ANY method,
    idempotent or not.  Higher-level retry loops (StorageClient's
    replica walk) key off this to stay double-apply-safe."""


class _Pending:
    __slots__ = ("event", "reply", "nbytes", "error")

    def __init__(self):
        self.event = threading.Event()
        self.reply = None
        self.nbytes = 0
        self.error: Optional[Exception] = None


class _Conn:
    """One pipelined connection: send lock + reader thread + pending map
    keyed by request id.  Death (socket error, malformed frame, close)
    fails every waiter at once."""

    __slots__ = ("sock", "send_lock", "pending", "plock", "_ids",
                 "dead", "inflight", "last_rx", "_reader", "timeout")

    def __init__(self, host: str, port: int, timeout: float):
        fail.hit("rpc:connect")     # raise here == connect refused
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # the socket KEEPS its timeout: a peer that stops reading must
        # not hang sendall() forever while it holds send_lock — the
        # reader tolerates idle timeouts between frames (below), so
        # pooled connections still survive quiet periods
        self.sock = sock
        self.timeout = timeout      # the BASE transport window
        self.send_lock = threading.Lock()
        self.pending: Dict[int, _Pending] = {}
        self.plock = threading.Lock()
        self._ids = itertools.count(1)
        self.dead: Optional[Exception] = None
        self.inflight = 0
        self.last_rx = time.monotonic()   # any frame received
        _gauge_delta(conns=1)
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"rpc-reader-{host}:{port}")
        self._reader.start()

    def _read_loop(self):
        hdr = bytearray(_LEN.size)
        view = memoryview(hdr)
        try:
            while True:
                # idle-tolerant length read: a socket timeout BETWEEN
                # frames just means no traffic — only a timeout
                # mid-frame (or mid-payload below) is a dead peer
                got = 0
                while got < _LEN.size:
                    try:
                        r = self.sock.recv_into(view[got:])
                    except socket.timeout:
                        if got == 0:
                            continue
                        raise RpcConnError("timeout mid-frame")
                    if r == 0:
                        raise RpcConnError("connection closed")
                    got += r
                (n,) = _LEN.unpack(hdr)
                if n > MAX_FRAME:
                    raise RpcConnError(f"frame too large: {n}")
                nbytes = _LEN.size + n
                mv = memoryview(_recv_exact(self.sock, n))
                rid = None
                if mv and mv[0] == 1:
                    if n < 5:
                        raise RpcConnError("malformed pipelined frame")
                    (rid,) = _LEN.unpack(mv[1:5])
                    mv = mv[5:]
                reply = _decode_body(mv)
                # armed kill_conn here == the connection dies with
                # replies (possibly not ours) in flight
                fail.hit("rpc:recv")
                self.last_rx = time.monotonic()
                with self.plock:
                    p = self.pending.pop(rid, None)
                if p is not None:       # late reply after timeout: drop
                    p.reply = reply
                    p.nbytes = nbytes
                    p.event.set()
        except Exception as ex:  # noqa: BLE001 — any framing/socket death
            self.die(ex)

    def die(self, ex: Exception):
        with self.plock:
            if self.dead is not None:
                return              # pending already failed by first death
            self.dead = ex
            waiters = list(self.pending.values())
            self.pending.clear()
        _gauge_delta(conns=-1)
        try:
            self.sock.close()
        except OSError:
            pass
        for p in waiters:
            p.error = ex
            p.event.set()

    def request(self, req: Dict[str, Any], timeout: float
                ) -> Tuple[Any, int, int]:
        """-> (reply, sent bytes, received bytes).  Raises RpcConnError
        on transport failure; the caller decides whether a retry is
        idempotency-safe."""
        p = _Pending()
        with self.plock:
            if self.dead is not None:
                raise RpcNeverSentError(str(self.dead))
            rid = next(self._ids)
            self.pending[rid] = p
            self.inflight += 1
        _gauge_delta(inflight=1)
        try:
            try:
                # a FIRED action here kills the live connection: the
                # request may or may not have hit the wire — the
                # mid-call at-least-once hazard, NOT a never-sent
                fail.hit("rpc:send", key=req.get("method"))
                with self.send_lock:
                    sent = _send_frame(self.sock, req, rid)
            except FrameTooLarge:
                with self.plock:
                    self.pending.pop(rid, None)
                raise                 # connection untouched, no retry
            except FailpointError as ex:
                self.die(ex)
                raise RpcConnError(f"send failed: {ex}") from None
            except OSError as ex:
                self.die(ex)
                raise RpcConnError(f"send failed: {ex}") from None
            # kill-aware reply wait (ISSUE 5): when the calling thread
            # carries a cancel context (statement-scoped call), wait in
            # slices and poll it — KILL QUERY must interrupt an
            # in-flight hop (e.g. a write stalled on a slow fsync), not
            # ride out the transport timeout.  Context-free callers
            # (heartbeats, replication) keep the single cheap wait.
            if _cancel.current_kill() is None and \
                    _cancel.current_deadline() is None:
                got = p.event.wait(timeout)
            else:
                got, wait_dl = False, time.monotonic() + timeout
                while not got:
                    rem = wait_dl - time.monotonic()
                    if rem <= 0:
                        break
                    got = p.event.wait(min(rem, 0.05))
                    if not got:
                        try:
                            _cancel.check()
                        except Exception:
                            # abandoned mid-flight: rid matching makes
                            # the late reply harmlessly droppable
                            with self.plock:
                                self.pending.pop(rid, None)
                            raise
            if not got:
                with self.plock:
                    self.pending.pop(rid, None)
                if time.monotonic() - self.last_rx >= \
                        max(timeout, self.timeout):
                    # the peer has been COMPLETELY silent for a full
                    # BASE transport window: treat the connection as
                    # dead so the pool stops queueing onto a zombie
                    # socket (fast failure detection for dead hosts).
                    # Judged against self.timeout, not the per-request
                    # wait: a deadline-clamped request can time out in
                    # milliseconds, which says nothing about the
                    # connection — killing it would collaterally abort
                    # sibling in-flight (possibly non-idempotent) calls
                    self.die(RpcConnError(
                        f"peer silent for {max(timeout, self.timeout)}s"))
                    raise RpcConnError(
                        f"rpc timeout after {timeout}s (peer silent)")
                # the connection is demonstrably alive (frames arrived
                # recently) — fail ONLY this request; rid matching makes
                # its late reply harmlessly droppable, and sibling
                # in-flight calls (possibly non-idempotent,
                # non-retryable) must not be collaterally aborted by
                # one slow handler
                raise RpcTimeoutError(f"rpc timeout after {timeout}s")
            if p.error is not None:
                raise RpcConnError(str(p.error))
            return p.reply, sent, p.nbytes
        finally:
            with self.plock:
                self.inflight -= 1
            _gauge_delta(inflight=-1)


class RpcClient:
    """Per-peer pipelined connection pool.

    Concurrent call()s multiplex over pooled connections by request id —
    they overlap in flight instead of serializing behind one socket lock
    (`StorageClient.fanout` to N partitions on one host is now wall-time
    ≈ max(partition), not sum).  Auto-reconnects; automatic retry after
    a mid-call connection death only for idempotent methods (see
    `is_idempotent`)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 2, pool_size: Optional[int] = None):
        self.host, self.port = host, port
        self.timeout = timeout
        self.retries = retries
        if pool_size is None:
            try:
                pool_size = int(get_config().get("rpc_pool_size"))
            except Exception:  # noqa: BLE001 — config not initialized
                pool_size = 2
        self.pool_size = max(1, pool_size)
        self._conns: list = []
        self._lock = threading.Lock()
        self._closed = False

    @classmethod
    def from_addr(cls, addr: str, **kw) -> "RpcClient":
        host, port = addr.rsplit(":", 1)
        return cls(host, int(port), **kw)

    def _pick(self) -> _Conn:
        """Least-loaded live connection; grow the pool while every
        existing connection is busy and the cap allows.  The blocking
        connect happens OUTSIDE the pool lock — one unreachable-peer
        connect must not stall callers that could ride an existing
        live connection."""
        with self._lock:
            if self._closed:
                raise RpcNeverSentError("client closed")
            live = [c for c in self._conns if c.dead is None]
            self._conns = live
            best = min(live, key=lambda c: c.inflight, default=None)
            if best is not None and (best.inflight == 0
                                     or len(live) >= self.pool_size):
                return best
        try:
            c = _Conn(self.host, self.port, self.timeout)
        except (OSError, FailpointError) as ex:
            raise RpcNeverSentError(
                f"connect to {self.host}:{self.port} failed: {ex}"
            ) from None
        with self._lock:
            if self._closed:
                c.die(RpcConnError("client closed"))
                raise RpcNeverSentError("client closed")
            live = [x for x in self._conns if x.dead is None]
            if len(live) >= self.pool_size:
                # a racing caller filled the pool meanwhile — keep the
                # cap: drop the extra socket, ride the least-loaded
                c.die(RpcConnError("pool full"))
                return min(live, key=lambda x: x.inflight)
            self._conns.append(c)
            return c

    def call(self, method: str, **params) -> Any:
        last_err: Optional[Exception] = None
        peer = f"{self.host}:{self.port}"
        br = breaker_for(peer)
        cc = current_cost()

        def note_retry(ex: Exception, attempt: int):
            _stats().inc_labeled("rpc_client_retries", {"op": method})
            # trace coverage (ISSUE 8 satellite): every retry attempt
            # is a leaf in the statement's trace with the peer labeled
            _trace.record_phase("rpc:retry", 0.0, peer=peer, op=method,
                                attempt=attempt,
                                error=type(ex).__name__)

        with _trace.span(f"rpc:{method}", peer=f"{self.host}:{self.port}"):
            for attempt in range(self.retries + 1):
                # deadline budget: no attempt (or backoff sleep) may
                # outlive the statement's remaining budget — raises
                # DeadlineExceeded/QueryKilled into the caller, which
                # surfaces as E_QUERY_TIMEOUT at the graphd boundary
                _cancel.check()
                # per-attempt timer: a success after a reconnect must
                # not record the dead attempt + backoff sleep as op
                # latency (the rpc:<method> span still covers the whole
                # call, retries included)
                t_call = time.perf_counter()
                req = {"method": method, "params": params}
                timeout = self.timeout
                rem = _cancel.remaining()
                if rem is not None:
                    # stamp the REMAINING seconds into the envelope (the
                    # server re-anchors on its own clock — clock-skew-
                    # free relative propagation) and clamp the transport
                    # wait to the budget.  Fixed-width so identical
                    # queries produce byte-identical frames regardless
                    # of how much budget happens to remain (the wire-
                    # byte work counters are a documented regression
                    # probe — docs/OBSERVABILITY.md)
                    req["dl"] = f"{min(max(rem, 0.001), 1e8):013.3f}"
                    timeout = min(timeout, max(rem, 0.001))
                tctx = _trace.wire_context()
                if tctx is not None:
                    req["trace"] = list(tctx)
                if cc is not None:
                    # ask the peer for a cost record in the reply
                    # envelope (per-plan-node attribution, ISSUE 8)
                    req["c"] = 1
                if not br.allow():
                    # open breaker: fail fast, provably never sent.
                    # Checked OUTSIDE the try: a short-circuit is not a
                    # peer failure — recording it would clear another
                    # thread's half-open probe and re-trip the breaker
                    # on a call that never left the process
                    last_err = RpcNeverSentError(
                        f"circuit open to {self.host}:{self.port}")
                    if attempt < self.retries:
                        # trace only — the breaker short-circuit never
                        # re-sent anything, so the rpc_client_retries
                        # counter (an internal-re-send measure feeding
                        # retry_amplification) must not move
                        _trace.record_phase(
                            "rpc:retry", 0.0, peer=peer, op=method,
                            attempt=attempt, error="CircuitOpen")
                        deadline_sleep(retry_backoff(attempt))
                    continue
                sent_any = False
                try:
                    conn = self._pick()
                    sent_any = True     # bytes may be on the wire now
                    reply, sent, recvd = conn.request(req, timeout)
                except FrameTooLarge:
                    br.release_probe()
                    raise
                except RpcNeverSentError as ex:
                    last_err = ex       # provably never sent: retryable
                    br.record_failure()
                    if attempt < self.retries:
                        note_retry(ex, attempt)
                        deadline_sleep(retry_backoff(attempt))
                    continue
                except RpcTimeoutError as ex:
                    # one slow request on an alive connection: breaker-
                    # neutral (see RpcTimeoutError) — free any probe
                    # slot, keep the mid-call idempotency gate below
                    last_err = ex
                    br.release_probe()
                    if sent_any and not is_idempotent(method):
                        raise RpcConnError(
                            f"rpc {method} to {self.host}:{self.port} "
                            f"failed mid-call and is not idempotent "
                            f"(not retried): {ex}") from None
                    if attempt < self.retries:
                        note_retry(ex, attempt)
                        deadline_sleep(retry_backoff(attempt))
                    continue
                except (OSError, RpcConnError,
                        json.JSONDecodeError) as ex:
                    last_err = ex
                    br.record_failure()
                    # connect failures never reached the peer — always
                    # retryable; mid-call deaths may have applied the
                    # request, so only idempotent methods auto-retry
                    if sent_any and not is_idempotent(method):
                        raise RpcConnError(
                            f"rpc {method} to {self.host}:{self.port} "
                            f"failed mid-call and is not idempotent "
                            f"(not retried): {ex}") from None
                    if attempt < self.retries:
                        note_retry(ex, attempt)
                        deadline_sleep(retry_backoff(attempt))
                    continue
                except BaseException:
                    # non-transport exit (QueryKilled/DeadlineExceeded
                    # from the kill-aware reply wait): no verdict on
                    # the peer — free the probe slot and re-raise
                    br.release_probe()
                    raise
                # ANY reply proves the peer alive — an application
                # error is not a transport failure
                br.record_success()
                us = (time.perf_counter() - t_call) * 1e6
                _stats().observe("rpc_client_latency_us", us,
                                 {"op": method})
                wc = current_work()
                if wc is not None:
                    wc.add_rpc(sent, recvd)
                if cc is not None:
                    # fold the peer's cost record (success AND error
                    # replies — a failing node's costs still land in
                    # PROFILE / the flight recorder) plus our own
                    # call/byte counts into the active node's sink
                    rcost = reply.get("cost")
                    if isinstance(rcost, dict):
                        cc.merge_reply(rcost)
                    cc.add("calls", 1)
                    cc.add("bytes_sent", sent)
                    cc.add("bytes_recv", recvd)
                # remote spans come back on error replies too — a
                # failing branch's storaged subtree must still land in
                # the coordinator's trace
                _trace.graft(reply.get("spans") or [])
                if reply.get("ok"):
                    return reply.get("result")
                _stats().inc_labeled("rpc_client_errors", {"op": method})
                err = reply.get("error", "unknown error")
                if is_overload(err):
                    # the peer SHED this request before its handler ran
                    # (bounded inbox / admission): retrying is safe for
                    # ANY method, and breaker-neutral — the reply
                    # itself proves the peer alive (record_success
                    # already ran above).  Honor the retry-after hint
                    # inside the deadline-budgeted backoff: the sleep
                    # is clamped to the statement's remaining budget
                    # and wakes on KILL QUERY like every other backoff.
                    last_err = RpcError(err)
                    if attempt < self.retries:
                        _stats().inc_labeled("overload_client_retries",
                                             {"op": method})
                        _trace.record_phase(
                            "rpc:retry", 0.0, peer=peer, op=method,
                            attempt=attempt, error="Overload")
                        hint = parse_retry_after(err)
                        # jitter the hint: every client shed in one
                        # saturation burst sees the same depth and the
                        # same hint — sleeping it verbatim re-arrives
                        # the whole herd in one pulse
                        deadline_sleep(
                            hint * random.uniform(0.5, 1.5)
                            if hint is not None
                            else retry_backoff(attempt))
                        continue
                    raise RpcError(err)
                if isinstance(err, str) and \
                        ("E_QUERY_TIMEOUT" in err or
                         err.startswith("DeadlineExceeded")):
                    # the remote hop's re-anchored budget expired first
                    # (sub-ms race with our own clock): surface the
                    # SAME exception the local deadline check raises so
                    # the engine boundary counts and reports timeouts
                    # identically whichever side's clock wins
                    raise _cancel.DeadlineExceeded(err)
                raise RpcError(err)
        # preserve the never-sent distinction through the final raise so
        # higher-level retry loops stay double-apply-safe
        kind = RpcNeverSentError if isinstance(last_err, RpcNeverSentError) \
            else RpcConnError
        raise kind(f"rpc to {self.host}:{self.port} failed: {last_err}")

    def close(self):
        with self._lock:
            self._closed = True
            conns, self._conns = self._conns, []
        for c in conns:
            c.die(RpcConnError("client closed"))


class RpcRaftTransport:
    """RaftTransport over RpcClient connections — raftex.thrift's role.

    peer ids ARE addresses ("host:port"); raft messages dispatch to the
    `raft` method of the peer's RpcServer, which routes to the right
    RaftPart by group.
    """

    def __init__(self):
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()

    def client(self, peer: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(peer)
            if c is None:
                c = self._clients[peer] = RpcClient.from_addr(
                    peer, timeout=2.0, retries=0, pool_size=1)
            return c

    def send(self, peer, group, method, payload):
        try:
            return self.client(peer).call(
                "raft", group=group, rmethod=method, payload=payload)
        except (RpcError, RpcConnError):
            return None


def serve_raft_parts(server: RpcServer, parts: Dict[str, Any]):
    """Register the `raft` dispatch method for a dict group → RaftPart."""
    def handler(params):
        part = parts.get(params["group"])
        if part is None:
            raise RpcError(f"no raft group `{params['group']}' here")
        return part.handle(params["rmethod"], params["payload"])
    server.register("raft", handler)
