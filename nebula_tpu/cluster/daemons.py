"""Daemon entrypoints — `python -m nebula_tpu.cluster.daemons <role>`.

The GraphDaemon/MetaDaemon/StorageDaemon analog (reference: src/daemons
[UNVERIFIED — empty mount, SURVEY §0]): flag parsing, service wiring,
signal-friendly foreground run.  One process per role:

    python -m nebula_tpu.cluster.daemons metad    --addr 0.0.0.0:9559 \
        --peers host1:9559,host2:9559,host3:9559 --data-dir /data/meta
    python -m nebula_tpu.cluster.daemons storaged --addr 0.0.0.0:9779 \
        --meta host1:9559 --data-dir /data/storage
    python -m nebula_tpu.cluster.daemons graphd   --addr 0.0.0.0:9669 \
        --meta host1:9559 [--tpu]
"""
from __future__ import annotations

import argparse
import signal
import threading
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-daemon")
    ap.add_argument("role", choices=["metad", "storaged", "graphd"])
    ap.add_argument("--addr", required=True, help="host:port to listen on")
    ap.add_argument("--peers", default="", help="metad: comma-sep peer addrs")
    ap.add_argument("--meta", default="", help="comma-sep metad addrs")
    ap.add_argument("--data-dir", default="./data")
    ap.add_argument("--tpu", action="store_true",
                    help="graphd: enable the device execution plane")
    ap.add_argument("--ws-port", type=int, default=-1,
                    help="HTTP admin port (/status /stats /flags); "
                         "-1 = rpc port + 1000, 0 = disabled")
    ap.add_argument("--local-conf", default="",
                    help="gflags-style key=value config file")
    args = ap.parse_args(argv)

    from ..utils.config import get_config
    if args.local_conf:
        get_config().load_file(args.local_conf)
    import logging
    lvl = {0: logging.INFO, 1: logging.WARNING}.get(
        int(get_config().get("minloglevel")), logging.ERROR)
    if int(get_config().get("v")) > 0:
        lvl = logging.DEBUG
    logging.basicConfig(level=lvl,
                        format="%(asctime)s %(levelname).1s %(name)s "
                               "%(message)s")

    from .meta_client import MetaClient
    from .rpc import RpcServer, serve_raft_parts

    host, port = args.addr.rsplit(":", 1)
    server = RpcServer(host, int(port))

    if args.role == "metad":
        from .meta_service import MetaService
        peers = [p for p in args.peers.split(",") if p] or [args.addr]
        svc = MetaService(args.addr, peers, args.data_dir, server=server)
        serve_raft_parts(server, {"meta": svc.raft})
    else:
        metas = [m for m in args.meta.split(",") if m]
        if not metas:
            ap.error(f"{args.role} requires --meta")
        mc = MetaClient(metas, my_addr=args.addr,
                        role="storage" if args.role == "storaged" else "graph")
        mc.wait_ready()
        mc.refresh(force=True)
        if args.role == "storaged":
            from .storage_service import StorageService
            svc = StorageService(args.addr, mc, args.data_dir, server=server)
        else:
            from .graph_service import GraphService
            rt = None
            if args.tpu:
                from ..tpu.runtime import TpuRuntime
                rt = TpuRuntime()
            svc = GraphService(args.addr, mc, server=server, tpu_runtime=rt)

    server.start()
    web = None
    fed = None
    if args.ws_port != 0:
        from .webservice import WebService
        ws_port = args.ws_port if args.ws_port > 0 else int(port) + 1000
        web = WebService(role=args.role, host=host, port=ws_port)
        if args.role == "metad":
            # metric federation (ISSUE 8): this metad scrapes every
            # daemon's /metrics (addresses ride the heartbeats) into
            # one labeled /cluster_metrics view
            from .federation import MetricFederator
            fed = MetricFederator(svc, self_ws=web.addr)
            web.providers["/cluster_metrics"] = lambda q: (
                200, fed.render(),
                "text/plain; version=0.0.4; charset=utf-8")
            import json as _json
            web.providers["/federation"] = lambda q: (
                200, _json.dumps(fed.scrape_status(), default=str),
                "application/json")
            # live workload federation (ISSUE 9): one endpoint answers
            # "what is the whole cluster running right now"
            web.providers["/cluster_queries"] = lambda q: (
                200, _json.dumps(fed.cluster_queries(), default=str),
                "application/json")
            # auto-repair plans (ISSUE 14): the raft-persisted
            # RepairPlan table (metrics_dump --repairs scrapes this)
            web.providers["/repairs"] = lambda q: (
                200, _json.dumps(svc.rpc_list_repairs({}), default=str),
                "application/json")
            # workload insights federation (ISSUE 16): every graphd's
            # fingerprint table, per-host + exactly merged
            web.providers["/cluster_statements"] = lambda q: (
                200, _json.dumps(fed.cluster_statements(), default=str),
                "application/json")
            # heat rides the heartbeats, so metad answers hotspots from
            # its own host table — no extra scrape round
            web.providers["/hotspots"] = lambda q: (
                200, _json.dumps(svc.rpc_hotspots({}), default=str),
                "application/json")
        else:
            # tell metad where to scrape us (rides the heartbeat) —
            # set BEFORE svc.start() so the first heartbeat carries it
            mc.ws_addr = web.addr
            import json as _json
            if args.role == "graphd":
                # this graphd's statement fingerprint table (ISSUE 16)
                # — the target of metad's /cluster_statements fan-out
                web.providers["/statements"] = lambda q: (
                    200, _json.dumps(svc.engine.insights.snapshot(),
                                     default=str),
                    "application/json")
            else:
                # this storaged's per-part heat rows (local, unmerged;
                # the cluster-ranked view lives on metad)
                web.providers["/hotspots"] = lambda q: (
                    200, _json.dumps(svc.part_heat.snapshot(),
                                     default=str),
                    "application/json")
        web.start()
    svc.start()
    if fed is not None:
        fed.start()
    # startup object graph (services, raft parts, jax runtime) is
    # permanent — freeze it out of the GC scan set; periodic gen-2
    # collections over a loaded jax runtime stall queries by ~250 ms
    import gc
    gc.collect()
    gc.freeze()
    print(f"nebula-tpu {args.role} serving on {server.addr}"
          + (f" (admin http on {web.addr})" if web else ""), flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    while not stop.is_set():
        time.sleep(0.5)
    if fed is not None:
        fed.stop()
    svc.stop()
    server.stop()
    if web is not None:
        web.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
