"""MetaClient — cached catalog + part map + heartbeat loop.

Analog of the reference's src/clients/meta MetaClient [UNVERIFIED —
empty mount, SURVEY §0]: every process (graphd, storaged, tools) holds
one; it finds the metad leader, keeps a versioned local replica of the
catalog and partition map (refreshed when a heartbeat reply reports a
newer version), and offers the meta operation set as methods.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..graphstore.schema import Catalog
from ..utils import cancel as _cancel
from .meta_service import _pk, _unpk
from .rpc import (RpcClient, RpcConnError, RpcError, deadline_sleep,
                  retry_backoff)


class MetaError(Exception):
    pass


class MetaClient:
    def __init__(self, meta_addrs: List[str], my_addr: str = "",
                 role: str = "client",
                 heartbeat_interval: Optional[float] = None):
        self.meta_addrs = list(meta_addrs)
        self.my_addr = my_addr
        self.role = role
        # this daemon's HTTP admin (webservice) address, carried in the
        # heartbeat so metad's metric federation knows where to scrape
        # (empty = no webservice / not scrapable)
        self.ws_addr = ""
        if heartbeat_interval is None:
            from ..utils.config import get_config
            heartbeat_interval = float(
                get_config().get("heartbeat_interval_secs"))
        self.hb_interval = heartbeat_interval
        self.catalog = Catalog()
        self.part_map: Dict[str, List[List[str]]] = {}
        # space → per-part learner lists (ISSUE 14): cached alongside
        # the part map but NEVER consulted by routing — a catching-up
        # learner serves no reads and takes no writes until promoted
        self.learner_map: Dict[str, List[List[str]]] = {}
        # (space, pid) → last leader learned from a storaged's
        # "part_leader_changed: <addr>" hint (ISSUE 11 satellite).  An
        # overlay, not an edit of part_map: it survives refresh()
        # overwriting the map (metad only reorders replicas on explicit
        # BALANCE LEADER — an election-driven leader change never
        # reaches the map, so without this every statement would re-walk
        # until the next transfer)
        self._part_hints: Dict[tuple, str] = {}
        self.version = -1
        from ..utils.racecheck import make_lock
        self.lock = make_lock("meta_client")
        self._clients: Dict[str, RpcClient] = {}
        self._leader: Optional[str] = None
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._hb_parts_fn = None          # set by storaged: () -> {space: [pid]}
        self._hb_heat_fn = None           # set by storaged: () -> PartHeat rows
        self._hb_epochs_fn = None         # set by storaged: () -> epoch vector
        self.on_epochs = None             # set by graphd: merged table fold
        self.on_refresh = None            # hook: called after a cache refresh

    # -- leader discovery -------------------------------------------------

    def _client(self, addr: str) -> RpcClient:
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = RpcClient.from_addr(addr, timeout=10.0,
                                                          retries=0)
        return c

    def call(self, method: str, _retries: int = 6, **params) -> Any:
        """Call the metad leader, following leader hints / re-probing."""
        last = None
        for attempt in range(_retries):
            # deadline budget: a statement-scoped meta call must stop
            # walking when the budget is spent (heartbeat threads carry
            # no context — check() is a no-op there)
            _cancel.check()
            addrs = ([self._leader] if self._leader else []) + \
                [a for a in self.meta_addrs if a != self._leader]
            for addr in addrs:
                try:
                    r = self._client(addr).call(method, **params)
                    self._leader = addr
                    return r
                except RpcError as ex:
                    last = ex
                    msg = str(ex)
                    if msg.startswith("not leader"):
                        # hint grammar: "not leader; leader=<addr>".  A
                        # reply with NO "=" (or an empty hint — election
                        # in flight) must clear the cache and re-probe,
                        # never adopt the message text as an address
                        halves = msg.split("=", 1)
                        hint = halves[1].strip() if len(halves) == 2 \
                            else ""
                        self._leader = hint or None
                        continue
                    raise MetaError(msg) from None
                except RpcConnError as ex:
                    last = ex
                    self._leader = None
                    continue
            # all metads down / electing: jittered exponential backoff
            # (deadline-clamped) instead of a fixed-step herd
            if attempt < _retries - 1:
                from ..utils.stats import stats as _stats
                _stats().inc("meta_leader_walk_retries")
                deadline_sleep(retry_backoff(attempt, base=0.1, cap=1.0))
        raise MetaError(f"no metad leader reachable: {last}")

    def wait_ready(self, timeout: float = 15.0):
        dl = time.monotonic() + timeout
        while time.monotonic() < dl:
            try:
                self.call("meta.ready", _retries=1)
                return
            except MetaError:
                time.sleep(0.1)
        raise MetaError("metad not ready")

    # -- cache ------------------------------------------------------------

    def refresh(self, force: bool = False):
        from ..utils import trace as _trace
        with self.lock:
            ver = None if force else self.version
        with _trace.span("meta:refresh", force=force):
            r = self.call("meta.get_catalog", version=ver)
        changed = r["catalog"] is not None
        with self.lock:
            if changed:
                self.catalog = _unpk(r["catalog"])
                self.part_map = r["part_map"]
                self.learner_map = r.get("learner_map") or {}
            self.version = r["version"]
        if changed and self.on_refresh is not None:
            self.on_refresh()

    def heartbeat_once(self) -> Dict[str, Any]:
        parts = self._hb_parts_fn() if self._hb_parts_fn else {}
        # per-partition heat rides the heartbeat (ISSUE 16): snapshot()
        # folds the QPS EWMAs forward, so metad's view decays with the
        # heartbeat cadence; an empty/None payload costs nothing
        heat = self._hb_heat_fn() if self._hb_heat_fn else None
        # per-space store epochs ride up (storaged) and the merged
        # cluster table rides every reply down (ISSUE 20) — the fleet
        # cache-coherence plane needs no RPC of its own
        epochs = self._hb_epochs_fn() if self._hb_epochs_fn else None
        r = self.call("meta.heartbeat", host=self.my_addr, role=self.role,
                      parts=parts, ws=self.ws_addr, heat=heat,
                      epochs=epochs)
        if r["version"] != self.version:
            self.refresh(force=True)
        if self.on_epochs is not None and r.get("epochs"):
            try:
                self.on_epochs(r["epochs"])
            except Exception:  # noqa: BLE001 — fold must never kill the hb
                pass
        return r

    def cluster_epochs(self) -> Dict[str, Any]:
        """Pull metad's merged epoch table on demand — the strict
        check-at-admission leg (ISSUE 20): one round-trip buys leader
        reads exactness instead of the heartbeat-bounded window."""
        return self.call("meta.cluster_epochs").get("epochs") or {}

    def start_heartbeat(self, parts_fn=None, heat_fn=None, epochs_fn=None):
        self._hb_parts_fn = parts_fn
        self._hb_heat_fn = heat_fn
        self._hb_epochs_fn = epochs_fn
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.hb_interval):
                try:
                    self.heartbeat_once()
                except MetaError:
                    pass
        self._hb_thread = threading.Thread(target=loop, daemon=True,
                                           name=f"meta-hb-{self.my_addr}")
        self._hb_thread.start()

    def stop_heartbeat(self):
        self._stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2)

    # -- meta ops ---------------------------------------------------------

    def create_space(self, name: str, **kw):
        r = self.call("meta.create_space", name=name, kw=kw)
        self.refresh(force=True)
        return r

    def drop_space(self, name: str, if_exists: bool = False):
        self.call("meta.drop_space", name=name, if_exists=if_exists)
        self.refresh(force=True)

    def ddl(self, method: str, *args, **kw):
        """create_tag/create_edge/alter_*/drop_*/create_index/drop_index
        with the same signatures as graphstore.schema.Catalog."""
        cmd = {"op": "catalog", "method": method, "args": args, "kw": kw}
        self.call("meta.ddl", cmd64=_pk(cmd))
        self.refresh(force=True)

    def note_part_leader(self, space: str, pid: int, addr: str):
        """Write a learned leader back into the cached part map (as an
        overlay): the walk that discovered a failover pays once, every
        later statement routes straight to the new leader."""
        if not addr or ":" not in addr:
            return
        with self.lock:
            self._part_hints[(space, pid)] = addr

    def parts_of(self, space: str) -> List[List[str]]:
        with self.lock:
            pm = self.part_map.get(space)
            hints = {p: a for (s, p), a in self._part_hints.items()
                     if s == space} if self._part_hints else None
        if pm is None:
            self.refresh(force=True)
            with self.lock:
                pm = self.part_map.get(space)
        if pm is None:
            raise MetaError(f"space `{space}' not found")
        if not hints:
            return pm
        # hint overlay: front-load the learned leader per part.  A hint
        # whose addr left the replica set (balance moved the part) is
        # simply ignored — the map is the membership authority.
        out = []
        for pid, replicas in enumerate(pm):
            a = hints.get(pid)
            if a and a in replicas and replicas[0] != a:
                replicas = [a] + [x for x in replicas if x != a]
            out.append(replicas)
        return out

    def create_session(self, user: str, graphd: str) -> int:
        return self.call("meta.create_session", user=user, graphd=graphd)

    def remove_session(self, sid: int):
        self.call("meta.remove_session", sid=sid)

    def update_session(self, sid: int, **fields):
        self.call("meta.update_session", sid=sid, fields=fields)

    def list_sessions(self):
        return self.call("meta.list_sessions")

    def session_gone(self, sid: int) -> bool:
        try:
            return bool(self.call("meta.session_gone", sid=sid).get("gone"))
        except Exception:  # noqa: BLE001 — old metad: no tombstones
            return False

    def list_hosts(self):
        return self.call("meta.list_hosts")

    def get_config(self, name: Optional[str] = None):
        return self.call("meta.get_config", **({"name": name} if name else {}))

    def set_config(self, name: str, value: Any):
        self.call("meta.set_config", name=name, value=value)

    def submit_job(self, cmd: str, space: Optional[str] = None,
                   graphd: str = "") -> int:
        """graphd: the submitting/executing graphd — recorded in the
        job row at birth so STOP can always route (no window where the
        row has no executor)."""
        return self.call("meta.submit_job", cmd=cmd, space=space,
                         graphd=graphd)

    def update_job(self, jid: int, **fields):
        self.call("meta.update_job", jid=jid, fields=fields)

    def add_hosts_to_zone(self, hosts, zone: str):
        self.call("meta.add_hosts", hosts=list(hosts), zone=zone)

    def drop_zone(self, zone: str):
        self.call("meta.drop_zone", zone=zone)

    def merge_zones(self, zones, into: str):
        self.call("meta.merge_zones", zones=list(zones), into=into)

    def rename_zone(self, old: str, new: str):
        self.call("meta.rename_zone", old=old, new=new)

    def divide_zone(self, zone: str, parts):
        self.call("meta.divide_zone", zone=zone,
                  parts=[[n, list(hs)] for n, hs in parts])

    def drop_hosts(self, hosts):
        self.call("meta.drop_hosts", hosts=list(hosts))

    def list_zones(self):
        return self.call("meta.list_zones")

    def allocate_ids(self, count: int = 1) -> int:
        """Cluster-unique monotonic id range; returns the range start."""
        return self.call("meta.allocate_ids", count=count)["start"]

    # -- balance / repair plane (BALANCE DATA / auto-repair, ISSUE 14) --

    def set_part_replicas(self, space: str, part: int, replicas):
        self.call("meta.set_part_replicas", space=space, part=part,
                  replicas=list(replicas))
        self.refresh(force=True)

    def learners_of(self, space: str) -> List[List[str]]:
        """Per-part learner lists (cached; padded to the part count)."""
        pm = self.parts_of(space)
        with self.lock:
            lm = self.learner_map.get(space) or []
        return [list(lm[pid]) if pid < len(lm) else []
                for pid in range(len(pm))]

    def set_part_learners(self, space: str, part: int, learners):
        self.call("meta.set_part_learners", space=space, part=part,
                  learners=list(learners))
        self.refresh(force=True)

    def promote_learner(self, space: str, part: int, host: str):
        self.call("meta.promote_learner", space=space, part=part,
                  host=host)
        self.refresh(force=True)

    def list_repairs(self):
        return self.call("meta.list_repairs")

    def transfer_leader(self, space: str, part: int, to: str):
        self.call("meta.transfer_leader", space=space, part=part, to=to)
        self.refresh(force=True)

    def list_jobs(self):
        return self.call("meta.list_jobs")

    def close(self):
        self.stop_heartbeat()
        for c in self._clients.values():
            c.close()
