"""Storage-side execution of pushed-down edge predicates and limits.

The reference compiles each GetNeighbors request into a storage-side
exec DAG (StoragePlan: ScanNode → FilterNode → LimitNode;
reference: src/storage/exec [UNVERIFIED — empty mount, SURVEY §2
row 12]) so filtering happens WHERE THE DATA IS and the RPC ships only
surviving rows.  Same essence here: graphd decides a predicate is
storage-evaluable (`pushable`), ships it as nGQL text (the wire-safe
canonical form — never pickled code), and storaged parses it once per
request and evaluates per edge row before serialization.

Pushable = references nothing beyond the edge being scanned: its props
(via `etype.prop` or the planner's `__edge__` alias), rank/src/dst/
type/typeid of `edge`, literals, and pure functions.  $$ / $^ vertex
props, input rows, variables, and nondeterministic functions stay on
graphd.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import expr as E
from ..core.expr import to_bool3, to_text
from ..core.value import NullValue, make_edge

# nondeterministic or environment-reading functions must evaluate once,
# on graphd — per-row storage evaluation would change semantics
_NONPUSHABLE_FNS = {"rand", "rand32", "rand64", "now", "timestamp",
                    "date", "time", "datetime"}

_EDGE_FNS = {"rank", "src", "dst", "type", "typeid", "id"}


class NotPushable(Exception):
    pass


def pushable(e: E.Expr, etypes: Sequence[str]) -> bool:
    """True iff the predicate can evaluate storage-side against one
    scanned edge row with identical semantics."""
    try:
        _check(e, set(etypes))
        return True
    except NotPushable:
        return False


def _check(e: E.Expr, etypes: set):
    k = e.kind
    if k == "literal":
        v = e.value
        if v is None or isinstance(v, (bool, int, float, str, NullValue)):
            return
        raise NotPushable(f"literal {type(v)}")
    if k == "edge_prop":
        if e.edge != "__edge__" and e.edge not in etypes:
            raise NotPushable(f"prop of non-scanned edge {e.edge}")
        return
    if k == "attribute" and isinstance(e.obj, E.LabelExpr):
        if e.obj.name != "__edge__" and e.obj.name not in etypes:
            raise NotPushable(f"attribute of {e.obj.name}")
        return
    if k == "edge":
        return
    if k == "unary":
        _check(e.operand, etypes)
        return
    if k == "binary":
        _check(e.lhs, etypes)
        _check(e.rhs, etypes)
        return
    if k in ("list", "set"):
        for item in e.items:
            _check(item, etypes)
        return
    if k == "case":
        if e.condition is not None:
            _check(e.condition, etypes)
        for w, t in e.whens:
            _check(w, etypes)
            _check(t, etypes)
        if e.default is not None:
            _check(e.default, etypes)
        return
    if k == "function":
        name = e.name.lower()
        if name in _NONPUSHABLE_FNS:
            raise NotPushable(f"function {name}")
        if name in _EDGE_FNS and len(e.args) == 1 \
                and e.args[0].kind == "edge":
            return
        for a in e.args:
            _check(a, etypes)
        return
    raise NotPushable(f"expr kind {k}")


def filter_to_wire(e: Optional[E.Expr]) -> Optional[str]:
    return None if e is None else to_text(e)


_parse_cache: Dict[str, E.Expr] = {}


def filter_from_wire(text: Optional[str]) -> Optional[E.Expr]:
    if not text:
        return None
    e = _parse_cache.get(text)
    if e is None:
        from ..query.parser import parse_expression
        e = parse_expression(text)
        if len(_parse_cache) > 512:     # traversals re-ship one filter
            _parse_cache.clear()        # per request; bound the cache
        _parse_cache[text] = e
    return e


def apply_edge_filter(rows: Iterable[Tuple], space: str,
                      edge_filter: Optional[E.Expr],
                      etype_ids: Dict[str, int],
                      limit_per_src: Optional[int] = None,
                      stats_prefix: Optional[str] = None):
    """Run the pushed-down (filter, per-src limit) over get_neighbors
    rows — the FilterNode/LimitNode stage, shared by storaged (cluster)
    and GraphStore (standalone parity)."""
    from ..exec.context import RowContext
    if stats_prefix is not None:
        from ..utils.stats import stats as _stats
        reg = _stats()
    else:
        reg = None
    taken: Dict[Any, int] = {}
    for row in rows:
        (src, et, rank, other, props, sd) = row
        if reg is not None:
            reg.inc(stats_prefix + "_scanned")
        if limit_per_src is not None:
            key = repr(src)
            if taken.get(key, 0) >= limit_per_src:
                continue
        if edge_filter is not None:
            e = make_edge(src, other, et, rank, props, sd, etype_ids[et])
            # the wire round-trip (to_text → parse) renders EdgeProp as
            # `etype.prop`, which re-parses as attribute-of-label — bind
            # the edge under its type name (and the planner's __edge__
            # alias) so both spellings resolve
            rc = RowContext(None, space,
                            {"_src": src, "_edge": e, "_dst": other},
                            extra_vars={et: e, "__edge__": e})
            if to_bool3(edge_filter.eval(rc)) is not True:
                continue
        if limit_per_src is not None:
            taken[key] = taken.get(key, 0) + 1
        if reg is not None:
            reg.inc(stats_prefix + "_shipped")
        yield row
