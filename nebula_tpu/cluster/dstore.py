"""DistributedStore — the GraphStore interface over cluster RPC.

graphd's executors run unchanged against this adapter: it implements the
store surface they use (get_neighbors / point reads / scans / mutations
/ DDL / stats) by routing through MetaClient + StorageClient.  This is
the seam that makes single-process and cluster mode share one executor
stack — the reference gets the same effect from StorageAccessExecutor
always speaking StorageClient (reference: src/graph/executor
[UNVERIFIED — empty mount, SURVEY §0]).

Write semantics: schema defaults are resolved HERE (so part raft logs
replay deterministically), then edge writes run as a TOSS chain — the
out-half to src's part, then the in-half to dst's part (SURVEY §2
row 14).
"""
from __future__ import annotations

import itertools
import uuid
from typing import Any, Dict, Iterable, List, Optional

from ..core.wire import from_wire, to_wire
from ..graphstore.schema import (SchemaError, apply_defaults,
                                  fill_row)
from ..graphstore.store import stable_vid_hash
from ..utils import consistency as _consistency
from ..utils.failpoints import fail
from .meta_client import MetaClient
from .storage_client import StorageClient, StorageError


def _decode_neighbors_columnar(r, edge_svs):
    """Decode a columnar get_neighbors reply (storage_service
    `_neighbors_columnar`) into the (src, et, rank, other, props, sd)
    row tuples the executor contract expects.  Schema-upgrade fill
    (fill_row) hoists out of the row loop: the reply's prop-key set is
    uniform, so the missing-prop defaults are per-reply constants."""
    et = r["et"]
    sv = edge_svs.get(et)
    if sv is None:
        return                        # edge type dropped: rows invisible
    from ..core.wire import decode_column
    srcs = decode_column(r["src"]).tolist()
    ranks = decode_column(r["rank"]).tolist()
    dsts = decode_column(r["dst"]).tolist()
    sds = decode_column(r["sd"]).tolist()
    pnames = list(r["props"])
    plists = []
    for c in r["props"].values():
        if c.get("b") is not None:
            plists.append(decode_column(c).tolist())
        else:
            plists.append([from_wire(x) for x in c["v"]])
    fill = fill_row(sv, dict.fromkeys(pnames, None))
    extra = [(k, v) for k, v in fill.items() if k not in r["props"]]
    if plists:
        for src, rank, dst, sd, *pv in zip(srcs, ranks, dsts, sds,
                                           *plists):
            props = dict(zip(pnames, pv))
            if extra:
                props.update(extra)
            yield (src, et, rank, dst, props, sd)
    else:
        props0 = dict(extra)
        for src, rank, dst, sd in zip(srcs, ranks, dsts, sds):
            yield (src, et, rank, dst, dict(props0) if extra else {},
                   sd)


class CatalogProxy:
    """Reads hit the local catalog replica; DDL mutations route to metad
    (so `qctx.catalog.create_tag(...)` in a DDL executor works unchanged
    in cluster mode)."""

    # create_user/alter_user/change_password do NOT route here — the
    # credential branch in __getattr__ rewrites them to hashed forms
    _MUTATORS = frozenset({
        "create_tag", "create_edge", "alter_tag", "alter_edge",
        "drop_tag", "drop_edge", "create_index", "drop_index",
        "create_fulltext_index", "drop_fulltext_index",
        "add_listener", "remove_listener",
        "drop_user", "grant_role", "revoke_role"})

    def __init__(self, meta: MetaClient):
        object.__setattr__(self, "_meta", meta)

    def __getattr__(self, name):
        meta = object.__getattribute__(self, "_meta")
        if name in ("create_user", "alter_user", "change_password"):
            # hash HERE: the metad raft WAL is a durable log and must
            # never carry plaintext credentials
            from ..graphstore.schema import hash_password

            def cred(*a, _name=name, **kw):
                if _name == "create_user":
                    meta.ddl("create_user_hashed", a[0],
                             hash_password(a[1]),
                             if_not_exists=(kw.get("if_not_exists")
                                            or (len(a) > 2 and a[2])))
                    return
                if _name == "change_password":
                    # atomic check-and-set inside the metad state
                    # machine (a cached-catalog check here would let a
                    # stale credential authorize the rotation)
                    meta.ddl("change_password_hashed", a[0],
                             hash_password(a[1]), hash_password(a[2]))
                    return
                meta.ddl("set_password_hash", a[0], hash_password(a[1]))
            return cred
        if name in CatalogProxy._MUTATORS:
            return lambda *a, **kw: meta.ddl(name, *a, **kw)
        return getattr(meta.catalog, name)


class DistributedStore:
    def __init__(self, meta: MetaClient, sc: Optional[StorageClient] = None):
        self.meta = meta
        self.sc = sc or StorageClient(meta)
        self._catalog_proxy = CatalogProxy(meta)
        # space → (epoch, vid_to_dense, dense_to_vid) from the last CSR
        # export; serves _SpaceView.dense_id for the device drivers
        self._dense_cache: Dict[str, Any] = {}
        # exactly-once write identity (ISSUE 5): every storage.write
        # request carries (writer_id, seq); storaged's raft-replicated
        # dedup window recognizes a re-sent request and returns its
        # recorded outcome instead of double-applying
        self.writer_id = uuid.uuid4().hex[:16]
        self._wseq = itertools.count(1)
        # read-your-writes floors (ISSUE 11): per-(space, part) highest
        # raft index any write THROUGH THIS STORE was acked at (the ack
        # carries it — including dedup-retry acks, so the floor is
        # right even when the reply that carried the original index was
        # lost).  Follower/bounded_stale reads ship the floor as
        # `min_applied`; a replica may only serve once its apply covers
        # it.  Process-wide (all sessions of this graphd share the
        # store) — a superset of per-session tracking, never weaker.
        self._applied_floor: Dict[tuple, int] = {}
        import threading
        self._floor_lock = threading.Lock()
        # cluster cache epochs (ISSUE 20): set by GraphService to fold
        # write-ack store epochs into the engine's ClusterEpochs —
        # (space, epoch) -> None
        self.on_epoch_ack = None
        # device delta feed (ISSUE 19): dirty-key log per watched space.
        # Keys are noted BEFORE the writes ship (a crash mid-send leaves
        # a superset — harmless, apply re-reads per key); coverage
        # against OTHER writers is proven at delta_records time by the
        # storaged write census (writes_total vs writes_from this
        # writer_id since the watch baseline)
        self._delta_logs: Dict[str, Any] = {}
        self._delta_baseline: Dict[str, Dict[int, tuple]] = {}
        self._delta_lock = threading.Lock()

    def _token(self) -> List[Any]:
        return [self.writer_id, next(self._wseq)]

    def _dnote(self, space: str, *keys) -> None:
        """Record dirty identity keys on the space's delta log (no-op
        unless a device snapshot is watching)."""
        log = self._delta_logs.get(space)
        if log is None:
            return
        with self._delta_lock:
            for k in keys:
                log.note(k)

    def _dbreak(self, space: str) -> None:
        log = self._delta_logs.get(space)
        if log is not None:
            log.note_break()

    def _note_applied(self, space: str, pid: int, reply: Any):
        """Record a write ack's applied index as the part's
        read-your-writes floor (and its post-apply store epoch on the
        delta log, when one is watching — the group-commit ack path
        that keeps the device delta plane's freshness accounting
        current without extra RPCs)."""
        if not isinstance(reply, dict):
            return
        log = self._delta_logs.get(space)
        if log is not None and reply.get("epoch"):
            with self._delta_lock:
                log.note_epoch(pid, int(reply["epoch"]))
        if self.on_epoch_ack is not None and reply.get("epoch"):
            # cluster cache epochs (ISSUE 20): the ack's store epoch
            # folds into the engine's epoch vector immediately — the
            # WRITING coordinator's caches turn over at ack latency,
            # not heartbeat latency
            self.on_epoch_ack(space, reply["epoch"])
        idx = int(reply.get("applied") or 0)
        if idx <= 0:
            return
        key = (space, pid)
        with self._floor_lock:
            if idx > self._applied_floor.get(key, 0):
                self._applied_floor[key] = idx

    def _read_params(self, space: str, pid: int) -> Dict[str, Any]:
        """Per-part consistency params for one read call: the effective
        level (thread-local override, else the read_consistency flag)
        plus this part's read-your-writes floor for the non-leader
        levels.  Empty for `leader` — byte-identical wire frames to the
        pre-ISSUE-11 client on the default path."""
        lvl = _consistency.effective_consistency()
        if lvl == _consistency.LEADER:
            return {}
        p: Dict[str, Any] = {"consistency": lvl}
        with self._floor_lock:
            floor = self._applied_floor.get((space, pid), 0)
        if floor:
            p["min_applied"] = floor
        return p

    @property
    def catalog(self):
        return self._catalog_proxy

    # ---- space lifecycle (DDL via metad) ----
    def create_space(self, name: str, **kw):
        self.meta.create_space(name, **kw)
        return self.catalog.get_space(name)

    def drop_space(self, name: str, if_exists=False):
        self._dbreak(name)
        self.meta.drop_space(name, if_exists=if_exists)
        # floors are keyed by space NAME: a dropped-and-recreated space
        # starts a fresh raft log, so stale floors would make its first
        # follower/bounded_stale reads wait for (or reject against) an
        # applied index the new group won't reach for a long time
        with self._floor_lock:
            for key in [k for k in self._applied_floor if k[0] == name]:
                del self._applied_floor[key]

    def clear_space(self, name: str, if_exists=False):
        """CLEAR SPACE across the cluster: one raft-replicated
        clear_part per partition (data gone on every replica), schema
        untouched."""
        from ..graphstore.schema import SchemaError
        try:
            self.catalog.get_space(name)
        except SchemaError:
            if if_exists:
                return
            raise
        self._dbreak(name)
        for pid in range(len(self.meta.parts_of(name))):
            self._write(name, pid, ("clear_part", pid))

    def space(self, name: str):
        """Minimal space info for executors (partition count, epoch)."""
        return _SpaceView(self, name)

    # ---- mutate ----
    def _write(self, space: str, pid: int, *cmds):
        # cat_ver: the issuer's catalog version rides along so a
        # storaged whose heartbeat-refreshed cache lags a just-issued
        # DDL refreshes BEFORE applying — otherwise a write landing in
        # the lag window applies without the new index/fulltext/TTL
        # schema state (silently missing derived entries)
        # the token is minted ONCE per logical request: replica-walk
        # retries re-send the same (writer_id, seq), which is what the
        # dedup window keys on
        r = self.sc._call_part(space, pid, "storage.write",
                               {"cmds": [to_wire(list(c)) for c in cmds],
                                "cat_ver": self.meta.version,
                                "token": self._token()})
        self._note_applied(space, pid, r)

    def _write_many(self, space: str, by_part: Dict[int, List[tuple]]):
        """One rpc_write per part — each part's command list becomes ONE
        batched raft proposal (group commit) — with parts fanned out in
        parallel over the StorageClient pool."""
        if not by_part:
            return
        if len(by_part) == 1:
            pid, cmds = next(iter(by_part.items()))
            self._write(space, pid, *cmds)
            return
        for pid, r in self.sc.fanout(
                space,
                {pid: {"cmds": [to_wire(list(c)) for c in cmds],
                       "cat_ver": self.meta.version,
                       "token": self._token()}
                 for pid, cmds in by_part.items()},
                "storage.write"):
            self._note_applied(space, pid, r)

    def insert_vertex(self, space: str, vid: Any, tag: str,
                      props: Dict[str, Any],
                      insert_names: Optional[List[str]] = None):
        self.insert_vertices(space, [(vid, tag, props, insert_names)])

    def insert_vertices(self, space: str,
                        rows: List[tuple]):
        """Batched INSERT VERTEX (ISSUE 3): rows is
        [(vid, tag, props, insert_names)].  The statement's writes are
        buffered per partition and shipped as ONE rpc_write per part
        (one batched raft proposal each), parts in parallel — instead
        of one RPC + one consensus round per row.  Per-vid write order
        is preserved: a vid always hashes to the same part, and order
        within a part's command list is the input order."""
        by_part: Dict[int, List[tuple]] = {}
        desc = self.catalog.get_space(space)
        for vid, tag, props, insert_names in rows:
            desc.check_vid(vid)
            sv = self.catalog.get_tag(space, tag).latest
            row = apply_defaults(sv, props, insert_names)
            by_part.setdefault(self.sc.part_of(space, vid), []).append(
                ("vertex", vid, tag, sv.version, row))
        self._dnote(space, *(("v", r[0]) for r in rows))
        self._write_many(space, by_part)

    def _chain_write(self, space: str, src: Any, dst: Any,
                     out_cmd: tuple, in_cmd: list):
        """TOSS chain with resume bookkeeping: the out-half part logs the
        in-half it owes before anything is applied; if this graphd dies
        mid-chain, the out-half leader's resume loop re-drives the
        in-half (storage_service._resume_chains).  In-half apply is
        idempotent, so the happy path completing the chain itself races
        safely with the janitor."""
        import time as _t
        import uuid
        cid = uuid.uuid4().hex
        src_pid = self.sc.part_of(space, src)
        dst_pid = self.sc.part_of(space, dst)
        # mark + out-half ride ONE raft entry: the journal must never
        # commit without the out-half it promises to mirror
        mark = ["chain_mark", src_pid, cid, dst_pid, in_cmd, _t.time()]
        fail.hit("toss:pre_out")
        self._write(space, src_pid, ("batch", [mark, list(out_cmd)]))
        # the torn-chain window: a crash here leaves the journal + out-
        # half committed with the in-half owed — the resume janitor's job
        fail.hit("toss:pre_in")
        self._write(space, dst_pid, tuple(in_cmd))
        fail.hit("toss:pre_done")
        self._write(space, src_pid, ("chain_done", src_pid, cid))

    def insert_edge(self, space: str, src: Any, etype: str, dst: Any,
                    rank: int, props: Dict[str, Any],
                    insert_names: Optional[List[str]] = None):
        self.insert_edges(space, etype, [(src, dst, rank, props)],
                          insert_names)

    def insert_edges(self, space: str, etype: str, rows: List[tuple],
                     insert_names: Optional[List[str]] = None):
        """Batched INSERT EDGE with coalesced TOSS chains (ISSUE 3):
        rows is [(src, dst, rank, props)].  Edges are grouped by
        (src_pid, dst_pid); each pair pays ONE chain — one raft entry
        with the chain mark + every out-half of the pair, one batched
        in-half command to the dst part, one chain_done — instead of a
        3-write chain per edge.  Each phase fans its parts out in
        parallel, and every per-part command list rides one batched
        proposal (group commit at the raft layer).

        Invariants preserved: the journal (chain_mark) commits in the
        SAME raft entry as the out-halves it promises to mirror; the
        in-half batch is idempotent per edge (same-row overwrite), so
        the resume janitor re-driving it converges; per-(src,dst)
        write order is input order (same pair → same group, ordered)."""
        import time as _t
        import uuid
        desc = self.catalog.get_space(space)
        sv = self.catalog.get_edge(space, etype).latest
        # (src_pid, dst_pid) → ([out-half cmds], [in-half cmds])
        groups: Dict[tuple, tuple] = {}
        n = 0
        for src, dst, rank, props in rows:
            desc.check_vid(src)
            desc.check_vid(dst)
            row = apply_defaults(sv, props, insert_names)
            key = (self.sc.part_of(space, src), self.sc.part_of(space, dst))
            outs, ins = groups.setdefault(key, ([], []))
            outs.append(["edge_half", src, etype, dst, rank, row, "out"])
            ins.append(["edge_half", src, etype, dst, rank, row, "in"])
            n += 1
        if not groups:
            return
        if n > len(groups):
            from ..utils.stats import stats as _stats
            _stats().inc("toss_chains_coalesced", n - len(groups))
        ts = _t.time()
        by_src: Dict[int, List[tuple]] = {}
        by_dst: Dict[int, List[tuple]] = {}
        dones: Dict[int, List[tuple]] = {}
        for (src_pid, dst_pid), (outs, ins) in groups.items():
            cid = uuid.uuid4().hex
            in_cmd = ["batch", ins] if len(ins) > 1 else ins[0]
            mark = ["chain_mark", src_pid, cid, dst_pid, in_cmd, ts]
            # mark + ALL the pair's out-halves ride ONE raft entry: the
            # journal must never commit without the out-halves it
            # promises to mirror (and vice versa)
            by_src.setdefault(src_pid, []).append(("batch", [mark] + outs))
            by_dst.setdefault(dst_pid, []).append(tuple(in_cmd))
            dones.setdefault(src_pid, []).append(
                ("chain_done", src_pid, cid))
        self._dnote(space, *(("e", etype, src, dst, rank)
                             for src, dst, rank, _props in rows))
        # out-halves (with journals) first — the source of truth — then
        # the in-halves, then the retirements.  The failpoints bracket
        # the two crash windows a batched TOSS chain has: after the
        # journaled out-halves (janitor re-drives the in-halves) and
        # after the in-halves (janitor retires stale journals)
        fail.hit("toss:pre_out")
        self._write_many(space, by_src)
        fail.hit("toss:pre_in")
        self._write_many(space, by_dst)
        fail.hit("toss:pre_done")
        self._write_many(space, dones)

    def delete_vertex(self, space: str, vid: Any, with_edges: bool = True):
        if with_edges:
            # collect both planes, then delete mirror halves on peer parts
            for (s, et, rank, other, _props, sd) in self.get_neighbors(
                    space, [vid], None, "both"):
                if sd > 0:      # out-edge vid→other; mirror in-half at other
                    self._dnote(space, ("e", et, vid, other, rank))
                    self._write(space, self.sc.part_of(space, other),
                                ("del_edge_half", vid, et, other, rank, "in"))
                else:           # in-edge other→vid; mirror out-half at other
                    self._dnote(space, ("e", et, other, vid, rank))
                    self._write(space, self.sc.part_of(space, other),
                                ("del_edge_half", other, et, vid, rank,
                                 "out"))
        self._dnote(space, ("v", vid))
        self._write(space, self.sc.part_of(space, vid), ("del_vertex", vid))

    def delete_tag(self, space: str, vid: Any, tags: List[str]):
        self._dnote(space, ("v", vid))
        self._write(space, self.sc.part_of(space, vid),
                    ("del_tag", vid, tags))

    def delete_edge(self, space: str, src: Any, etype: str, dst: Any,
                    rank: int):
        self._dnote(space, ("e", etype, src, dst, rank))
        self._chain_write(space, src, dst,
                          ("del_edge_half", src, etype, dst, rank, "out"),
                          ["del_edge_half", src, etype, dst, rank, "in"])

    def update_vertex(self, space: str, vid: Any, tag: str,
                      updates: Dict[str, Any]) -> bool:
        sv = self.catalog.get_tag(space, tag).latest
        for k in updates:
            if sv.prop(k) is None:
                raise SchemaError(f"unknown prop `{k}'")
        tv = self.get_vertex(space, vid)
        if tv is None or tag not in tv:
            return False
        self._dnote(space, ("v", vid))
        self._write(space, self.sc.part_of(space, vid),
                    ("upd_vertex", vid, tag, updates))
        return True

    def update_edge(self, space: str, src: Any, etype: str, dst: Any,
                    rank: int, updates: Dict[str, Any]) -> bool:
        sv = self.catalog.get_edge(space, etype).latest
        for k in updates:
            if sv.prop(k) is None:
                raise SchemaError(f"unknown prop `{k}'")
        if self.get_edge(space, src, etype, dst, rank) is None:
            return False
        self._dnote(space, ("e", etype, src, dst, rank))
        self._chain_write(
            space, src, dst,
            ("upd_edge_half", src, etype, dst, rank, updates, "out"),
            ["upd_edge_half", src, etype, dst, rank, updates, "in"])
        return True

    # ---- read ----
    # Rows are fill_row'd against THIS client's catalog too: the serving
    # storaged's cache may predate an ALTER ... ADD by one heartbeat,
    # while the DDL issuer's catalog is refreshed synchronously — the
    # reader's schema wins (read-side versioned-row upgrade, SURVEY §2
    # row 9).  Schema versions resolve ONCE per call (_sv_maps), and a
    # tag/edge the reader's catalog no longer lists is INVISIBLE — the
    # host path's dropped-schema semantics.

    def _sv_maps(self, space):
        """-> ({tag: latest}, {etype: latest}) for one read call."""
        tags = {t.name: t.latest for t in self.catalog.tags(space)}
        edges = {e.name: e.latest for e in self.catalog.edges(space)}
        return tags, edges

    def get_vertex(self, space: str, vid: Any):
        pid = self.sc.part_of(space, vid)
        r = self.sc._call_part(space, pid, "storage.get_vertex",
                               {"vid": to_wire(vid),
                                **self._read_params(space, pid)})
        if r is None:
            return None
        tag_svs, _ = self._sv_maps(space)
        out = {t: fill_row(tag_svs[t],
                           {k: from_wire(v) for k, v in row.items()})
               for t, row in r.items() if t in tag_svs}
        return out or None

    def get_edge(self, space: str, src: Any, etype: str, dst: Any,
                 rank: int = 0):
        pid = self.sc.part_of(space, src)
        r = self.sc._call_part(space, pid, "storage.get_edge",
                               {"src": to_wire(src), "etype": etype,
                                "dst": to_wire(dst), "rank": rank,
                                **self._read_params(space, pid)})
        if r is None:
            return None
        try:
            sv = self.catalog.get_edge(space, etype).latest
        except SchemaError:
            return None          # edge type dropped: rows invisible
        return fill_row(sv, {k: from_wire(v) for k, v in r.items()})

    def scan_vertices(self, space: str, tag: Optional[str] = None,
                      parts: Optional[Iterable[int]] = None):
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        tag_svs, _ = self._sv_maps(space)
        for pid, rows in self.sc.fanout(
                space, {p: {"tag": tag, **self._read_params(space, p)}
                        for p in pids},
                "storage.scan_vertices"):
            for vid, t, row in rows:
                sv = tag_svs.get(t)
                if sv is None:
                    continue     # tag dropped: rows invisible
                yield from_wire(vid), t, fill_row(
                    sv, {k: from_wire(v) for k, v in row.items()})

    def scan_edges(self, space: str, etype: Optional[str] = None,
                   parts: Optional[Iterable[int]] = None):
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        _, edge_svs = self._sv_maps(space)
        for pid, rows in self.sc.fanout(
                space, {p: {"etype": etype, **self._read_params(space, p)}
                        for p in pids},
                "storage.scan_edges"):
            for src, et, rank, dst, row in rows:
                sv = edge_svs.get(et)
                if sv is None:
                    continue     # edge type dropped: rows invisible
                yield from_wire(src), et, rank, from_wire(dst), \
                    fill_row(sv, {k: from_wire(v) for k, v in row.items()})

    def get_neighbors(self, space: str, vids: List[Any],
                      edge_types: Optional[List[str]] = None,
                      direction: str = "out",
                      edge_filter=None, limit_per_src: Optional[int] = None):
        """Same contract as GraphStore.get_neighbors, including row order
        (input vid order, etype name, then (rank, neighbor)).  A pushed
        edge_filter / limit ships to storaged as nGQL text and executes
        there — only surviving rows cross the RPC (SURVEY §2 row 12)."""
        from .pushdown import filter_to_wire
        _, edge_svs = self._sv_maps(space)
        ftext = filter_to_wire(edge_filter)
        by_part = self.sc.split_by_part(space, vids)
        results = dict(self.sc.fanout(
            space,
            {pid: {"vids": to_wire(pvids), "edge_types": edge_types,
                   "direction": direction, "filter": ftext,
                   "limit_per_src": limit_per_src,
                   **self._read_params(space, pid)}
             for pid, pvids in by_part.items()},
            "storage.get_neighbors"))
        # merge preserving input vid order: index rows per (vid, dir)
        from ..utils.stats import current_work
        wc = current_work()
        if wc is not None:
            # edges shipped over the wire = edges this hop examined
            # post-pushdown: the cluster host path's deterministic
            # edges-traversed work count
            n_rows = sum(rows["n"] if isinstance(rows, dict)
                         else len(rows) for rows in results.values())
            wc.add("edges_traversed", n_rows)
            wc.add("storage_rows", n_rows)
        per_vid: Dict[Any, List] = {}
        for pid, rows in results.items():
            if isinstance(rows, dict):
                # columnar reply (ISSUE 2): typed blobs decode straight
                # to numpy and materialize with C-level tolist()s — no
                # per-cell from_wire, no per-row fill_row
                for row in _decode_neighbors_columnar(rows, edge_svs):
                    per_vid.setdefault(repr(row[0]), []).append(row)
                continue
            for (src, et, rank, other, props, sd) in rows:
                src_v = from_wire(src)
                sv = edge_svs.get(et)
                if sv is None:
                    continue     # edge type dropped: rows invisible
                per_vid.setdefault(repr(src_v), []).append(
                    (src_v, et, rank, from_wire(other),
                     fill_row(sv, {k: from_wire(v)
                                   for k, v in props.items()}), sd))
        for vid in vids:
            for row in per_vid.get(repr(vid), []):
                yield row

    def index_scan(self, space: str, index_name: str, eq_prefix: List[Any],
                   range_hint=None, parts: Optional[List[int]] = None):
        from ..graphstore.index import _Sentinel
        rng = None
        if range_hint is not None:
            # open bounds ride as JSON null — a real bound can't be None
            # (null predicates are rejected at hint extraction)
            lo, hi, li, hi_inc = range_hint
            lo = None if isinstance(lo, _Sentinel) else to_wire(lo)
            hi = None if isinstance(hi, _Sentinel) else to_wire(hi)
            rng = [lo, hi, li, hi_inc]
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        out: List[Any] = []
        for pid, ents in self.sc.fanout(
                space, {p: {"index": index_name, "eq": to_wire(eq_prefix),
                            "range": rng,
                            **self._read_params(space, p)} for p in pids},
                "storage.index_scan"):
            for e in ents:
                v = from_wire(e)
                out.append(tuple(v) if isinstance(v, list) else v)
        return out

    def index_scan_geo(self, space: str, index_name: str,
                       ranges: List[tuple],
                       parts: Optional[List[int]] = None):
        """Geo token-range scan fan-out; ranges are plain int pairs
        (wire-safe as JSON lists)."""
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        out: List[Any] = []
        for pid, ents in self.sc.fanout(
                space, {p: {"index": index_name,
                            "ranges": [list(r) for r in ranges],
                            **self._read_params(space, p)}
                        for p in pids},
                "storage.index_scan_geo"):
            for e in ents:
                v = from_wire(e)
                out.append(tuple(v) if isinstance(v, list) else v)
        return out

    def rebuild_index(self, space: str, index_name: str,
                      parts: Optional[List[int]] = None) -> int:
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        total = 0
        # cat_ver: the issuer validated the index against ITS catalog —
        # a storaged with an older cache must refresh before the rebuild
        # or apply fails "index not found" (same contract as writes)
        for pid, n in self.sc.fanout(
                space, {p: {"index": index_name,
                            "cat_ver": self.meta.version} for p in pids},
                "storage.rebuild_index"):
            total += n
        return total

    def _ft_want_id(self, space: str, index_name: str) -> int:
        """This client's (DDL-fresh) view of the index generation —
        shipped with the RPC so a storaged whose catalog cache predates a
        DROP+re-CREATE refreshes instead of serving the old incarnation."""
        d = next((x for x in self.catalog.fulltext_indexes(space)
                  if x.name == index_name), None)
        return d.index_id if d is not None else -1

    def fulltext_search(self, space: str, index_name: str, op: str,
                        pattern: str,
                        parts: Optional[List[int]] = None) -> List[Any]:
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        want = self._ft_want_id(space, index_name)
        out: List[Any] = []
        for pid, ents in self.sc.fanout(
                space, {p: {"index": index_name, "op": op,
                            "pattern": pattern, "want_id": want,
                            **self._read_params(space, p)}
                        for p in pids},
                "storage.fulltext_search"):
            for e in ents:
                v = from_wire(e)
                out.append(tuple(v) if isinstance(v, list) else v)
        return out

    def rebuild_fulltext_index(self, space: str, index_name: str,
                               parts: Optional[List[int]] = None) -> int:
        pids = list(parts) if parts is not None else self.sc.all_parts(space)
        want = self._ft_want_id(space, index_name)
        return sum(n for _, n in self.sc.fanout(
            space, {p: {"index": index_name, "want_id": want}
                    for p in pids},
            "storage.rebuild_fulltext"))

    # ---- device delta feed (ISSUE 19): dirty-key log over the write
    # census.  The log alone can only vouch for writes THROUGH THIS
    # STORE; coverage against other writers is proven per part by the
    # storaged census — (writes_total − baseline) must equal
    # (writes_from_me − baseline), else the keys are incomplete and
    # the runtime full-rebuilds. ----

    def _census_probe(self, space: str) -> Dict[int, tuple]:
        """Per-part (epoch, writes_total, writes_from_me) fan-out."""
        pids = self.sc.all_parts(space)
        per = dict(self.sc.fanout(
            space, {p: {"writer": self.writer_id} for p in pids},
            "storage.part_stats"))
        return {pid: (int(r.get("epoch", 0)),
                      int(r.get("writes_total", 0)),
                      int(r.get("writes_from", 0)))
                for pid, r in per.items()}

    def delta_watch(self, space: str, cap: int = 65536) -> int:
        from ..graphstore.delta import DeltaLog
        probe = self._census_probe(space)
        epoch = max((e for e, _t, _m in probe.values()), default=0)
        with self._delta_lock:
            log = self._delta_logs.get(space)
            if log is None or log.broken:
                # an unbroken log keeps watching across re-watches
                # (compaction rebuilds must not reset the floor or the
                # census baseline out from under the serving snapshot)
                self._delta_logs[space] = DeltaLog(floor_epoch=epoch,
                                                  cap=cap)
                self._delta_baseline[space] = {
                    pid: (t, m) for pid, (_e, t, m) in probe.items()}
        return epoch

    def delta_records(self, space: str):
        """-> (keys, target_epoch, floor_epoch), or None when the log
        cannot vouch for completeness (never watched / broken / census
        shows a foreign writer) — the caller full-rebuilds."""
        log = self._delta_logs.get(space)
        if log is None:
            return None
        try:
            probe = self._census_probe(space)
        except Exception:  # noqa: BLE001 — RPC trouble: rebuild decides
            return None
        base = self._delta_baseline.get(space) or {}
        covered = set(probe) == set(base)
        if covered:
            for pid, (_e, t, m) in probe.items():
                t0, m0 = base[pid]
                if t < t0 or m < m0 or (t - t0) != (m - m0):
                    covered = False     # foreign writes (or failover
                    break               # census reset): keys incomplete
        with self._delta_lock:
            if log.broken:
                return None
            if not covered:
                log.note_break()
                return None
            # keys snapshot AFTER the census probe: a write of ours
            # landing in between adds a key (superset-safe) but not its
            # epoch — applied_epoch lands below sd.epoch and the next
            # pin probe catches up; a FOREIGN write in the window bumps
            # the epoch past target, so the next probe re-runs this
            # census and breaks.  Either way no stale read is served.
            keys = list(log.keys)
            floor = log.floor_epoch
        target = max((e for e, _t, _m in probe.values()), default=0)
        return keys, target, floor

    def delta_trim(self, space: str, keys) -> None:
        with self._delta_lock:
            log = self._delta_logs.get(space)
            if log is not None:
                log.trim(keys)

    def delta_reader(self, space: str):
        return _ClusterDeltaReader(self, space)

    # ---- device plane: bulk CSR export (the north-star storage
    # addition; SURVEY §2 row 12 + BASELINE.json) ----

    def build_csr_snapshot(self, space: str):
        """Assemble a CsrSnapshot for the WHOLE space from per-part
        `storage.export_part` bulk exports — the cluster analog of
        build_snapshot over a local SpaceData.  The graphd's TpuRuntime
        pins the result; writes bump part epochs, and the runtime's
        epoch probe triggers a re-export (epoch-based re-pin, SURVEY
        §5).

        Per-part exports are taken under each leader's lock but NOT
        atomically across parts — the same read consistency as the
        reference's per-partition storage reads."""
        from ..graphstore.csr import build_snapshot
        from ..graphstore.store import SpaceData

        desc = self.catalog.get_space(space)
        sd = SpaceData(desc)
        # epoch BEFORE the export: a write racing the per-part fan-out
        # bumps some leader's epoch past this value, so the runtime's
        # next probe re-exports (stamping the post-export epoch instead
        # would let a snapshot claim data it missed, forever)
        epoch_before = self.stats(space)["epoch"]
        pids = self.sc.all_parts(space)
        for pid, payload in self.sc.fanout(
                space, {p: {} for p in pids}, "storage.export_part"):
            st = from_wire(payload)
            p = sd.parts[pid]
            p.vertices = st["vertices"]
            p.out_edges = st["out_edges"]
            p.in_edges = st["in_edges"]
            sd.part_counts[pid] = st["part_count"]
            sd.install_dense(st["dense"])
        sd.epoch = epoch_before

        class _Shim:
            """Duck-typed store for build_snapshot: catalog + one space."""

            def __init__(self, catalog, sdata):
                self.catalog = catalog
                self._sd = sdata

            def space(self, _name):
                return self._sd

        from ..utils.config import get_config
        dflag = int(get_config().get("tpu_delta_max_edges") or 0)
        snap = build_snapshot(
            _Shim(self.meta.catalog, sd), space,
            vmax_extra=(int(get_config().get("tpu_delta_vmax_slack"))
                        if dflag > 0 else 0))
        # the space view serves dense-id lookups from this export (the
        # device data plane's vid dictionary); part_counts ride along so
        # the delta reader can mint dense ids for post-export vids
        self._dense_cache[space] = (sd.epoch, sd.vid_to_dense,
                                    sd.dense_to_vid, sd.part_counts)
        return snap

    def stats_detail(self, space: str) -> Dict[str, Dict[str, int]]:
        """Per-tag / per-edge-type counts aggregated over part leaders
        (SHOW STATS per-schema rows)."""
        pids = self.sc.all_parts(space)
        tags: Dict[str, int] = {}
        edges: Dict[str, int] = {}
        vertices = 0
        for pid, r in self.sc.fanout(
                space, {p: {"detail": True} for p in pids},
                "storage.part_stats"):
            d = r.get("detail") or {}
            vertices += d.get("vertices", 0)
            for t, n in (d.get("tags") or {}).items():
                tags[t] = tags.get(t, 0) + n
            for et, n in (d.get("edges") or {}).items():
                edges[et] = edges.get(et, 0) + n
        return {"tags": tags, "edges": edges, "vertices": vertices,
                "total_edges": sum(edges.values())}

    def stats(self, space: str) -> Dict[str, Any]:
        pids = self.sc.all_parts(space)
        per = dict(self.sc.fanout(space, {p: {} for p in pids},
                                  "storage.part_stats"))
        return {
            "space": space,
            "partition_num": len(pids),
            "vertices": sum(r["vertices"] for r in per.values()),
            "edges": sum(r["edges"] for r in per.values()),
            "epoch": max((r["epoch"] for r in per.values()), default=0),
            "per_part_edges": [per[p]["edges"] for p in pids],
        }


class _SpaceView:
    """Duck-typed SpaceData stand-in for the few executor uses."""

    def __init__(self, ds: DistributedStore, name: str):
        self._ds = ds
        self.name = name
        self.desc = ds.catalog.get_space(name)

    @property
    def num_parts(self) -> int:
        return len(self._ds.meta.parts_of(self.name))

    def part_of(self, vid: Any) -> int:
        return stable_vid_hash(vid) % self.num_parts

    @property
    def epoch(self) -> int:
        return self._ds.stats(self.name)["epoch"]

    # -- device-plane vid dictionary (filled by build_csr_snapshot; the
    # runtime always pins BEFORE resolving seeds, so queries see the
    # mapping of the snapshot they execute against) --

    def dense_id(self, vid: Any, create: bool = False) -> int:
        cache = self._ds._dense_cache.get(self.name)
        if cache is None:
            return -1
        return cache[1].get(vid, -1)

    def vid_of_dense(self, dense: int) -> Any:
        cache = self._ds._dense_cache.get(self.name)
        if cache is None:
            return None
        d2v = cache[2]
        if 0 <= dense < len(d2v):
            return d2v[dense]
        return None


class _ClusterDeltaReader:
    """Re-read adapter over the cluster for HostDelta.apply: identity
    keys resolve through leader-consistency point reads (get_vertex /
    get_edge RPCs), so the mirror folds in exactly the committed state.

    Dense ids come from the last CSR export's dictionary; a vid minted
    since then gets the next local row of its part — self-consistent
    within the pinned snapshot, which is all the mirror needs (the next
    full rebuild re-derives the authoritative mapping).  A mint for a
    phantom key (edge inserted and deleted between applies) wastes one
    vmax-slack row at worst; overflow degrades to a rebuild."""

    def __init__(self, ds: DistributedStore, space: str):
        cache = ds._dense_cache.get(space)
        if cache is None or len(cache) < 4:
            from ..graphstore.delta import DeltaUnsupported
            raise DeltaUnsupported("no CSR export to map dense ids from")
        self.ds = ds
        self.space = space
        self._v2d = cache[1]
        self._d2v = cache[2]
        self._counts = cache[3]
        self._P = len(ds.meta.parts_of(space))

    def dense_of(self, vid) -> Optional[int]:
        d = self._v2d.get(vid)
        if d is not None:
            return int(d)
        p = stable_vid_hash(vid) % self._P
        d = self._counts[p] * self._P + p
        self._counts[p] += 1
        self._v2d[vid] = d
        need = d + 1 - len(self._d2v)
        if need > 0:
            self._d2v.extend([None] * need)
        self._d2v[d] = vid
        return d

    def edge_row(self, etype, src, dst, rank):
        try:
            sv = self.ds.catalog.get_edge(self.space, etype).latest
        except SchemaError:
            return None, None           # dropped edge type: invisible
        row = self.ds.get_edge(self.space, src, etype, dst, rank)
        return row, sv

    def vertex_rows(self, vid) -> Dict[str, Dict[str, Any]]:
        return self.ds.get_vertex(self.space, vid) or {}

    def tag_schema(self, tag):
        try:
            return self.ds.catalog.get_tag(self.space, tag).latest
        except SchemaError:
            return None
