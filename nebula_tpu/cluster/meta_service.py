"""Meta service — the cluster brain (metad).

Catalog DDL, space/partition map with host placement, host liveness from
heartbeats, session registry, dynamic config, cluster jobs.  Analog of
the reference's src/meta processors + JobManager + ActiveHostsMan
[UNVERIFIED — empty mount, SURVEY §0], with one TPU-build twist: the
part map doubles as the CHIP PLACEMENT map (partition → mesh slot) that
the device plane pins from (SURVEY §2 row 17).

State mutations ride a Raft group over the metad peers ("meta" group).
Commands, snapshots, and client-supplied DDL blobs are JSON wire
payloads (graphstore/schema_wire.py) — never pickle: anything that can
reach an RPC port could otherwise execute arbitrary code on unpickle.
Every non-deterministic input (host placement, timestamps) is resolved
by the leader BEFORE propose and embedded in the command, so replica
replay is deterministic.

Liveness (ActiveHostsMan) is deliberately NOT replicated: each metad
tracks heartbeat arrival times in memory, like the reference.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..graphstore import schema_wire
from ..graphstore.schema import Catalog, SchemaError
from .raft import RaftPart, RaftTransport
from .repair import PartSupervisor
from .rpc import RpcError, RpcServer

HB_EXPIRE_S = 10.0


def _hb_expire_s() -> float:
    """Liveness horizon — flag-tunable so failover tests don't wait 10s
    of wall clock for a killed host to read as dead."""
    try:
        from ..utils.config import get_config
        return float(get_config().get("host_hb_expire_secs"))
    except Exception:  # noqa: BLE001 — config not initialized
        return HB_EXPIRE_S

# catalog methods a DDL command may invoke on replicas
_CATALOG_METHODS = frozenset({
    "create_tag", "create_edge", "alter_tag", "alter_edge",
    "drop_tag", "drop_edge", "create_index", "drop_index",
    "create_fulltext_index", "drop_fulltext_index",
    "add_listener", "remove_listener",
    "create_user_hashed", "set_password_hash", "change_password_hashed",
    "drop_user", "grant_role", "revoke_role"})


def _translate_cred_cmd(cmd):
    """Rewrite legacy plaintext credential DDL to the hashed form —
    applied BEFORE propose (so new raft entries never carry plaintext)
    and again at apply time (so WAL entries written by older builds
    still replay instead of silently dropping accounts)."""
    from ..graphstore.schema import hash_password
    m = cmd.get("method")
    if m not in ("create_user", "alter_user", "change_password"):
        return cmd
    args = list(cmd.get("args", ()))
    kw = dict(cmd.get("kw", {}))
    out = dict(cmd)
    if m == "create_user":
        ine = kw.pop("if_not_exists", False) or             (len(args) > 2 and bool(args[2]))
        out.update(method="create_user_hashed",
                   args=[args[0], hash_password(args[1])],
                   kw={"if_not_exists": ine})
    elif m == "alter_user":
        out.update(method="set_password_hash",
                   args=[args[0], hash_password(args[1])], kw={})
    else:
        out.update(method="change_password_hashed",
                   args=[args[0], hash_password(args[1]),
                         hash_password(args[2])], kw={})
    return out


def _pk(obj) -> str:
    """JSON-encode a schema/command payload for an RPC string field."""
    return json.dumps(schema_wire.to_jso(obj), separators=(",", ":"))


def _unpk(s: str):
    return schema_wire.from_jso(json.loads(s))


class MetaState:
    """The replicated state machine (deterministic apply)."""

    def __init__(self):
        self.catalog = Catalog()
        # space name → [ [replica addrs...] per part ]; [0] is the leader
        self.part_map: Dict[str, List[List[str]]] = {}
        # space name → [ [learner addrs...] per part ] — catching-up
        # replicas (ISSUE 14): they ride replication but are invisible
        # to routing (parts_of serves voters only) and to quorum until
        # promote_learner moves them into the part map
        self.learner_map: Dict[str, List[List[str]]] = {}
        # rid → repair plan row (the raft-persisted RepairPlan of the
        # PartSupervisor: phase/status survive metad restarts and
        # leader failovers, so a half-driven repair resumes)
        self.repairs: Dict[int, Dict[str, Any]] = {}
        self.next_repair = 1
        self.sessions: Dict[int, Dict[str, Any]] = {}
        self.next_session = 1
        # bounded tombstones of removed sids (ISSUE 20): KILL SESSION
        # from any coordinator must be idempotent — the second kill
        # (or a kill racing the owner's death/signout) finds the row
        # gone and needs to distinguish "already killed" (quiet
        # success) from "never existed" (error)
        self.removed_sessions: List[int] = []
        self.configs: Dict[str, Any] = {}
        self.jobs: Dict[int, Dict[str, Any]] = {}
        self.next_job = 1
        # zone → member hosts (replica placement isolation, SURVEY §2
        # row 17); hosts outside any zone placement-wise form singleton
        # zones of their own
        self.zones: Dict[str, List[str]] = {}
        # segment ID allocator (the metad ID service): monotonically
        # increasing, raft-replicated, never reused
        self.next_alloc_id = 1
        self.version = 0

    def snapshot(self) -> bytes:
        return schema_wire.dumps(dict(self.__dict__))

    def restore(self, data: bytes):
        self.__dict__.update(schema_wire.loads(data))

    def apply(self, cmd: Dict[str, Any]):
        op = cmd["op"]
        if op == "catalog":
            cmd = _translate_cred_cmd(cmd)
            if cmd["method"] not in _CATALOG_METHODS:
                raise RpcError(f"bad catalog method {cmd['method']!r}")
            out = getattr(self.catalog, cmd["method"])(
                *cmd.get("args", ()), **cmd.get("kw", {}))
            out = None          # schema objects don't cross the wire here
        else:
            out = getattr(self, "_ap_" + op)(cmd)
        self.version += 1
        return out

    def _ap_create_space(self, c):
        sp = self.catalog.create_space(c["name"], **c["kw"])
        self.part_map.setdefault(c["name"], c["assignment"])
        return sp.space_id

    def _ap_drop_space(self, c):
        self.catalog.drop_space(c["name"], if_exists=c["if_exists"])
        self.part_map.pop(c["name"], None)
        self.learner_map.pop(c["name"], None)

    def _ap_create_session(self, c):
        sid = self.next_session
        self.next_session += 1
        self.sessions[sid] = {"user": c["user"], "graphd": c["graphd"],
                              "created": c["ts"], "space": None}
        return sid

    def _ap_update_session(self, c):
        s = self.sessions.get(c["sid"])
        if s:
            s.update(c["fields"])

    def _ap_remove_session(self, c):
        if self.sessions.pop(c["sid"], None) is not None:
            self.removed_sessions.append(c["sid"])
            del self.removed_sessions[:-512]

    def _ap_set_config(self, c):
        self.configs[c["name"]] = c["value"]

    def _ap_add_job(self, c):
        jid = self.next_job
        self.next_job += 1
        self.jobs[jid] = {"cmd": c["cmd"], "space": c.get("space"),
                          "graphd": c.get("graphd", ""),
                          "status": "QUEUE", "ts": c["ts"], "result": None}
        return jid

    def _ap_update_job(self, c):
        j = self.jobs.get(c["jid"])
        if j:
            j.update(c["fields"])

    def _ap_transfer_leader(self, c):
        pm = self.part_map.get(c["space"])
        if pm and 0 <= c["part"] < len(pm):
            replicas = pm[c["part"]]
            if c["to"] in replicas:
                replicas.remove(c["to"])
                replicas.insert(0, c["to"])

    def _ap_add_zone_hosts(self, c):
        z = self.zones.setdefault(c["zone"], [])
        for h in c["hosts"]:
            for other in self.zones.values():
                if h in other:
                    other.remove(h)
            if h not in z:
                z.append(h)

    def _ap_drop_zone(self, c):
        if c["zone"] not in self.zones:
            raise RpcError(f"zone `{c['zone']}' not found")
        self.zones.pop(c["zone"])

    def _ap_merge_zones(self, c):
        """MERGE ZONE a,b INTO z: union the member hosts, drop sources.
        The target may be one of the sources or a new zone."""
        for z in c["zones"]:
            if z not in self.zones:
                raise RpcError(f"zone `{z}' not found")
        members: List[str] = []
        for z in c["zones"]:
            for h in self.zones.pop(z):
                if h not in members:
                    members.append(h)
        tgt = self.zones.setdefault(c["into"], [])
        for h in members:
            if h not in tgt:
                tgt.append(h)

    def _ap_rename_zone(self, c):
        if c["old"] not in self.zones:
            raise RpcError(f"zone `{c['old']}' not found")
        if c["new"] in self.zones:
            raise RpcError(f"zone `{c['new']}' already exists")
        self.zones[c["new"]] = self.zones.pop(c["old"])

    def _ap_divide_zone(self, c):
        """DIVIDE ZONE z INTO z1 (hosts) z2 (hosts): the target host
        lists must partition z's members EXACTLY (reference semantics —
        a divide can neither drop nor import hosts)."""
        zone = c["zone"]
        if zone not in self.zones:
            raise RpcError(f"zone `{zone}' not found")
        parts = [(n, list(hs)) for n, hs in c["parts"]]
        names = [n for n, _ in parts]
        if len(set(names)) != len(names):
            raise RpcError("duplicate target zone name in DIVIDE ZONE")
        if any(not hs for _, hs in parts):
            raise RpcError("DIVIDE ZONE target zones must be non-empty")
        for n in names:
            if n != zone and n in self.zones:
                raise RpcError(f"zone `{n}' already exists")
        claimed: List[str] = []
        for _, hs in parts:
            claimed.extend(hs)
        members = self.zones[zone]
        if sorted(claimed) != sorted(members):
            raise RpcError(
                f"DIVIDE ZONE host lists must partition `{zone}' exactly "
                f"(zone has {sorted(members)}, got {sorted(claimed)})")
        self.zones.pop(zone)
        for n, hs in parts:
            self.zones[n] = list(hs)

    def _ap_drop_hosts(self, c):
        """DROP HOSTS: remove hosts from placement metadata.  Refused
        while any part replica still lives on the host — BALANCE DATA
        REMOVE must drain it first (reference semantics)."""
        for h in c["hosts"]:
            for sp, pm in self.part_map.items():
                for pid, reps in enumerate(pm):
                    if h in reps:
                        raise RpcError(
                            f"host {h} still holds {sp}/part {pid}; "
                            f"run BALANCE DATA REMOVE first")
            for sp, lm in self.learner_map.items():
                for pid, ls in enumerate(lm):
                    if h in ls:
                        raise RpcError(
                            f"host {h} is still a learner of {sp}/part "
                            f"{pid}; wait for the repair to finish")
        for h in c["hosts"]:
            for hs in self.zones.values():
                if h in hs:
                    hs.remove(h)

    def _ap_allocate_ids(self, c):
        start = self.next_alloc_id
        self.next_alloc_id += int(c["count"])
        return start

    def _ap_set_part_replicas(self, c):
        """BALANCE DATA membership step: adopt a new replica list for one
        part.  The orchestrator only ever proposes add-then-remove (one
        side per step), so consecutive configurations share a quorum."""
        pm = self.part_map.get(c["space"])
        if pm is None or not (0 <= c["part"] < len(pm)):
            raise RpcError(f"no part {c['space']}/{c['part']}")
        pm[c["part"]] = list(c["replicas"])
        # a host that became a voter can never linger as a learner
        lm = self.learner_map.get(c["space"])
        if lm and 0 <= c["part"] < len(lm):
            lm[c["part"]] = [l for l in lm[c["part"]]
                             if l not in c["replicas"]]

    def learners_of(self, space: str) -> List[List[str]]:
        """Per-part learner lists, padded to the part count (spaces
        created before the learner plane existed have no entry)."""
        pm = self.part_map.get(space)
        if pm is None:
            return []
        lm = self.learner_map.setdefault(space, [])
        while len(lm) < len(pm):
            lm.append([])
        return lm

    def _ap_set_part_learners(self, c):
        """Membership-change step (ISSUE 14): adopt a new learner list
        for one part.  Learners never affect quorum, so this step is
        always safe to (re)propose — the idempotency anchor of the
        resumable task engine's add phase."""
        pm = self.part_map.get(c["space"])
        if pm is None or not (0 <= c["part"] < len(pm)):
            raise RpcError(f"no part {c['space']}/{c['part']}")
        lm = self.learners_of(c["space"])
        lm[c["part"]] = [l for l in c["learners"]
                         if l not in pm[c["part"]]]

    def _ap_promote_learner(self, c):
        """Promote a caught-up learner to voter as ONE deterministic
        state change: leave the learner list, join the replica list.
        The voter set grows by a member that already holds the log, so
        the old and new configurations share a quorum."""
        pm = self.part_map.get(c["space"])
        if pm is None or not (0 <= c["part"] < len(pm)):
            raise RpcError(f"no part {c['space']}/{c['part']}")
        lm = self.learners_of(c["space"])
        host = c["host"]
        if host not in lm[c["part"]] and host not in pm[c["part"]]:
            raise RpcError(
                f"{host} is not a learner of {c['space']}/{c['part']}")
        lm[c["part"]] = [l for l in lm[c["part"]] if l != host]
        if host not in pm[c["part"]]:
            pm[c["part"]].append(host)

    def _ap_add_repair(self, c):
        rid = self.next_repair
        self.next_repair += 1
        self.repairs[rid] = {
            "space": c["space"], "part": c["part"], "dead": c["dead"],
            "target": c["target"], "phase": c.get("phase", "add_learner"),
            "status": "RUNNING", "created": c["ts"], "updated": c["ts"],
            "error": None}
        return rid

    def _ap_update_repair(self, c):
        r = self.repairs.get(c["rid"])
        if r:
            r.update(c["fields"])


class MetaService:
    """One metad: raft member + RPC surface."""

    def __init__(self, my_addr: str, peers: List[str], data_dir: str,
                 transport: Optional[RaftTransport] = None,
                 server: Optional[RpcServer] = None):
        self.my_addr = my_addr
        self.peers = peers
        self.state = MetaState()
        from ..utils.racecheck import make_lock
        self.state_lock = make_lock("meta_state")
        # addr → {"role", "last_hb" (monotonic), "parts": {space: [pids]}}
        self.active_hosts: Dict[str, Dict[str, Any]] = {}
        # merged cluster epoch vector (ISSUE 20): space → {storaged:
        # [boot, epoch, bump_ts]}.  Leader-local like liveness/heat —
        # deliberately NOT raft-replicated; a fresh leader rebuilds it
        # from the next storaged heartbeat wave, and the graphd-side
        # fold is per-host-boot monotonic so the brief hole can only
        # delay invalidations, never resurrect a retired cache key.
        self.cluster_epochs_tbl: Dict[str, Dict[str, list]] = {}
        self._epochs_lock = threading.Lock()
        # post-election liveness grace (ISSUE 14 satellite): liveness is
        # leader-local, so a FRESH metad leader knows no heartbeats —
        # every host would read dead until they re-arrive.  Until one
        # full heartbeat interval of CONTINUOUS leadership has elapsed,
        # silent hosts are UNKNOWN (not OFFLINE): never declared dead,
        # never repaired against.  (term, leader-since monotonic).
        self._leader_streak: Optional[tuple] = None

        if transport is None:
            from .rpc import RpcRaftTransport
            transport = RpcRaftTransport()
        self.raft = RaftPart(
            "meta", my_addr, peers, transport, data_dir,
            apply_cb=self._apply, snapshot_cb=self._snap,
            restore_cb=self._restore)
        self._apply_result: Dict[int, Any] = {}

        self.server = server
        if server is not None:
            server.service_role = "metad"
            server.register_service(self, prefix="meta.")

        # automatic replica repair (ISSUE 14): scans liveness × part map
        # on the leader, drives raft-persisted RepairPlans
        self.supervisor = PartSupervisor(self)

    # -- raft plumbing ----------------------------------------------------

    def _apply(self, idx: int, data: bytes):
        cmd = schema_wire.loads(data)
        with self.state_lock:
            try:
                self._apply_result[idx] = ("ok", self.state.apply(cmd))
            except Exception as ex:  # noqa: BLE001 — deterministic failure
                self._apply_result[idx] = ("err", str(ex))
            if len(self._apply_result) > 4096:
                for k in sorted(self._apply_result)[:2048]:
                    self._apply_result.pop(k, None)

    def _snap(self) -> bytes:
        with self.state_lock:
            return self.state.snapshot()

    def _restore(self, data: bytes):
        with self.state_lock:
            self.state.restore(data)

    def start(self):
        self.raft.start()
        self.supervisor.start()

    def stop(self):
        self.supervisor.stop()
        self.raft.stop()

    def _propose(self, cmd: Dict[str, Any]):
        if not self.raft.is_leader():
            raise RpcError(f"not leader; leader={self.raft.leader_id or ''}")
        idx = self.raft.propose(schema_wire.dumps(cmd))
        if idx is None:
            # lost leadership mid-propose — redirect like any follower
            raise RpcError(f"not leader; leader={self.raft.leader_id or ''}")
        res = self._apply_result.get(idx)
        if res and res[0] == "err":
            raise RpcError(res[1])
        return res[1] if res else None

    # -- RPC handlers (rpc_* → "meta.*") ----------------------------------

    def rpc_ready(self, p):
        return {"leader": self.raft.is_leader(),
                "leader_hint": self.raft.leader_id}

    def _require_leader(self):
        if not self.raft.is_leader():
            raise RpcError(f"not leader; leader={self.raft.leader_id or ''}")

    def rpc_heartbeat(self, p):
        # liveness must live on the leader — it feeds placement decisions
        # (create_space host assignment); clients follow the hint
        self._require_leader()
        host, role = p["host"], p["role"]
        self.active_hosts[host] = {
            "role": role, "last_hb": time.monotonic(),
            "parts": p.get("parts", {}),
            # webservice addr for metric federation scrapes (ISSUE 8)
            "ws": p.get("ws", ""),
            # per-partition heat rows (ISSUE 16): storaged's PartHeat
            # snapshot rides every heartbeat; rpc_hotspots merges them
            "heat": p.get("heat") or []}
        # fold the host's per-space store epochs into the merged table
        # (ISSUE 20): same boot → max-merge, new boot → replace.  The
        # merged table rides EVERY heartbeat reply (graphd and storaged
        # alike), so cache coherence needs no RPC of its own.
        with self._epochs_lock:
            for space, ent in (p.get("epochs") or {}).items():
                try:
                    boot, epoch = ent[0], int(ent[1])
                except (TypeError, ValueError, IndexError):
                    continue
                hosts = self.cluster_epochs_tbl.setdefault(space, {})
                cur = hosts.get(host)
                if cur is None or cur[0] != boot or epoch > int(cur[1]):
                    hosts[host] = [boot, epoch,
                                   ent[2] if len(ent) > 2 else None]
            merged = {sp: dict(hosts)
                      for sp, hosts in self.cluster_epochs_tbl.items()}
        with self.state_lock:
            return {"version": self.state.version,
                    "leader": self.raft.is_leader(),
                    "epochs": merged}

    def rpc_cluster_epochs(self, p):
        """On-demand merged epoch table — the strict check-at-admission
        leg of ISSUE 20 (leader-consistency cached reads) and tooling."""
        with self._epochs_lock:
            return {"epochs": {sp: dict(hosts) for sp, hosts
                               in self.cluster_epochs_tbl.items()}}

    def _grace_window_s(self) -> float:
        """How long a fresh leader withholds OFFLINE verdicts: one full
        heartbeat interval — every live host has beaten by then."""
        try:
            from ..utils.config import get_config
            return max(float(get_config().get("heartbeat_interval_secs")),
                       0.05)
        except Exception:  # noqa: BLE001 — config not initialized
            return 1.0

    def _liveness_anchor(self) -> Optional[float]:
        """Monotonic instant this metad's liveness view became
        authoritative: leadership start + one grace window.  None while
        not leading.  Before the anchor, a silent host is UNKNOWN; a
        host's dead-clock can never start earlier than the anchor."""
        if not self.raft.is_leader():
            self._leader_streak = None
            return None
        term = self.raft.current_term
        streak = self._leader_streak
        if streak is None or streak[0] != term:
            streak = self._leader_streak = (term, time.monotonic())
        return streak[1] + self._grace_window_s()

    def host_liveness(self) -> Dict[str, Dict[str, Any]]:
        """addr → {role, status ONLINE|UNKNOWN|OFFLINE, parts, ws,
        dead_for}: the union of heartbeating hosts and every host the
        part/learner/zone maps reference — a fresh leader must LIST the
        hosts it has never heard from (as UNKNOWN), not forget them."""
        now = time.monotonic()
        exp = _hb_expire_s()
        anchor = self._liveness_anchor()
        out: Dict[str, Dict[str, Any]] = {}
        # snapshot: concurrent rpc_heartbeat handlers insert keys while
        # the supervisor iterates (dict-changed-size RuntimeError)
        for a, h in list(self.active_hosts.items()):
            out[a] = {"role": h["role"], "parts": h["parts"],
                      "ws": h.get("ws", ""), "last_hb": h["last_hb"]}
        with self.state_lock:
            placed = {r for pm in self.state.part_map.values()
                      for reps in pm for r in reps}
            placed |= {l for lm in self.state.learner_map.values()
                       for ls in lm for l in ls}
            placed |= {h for hs in self.state.zones.values() for h in hs}
        for a in placed:
            out.setdefault(a, {"role": "storage", "parts": {},
                               "ws": "", "last_hb": None})
        for a, h in out.items():
            hb = h.pop("last_hb")
            if hb is not None and now - hb < exp:
                h["status"], h["dead_for"] = "ONLINE", 0.0
                continue
            # silent.  Its dead-clock starts when the heartbeat horizon
            # passed — but never before the liveness anchor (a fresh
            # leader's grace): continuity of death, not of suspicion.
            dead_since = (hb + exp) if hb is not None else None
            if anchor is None:
                # not leading: no authority to call anyone dead
                h["status"], h["dead_for"] = "UNKNOWN", 0.0
                continue
            dead_since = max(dead_since if dead_since is not None
                             else anchor, anchor)
            if now < dead_since:
                h["status"], h["dead_for"] = "UNKNOWN", 0.0
            else:
                h["status"] = "OFFLINE"
                h["dead_for"] = now - dead_since
        return out

    def rpc_list_hosts(self, p):
        # liveness is leader-local: a follower's view is empty/stale,
        # so it redirects the client to the leader like rpc_heartbeat
        # (a fresh leader reports silent hosts as UNKNOWN, never DEAD,
        # until one heartbeat interval of leadership passed — ISSUE 14)
        self._require_leader()
        return [{"addr": a, "role": h["role"],
                 "alive": h["status"] == "ONLINE",
                 "status": h["status"],
                 "parts": h["parts"], "ws": h.get("ws", "")}
                for a, h in sorted(self.host_liveness().items())]

    def rpc_hotspots(self, p):
        """Cluster-wide per-partition heat map (ISSUE 16): merge the
        PartHeat rows the storaged heartbeats carry, rank by load and
        annotate each part with its placement (leader = replicas[0] of
        the part map) — the SHOW HOTSPOTS backend and the read side of
        heat-driven balancing."""
        self._require_leader()
        from ..utils.insights import merge_heat_snapshots
        per_host = {a: h.get("heat") or []
                    for a, h in self.active_hosts.items()
                    if h["role"] == "storage"}
        rows = merge_heat_snapshots(per_host)
        with self.state_lock:
            pm = {sp: [list(r) for r in parts]
                  for sp, parts in self.state.part_map.items()}
        for r in rows:
            reps = pm.get(r["space"], [])
            pid = r["part"]
            r["replicas"] = reps[pid] if pid < len(reps) else []
            r["leader"] = r["replicas"][0] if r["replicas"] else ""
        return rows

    def storage_hosts(self) -> List[str]:
        now = time.monotonic()
        exp = _hb_expire_s()
        return sorted(a for a, h in self.active_hosts.items()
                      if h["role"] == "storage"
                      and now - h["last_hb"] < exp)

    def rpc_create_space(self, p):
        self._require_leader()
        kw = p["kw"]
        partition_num = int(kw.get("partition_num", 8))
        replica = int(kw.get("replica_factor", 1))
        hosts = self.storage_hosts()
        if not hosts:
            raise RpcError("no active storage hosts registered")
        if replica > len(hosts):
            raise RpcError(f"replica_factor {replica} > {len(hosts)} hosts")
        # leader resolves placement; replicas replay it verbatim.  This
        # list IS the chip-placement map for device-pinned spaces.
        # Zone-aware spreading: when zones exist, a part's replicas land
        # in DISTINCT zones (unzoned hosts count as singleton zones), so
        # a zone loss takes at most one replica of any part.
        with self.state_lock:
            zones = {z: [h for h in hs if h in hosts]
                     for z, hs in self.state.zones.items()}
        zoned = {h for hs in zones.values() for h in hs}
        for h in hosts:
            if h not in zoned:
                zones[f"__host_{h}"] = [h]
        zone_names = sorted(z for z, hs in zones.items() if hs)
        if replica > len(zone_names):
            # zone isolation unsatisfiable — fall back to host spreading
            assignment = [[hosts[(pid + r) % len(hosts)]
                           for r in range(replica)]
                          for pid in range(partition_num)]
        else:
            assignment = []
            for pid in range(partition_num):
                reps = []
                for r in range(replica):
                    zn = zones[zone_names[(pid + r) % len(zone_names)]]
                    # decorrelated intra-zone pick: pid % len(zn) would
                    # rotate in lockstep with the zone rotation, starving
                    # some hosts of leaders (reps[0]) entirely
                    reps.append(zn[(pid // len(zone_names)) % len(zn)])
                assignment.append(reps)
        return self._propose({"op": "create_space", "name": p["name"],
                              "kw": kw, "assignment": assignment})

    def rpc_drop_space(self, p):
        return self._propose({"op": "drop_space", "name": p["name"],
                              "if_exists": p.get("if_exists", False)})

    def rpc_ddl(self, p):
        """DDL: {"cmd64": wire-JSON {"op":"catalog","method":...,args,kw}}."""
        cmd = _unpk(p["cmd64"])
        if isinstance(cmd, dict):
            cmd = _translate_cred_cmd(cmd)
        if not isinstance(cmd, dict) or cmd.get("op") != "catalog" or \
                cmd.get("method") not in _CATALOG_METHODS:
            raise RpcError(f"bad ddl command {cmd.get('method') if isinstance(cmd, dict) else cmd!r}")
        # pre-validate on the leader for a clean error before consensus
        # (wire round-trip = deep copy of the catalog)
        with self.state_lock:
            probe = schema_wire.from_jso(schema_wire.to_jso(self.state.catalog))
        try:
            getattr(probe, cmd["method"])(*cmd.get("args", ()),
                                          **cmd.get("kw", {}))
        except (SchemaError, KeyError, ValueError, TypeError) as ex:
            raise RpcError(str(ex)) from None
        return self._propose(cmd)

    def rpc_get_catalog(self, p):
        with self.state_lock:
            if p.get("version") == self.state.version:
                return {"version": self.state.version, "catalog": None,
                        "part_map": None}
            return {"version": self.state.version,
                    "catalog": _pk(self.state.catalog),
                    "part_map": self.state.part_map,
                    "learner_map": self.state.learner_map}

    def rpc_part_map(self, p):
        with self.state_lock:
            pm = self.state.part_map.get(p["space"])
            if pm is None:
                raise RpcError(f"space `{p['space']}' not found")
            return pm

    def rpc_create_session(self, p):
        return self._propose({"op": "create_session", "user": p["user"],
                              "graphd": p["graphd"], "ts": time.time()})

    def rpc_update_session(self, p):
        return self._propose({"op": "update_session", "sid": p["sid"],
                              "fields": p["fields"]})

    def rpc_remove_session(self, p):
        return self._propose({"op": "remove_session", "sid": p["sid"]})

    def rpc_list_sessions(self, p):
        with self.state_lock:
            return [{"sid": k, **v}
                    for k, v in sorted(self.state.sessions.items())]

    def rpc_session_gone(self, p):
        """True iff `sid` WAS a session and has been removed — the
        idempotent-kill predicate (double KILL SESSION from any
        coordinator succeeds quietly; a garbage sid still errors)."""
        with self.state_lock:
            return {"gone": p["sid"] in self.state.removed_sessions}

    def rpc_set_config(self, p):
        return self._propose({"op": "set_config", "name": p["name"],
                              "value": p["value"]})

    def rpc_get_config(self, p):
        with self.state_lock:
            if "name" in p:
                return self.state.configs.get(p["name"])
            return dict(self.state.configs)

    def rpc_submit_job(self, p):
        return self._propose({"op": "add_job", "cmd": p["cmd"],
                              "space": p.get("space"),
                              "graphd": p.get("graphd", ""),
                              "ts": time.time()})

    def rpc_update_job(self, p):
        return self._propose({"op": "update_job", "jid": p["jid"],
                              "fields": p["fields"]})

    def rpc_list_jobs(self, p):
        with self.state_lock:
            return [{"jid": k, **v}
                    for k, v in sorted(self.state.jobs.items())]

    def rpc_transfer_leader(self, p):
        return self._propose({"op": "transfer_leader", "space": p["space"],
                              "part": p["part"], "to": p["to"]})

    def rpc_add_hosts(self, p):
        """ADD HOSTS ... INTO ZONE z: assign hosts to a placement zone
        (moves them out of any previous zone).  Hosts must be
        `host:port` — a malformed entry would raft-replicate verbatim
        and break every later SHOW ZONES."""
        hosts = list(p["hosts"])
        for h in hosts:
            bad = ":" not in h
            if not bad:
                try:
                    int(h.rsplit(":", 1)[1])
                except ValueError:
                    bad = True
            if bad:
                raise RpcError(f"bad host `{h}' (want host:port)")
        return self._propose({"op": "add_zone_hosts", "zone": p["zone"],
                              "hosts": hosts})

    def rpc_drop_zone(self, p):
        return self._propose({"op": "drop_zone", "zone": p["zone"]})

    def rpc_merge_zones(self, p):
        return self._propose({"op": "merge_zones", "zones": list(p["zones"]),
                              "into": p["into"]})

    def rpc_divide_zone(self, p):
        return self._propose({"op": "divide_zone", "zone": p["zone"],
                              "parts": [[n, list(hs)]
                                        for n, hs in p["parts"]]})

    def rpc_rename_zone(self, p):
        return self._propose({"op": "rename_zone", "old": p["old"],
                              "new": p["new"]})

    def rpc_drop_hosts(self, p):
        with self.state_lock:
            zoned = {h for hs in self.state.zones.values() for h in hs}
        for h in p["hosts"]:
            if h not in self.active_hosts and h not in zoned:
                raise RpcError(f"host {h} not found")
        out = self._propose({"op": "drop_hosts", "hosts": list(p["hosts"])})
        # liveness is leader-local (not raft state): forget the host so
        # SHOW HOSTS stops listing it
        for h in p["hosts"]:
            self.active_hosts.pop(h, None)
        return out

    def rpc_list_zones(self, p):
        with self.state_lock:
            return {z: list(hs) for z, hs in self.state.zones.items()}

    def rpc_allocate_ids(self, p):
        """Segment ID allocation (the metad ID service): returns the
        start of a [start, start+count) range unique across the cluster
        lifetime — raft-serialized, never reused."""
        start = self._propose({"op": "allocate_ids",
                               "count": int(p.get("count", 1))})
        return {"start": start, "count": int(p.get("count", 1))}

    def rpc_set_part_replicas(self, p):
        return self._propose({"op": "set_part_replicas",
                              "space": p["space"], "part": p["part"],
                              "replicas": p["replicas"]})

    # -- repair plane (ISSUE 14): learners + raft-persisted plans ---------

    def rpc_set_part_learners(self, p):
        return self._propose({"op": "set_part_learners",
                              "space": p["space"], "part": p["part"],
                              "learners": p["learners"]})

    def rpc_promote_learner(self, p):
        return self._propose({"op": "promote_learner",
                              "space": p["space"], "part": p["part"],
                              "host": p["host"]})

    def rpc_part_learners(self, p):
        with self.state_lock:
            if p["space"] not in self.state.part_map:
                raise RpcError(f"space `{p['space']}' not found")
            return [list(ls) for ls in
                    self.state.learners_of(p["space"])]

    def rpc_list_repairs(self, p):
        with self.state_lock:
            return [{"rid": k, **v}
                    for k, v in sorted(self.state.repairs.items())]
