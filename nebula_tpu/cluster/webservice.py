"""HTTP admin endpoints — the proxygen webservice analog.

Every daemon exposes (reference: src/webservice [UNVERIFIED — empty
mount, SURVEY §0]):

    GET /status          liveness + role + git-describe-ish version
    GET /stats           metrics text (`?format=json` for JSON)
    GET /metrics         Prometheus text exposition format (ISSUE 1)
    GET /traces          recent trace summaries (`?id=<tid>` for one
                         trace's spans; add `&format=text` for the
                         indented tree rendering)
    GET /flight          flight-recorder summaries (`?id=<n>` for one
                         entry's full per-operator breakdown) (ISSUE 8)
    GET /queries         live workload plane (ISSUE 9): in-flight
                         statements with per-operator progress, plus
                         the device dispatch table (queued/running)
    GET /admission       overload plane (ISSUE 10): admission slots,
                         queue depth by session, watermark memory,
                         observed drain rate
    GET /tenants         tenant QoS plane (ISSUE 20): per-tenant DWRR
                         weight / running / queued / admitted share
    GET /stalls          stall-watchdog captures (`?id=<n>` for one
                         capture's full thread stacks / dispatch table
                         / kernel-ledger tail)
    GET /kernels         device kernel ledger: recent dispatches with
                         shape bucket / compile-vs-cache / µs / HBM
    GET /slo             multi-window SLO burn rates (availability +
                         latency objectives)
    GET /flags           all flag values (`?format=json`)
    PUT /flags           body `name=value` (or JSON object) — live update

Role-specific endpoints (metad's `/cluster_metrics` federation view)
are mounted through the `providers` dict: path → fn(query_dict) →
(status, body, content_type).

Plus TPU-build extras under /stats: device gauges (HBM bytes pinned,
last hop stats) fed through the same StatsManager.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qsl, urlparse

from ..utils.config import ConfigError, get_config
from ..utils.flight import flight_recorder, kernel_ledger
from ..utils.slo import slo_engine
from ..utils.stats import stats
from ..utils.trace import render_tree, trace_store

# provider signature: fn(query: dict) -> (http status, body, ctype)
Provider = Callable[[dict], Tuple[int, str, str]]


def _int_q(q: dict, key: str, default: int) -> int:
    try:
        return int(q.get(key, default))
    except (TypeError, ValueError):
        return default


class WebService:
    def __init__(self, role: str = "unknown", host: str = "127.0.0.1",
                 port: int = 0,
                 providers: Optional[Dict[str, Provider]] = None):
        self.role = role
        self.providers: Dict[str, Provider] = dict(providers or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A003 — quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                u = urlparse(self.path)
                q = dict(parse_qsl(u.query))
                as_json = q.get("format") == "json"
                if u.path == "/status":
                    self._send(200, json.dumps(
                        {"status": "running", "role": outer.role}),
                        "application/json")
                elif u.path == "/stats":
                    if as_json:
                        self._send(200,
                                   json.dumps(stats().snapshot(),
                                              default=str),
                                   "application/json")
                    else:
                        self._send(200, stats().to_text())
                elif u.path == "/metrics":
                    # refresh this process's slo_burn_* gauges on every
                    # scrape: the objectives measure THIS daemon's
                    # statement traffic, and without this a federated
                    # view would carry stale/absent burn rates for the
                    # graphds — the processes whose burn matters
                    try:
                        slo_engine().burn_rates()
                    except Exception:  # noqa: BLE001 — gauges best-effort
                        pass
                    self._send(200, stats().to_prometheus(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif u.path == "/traces":
                    tid = q.get("id")
                    if tid:
                        entry = trace_store().get(tid)
                        if entry is None:
                            self._send(404, f"no trace `{tid}'")
                        elif q.get("format") == "text":
                            self._send(200, render_tree(entry))
                        else:
                            self._send(200, json.dumps(entry,
                                                       default=str),
                                       "application/json")
                    else:
                        self._send(200,
                                   json.dumps(trace_store().list(),
                                              default=str),
                                   "application/json")
                elif u.path == "/flight":
                    # flight recorder (ISSUE 8): the per-operator
                    # breakdown of sampled/slow/failed statements,
                    # retrievable after the fact
                    fid = q.get("id")
                    if fid:
                        try:
                            entry = flight_recorder().get(int(fid))
                        except ValueError:
                            entry = None
                        if entry is None:
                            self._send(404, f"no flight entry `{fid}'")
                        else:
                            self._send(200, json.dumps(entry,
                                                       default=str),
                                       "application/json")
                    else:
                        limit = _int_q(q, "limit", 50)
                        self._send(200,
                                   json.dumps(
                                       flight_recorder().list(limit),
                                       default=str),
                                   "application/json")
                elif u.path == "/queries":
                    # live workload plane (ISSUE 9): what is running
                    # RIGHT NOW on this daemon, with per-operator
                    # progress and the device dispatch queue
                    from ..utils.workload import (dispatch_table,
                                                  live_registry)
                    self._send(200, json.dumps(
                        {"queries": live_registry().snapshot(),
                         "dispatches": dispatch_table().snapshot()},
                        default=str), "application/json")
                elif u.path == "/admission":
                    # overload plane (ISSUE 10): slots, queue depth,
                    # per-session backlog, watermark memory, drain rate
                    from ..utils.admission import admission
                    self._send(200, json.dumps(admission().snapshot(),
                                               default=str),
                               "application/json")
                elif u.path == "/tenants":
                    # tenant QoS plane (ISSUE 20): per-tenant DWRR
                    # weight / running / queued / admitted share on
                    # THIS coordinator (SHOW TENANTS merges the fleet)
                    from ..utils.admission import admission
                    self._send(200,
                               json.dumps(admission().tenant_snapshot(),
                                          default=str),
                               "application/json")
                elif u.path == "/stalls":
                    from ..utils.workload import stall_watchdog
                    sid = q.get("id")
                    if sid:
                        try:
                            entry = stall_watchdog().get(int(sid))
                        except ValueError:
                            entry = None
                        if entry is None:
                            self._send(404, f"no stall entry `{sid}'")
                        else:
                            self._send(200, json.dumps(entry,
                                                       default=str),
                                       "application/json")
                    else:
                        limit = _int_q(q, "limit", 20)
                        self._send(200,
                                   json.dumps(
                                       stall_watchdog().list(limit),
                                       default=str),
                                   "application/json")
                elif u.path == "/kernels":
                    limit = _int_q(q, "limit", 100)
                    self._send(200,
                               json.dumps(kernel_ledger().list(limit),
                                          default=str),
                               "application/json")
                elif u.path == "/slo":
                    self._send(200,
                               json.dumps(slo_engine().burn_rates(),
                                          default=str),
                               "application/json")
                elif u.path == "/flags":
                    vals = get_config().all_values()
                    if as_json:
                        self._send(200, json.dumps(vals, default=str),
                                   "application/json")
                    else:
                        self._send(200, "\n".join(
                            f"{k}={vals[k]}" for k in sorted(vals)))
                elif u.path in outer.providers:
                    try:
                        code, body, ctype = outer.providers[u.path](q)
                    except Exception as ex:  # noqa: BLE001 — 500, not death
                        code, body, ctype = 500, str(ex), "text/plain"
                    self._send(code, body, ctype)
                else:
                    self._send(404, "not found")

            def do_PUT(self):  # noqa: N802
                u = urlparse(self.path)
                if u.path != "/flags":
                    self._send(404, "not found")
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                try:
                    if body.lstrip().startswith("{"):
                        updates = json.loads(body)
                    else:
                        updates = dict(
                            ln.split("=", 1) for ln in body.splitlines()
                            if ln.strip())
                    # validate ALL keys before applying ANY — a 400 must
                    # mean nothing changed (the atomic multi-key path)
                    get_config().set_dynamic_many(
                        {k.strip(): v for k, v in updates.items()})
                    self._send(200, "ok")
                except (ConfigError, ValueError) as ex:
                    self._send(400, str(ex))

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"web-{self.port}")
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
