"""HTTP admin endpoints — the proxygen webservice analog.

Every daemon exposes (reference: src/webservice [UNVERIFIED — empty
mount, SURVEY §0]):

    GET /status          liveness + role + git-describe-ish version
    GET /stats           metrics text (`?format=json` for JSON)
    GET /metrics         Prometheus text exposition format (ISSUE 1)
    GET /traces          recent trace summaries (`?id=<tid>` for one
                         trace's spans; add `&format=text` for the
                         indented tree rendering)
    GET /flags           all flag values (`?format=json`)
    PUT /flags           body `name=value` (or JSON object) — live update

Plus TPU-build extras under /stats: device gauges (HBM bytes pinned,
last hop stats) fed through the same StatsManager.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from ..utils.config import ConfigError, get_config
from ..utils.stats import stats
from ..utils.trace import render_tree, trace_store


class WebService:
    def __init__(self, role: str = "unknown", host: str = "127.0.0.1",
                 port: int = 0):
        self.role = role
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A003 — quiet
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                u = urlparse(self.path)
                q = dict(parse_qsl(u.query))
                as_json = q.get("format") == "json"
                if u.path == "/status":
                    self._send(200, json.dumps(
                        {"status": "running", "role": outer.role}),
                        "application/json")
                elif u.path == "/stats":
                    if as_json:
                        self._send(200,
                                   json.dumps(stats().snapshot(),
                                              default=str),
                                   "application/json")
                    else:
                        self._send(200, stats().to_text())
                elif u.path == "/metrics":
                    self._send(200, stats().to_prometheus(),
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif u.path == "/traces":
                    tid = q.get("id")
                    if tid:
                        entry = trace_store().get(tid)
                        if entry is None:
                            self._send(404, f"no trace `{tid}'")
                        elif q.get("format") == "text":
                            self._send(200, render_tree(entry))
                        else:
                            self._send(200, json.dumps(entry,
                                                       default=str),
                                       "application/json")
                    else:
                        self._send(200,
                                   json.dumps(trace_store().list(),
                                              default=str),
                                   "application/json")
                elif u.path == "/flags":
                    vals = get_config().all_values()
                    if as_json:
                        self._send(200, json.dumps(vals, default=str),
                                   "application/json")
                    else:
                        self._send(200, "\n".join(
                            f"{k}={vals[k]}" for k in sorted(vals)))
                else:
                    self._send(404, "not found")

            def do_PUT(self):  # noqa: N802
                u = urlparse(self.path)
                if u.path != "/flags":
                    self._send(404, "not found")
                    return
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                try:
                    if body.lstrip().startswith("{"):
                        updates = json.loads(body)
                    else:
                        updates = dict(
                            ln.split("=", 1) for ln in body.splitlines()
                            if ln.strip())
                    cfg = get_config()
                    # validate ALL keys before applying ANY — a 400 must
                    # mean nothing changed
                    parsed = {k.strip(): cfg.check(k.strip(), v)
                              for k, v in updates.items()}
                    for k, v in parsed.items():
                        cfg.set_dynamic(k, v)
                    self._send(200, "ok")
                except (ConfigError, ValueError) as ex:
                    self._send(400, str(ex))

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"web-{self.port}")
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
