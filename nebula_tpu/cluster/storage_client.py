"""StorageClient — per-partition request routing + fan-out + retry.

Analog of the reference's src/clients/storage StorageClientBase
[UNVERIFIED — empty mount, SURVEY §0]: splits every request by the
partition of its vids (stable hash, same function the store uses),
sends each shard to a replica chosen per the request's consistency
level, retries on leader-change / connection errors after re-pulling
the map, and merges responses.  Fan-out is a thread pool (the
folly-futures analog) over PIPELINED per-peer clients (ISSUE 2):
partitions hosted on the same storaged multiplex over the pooled
connection by request id, so N-partition fan-out to one host is
wall-time ≈ max(partition), not sum.  Per-hop data-plane traffic does
NOT ride this in TPU mode (SURVEY §5 two-plane rule).

Replica routing (ISSUE 11 tentpole): `leader`-consistency calls keep
the leader-first walk (the cached part map front-loads the last known
leader — see MetaClient.note_part_leader).  Follower-readable calls
(`follower` / `bounded_stale` reads) rank the replica set by a
per-peer health score combining the PR 5 circuit-breaker state, the
PR 8 E_OVERLOAD retry-after penalty window, and a latency EWMA — so
reads steer toward the best live replica instead of piling onto a
sick or overloaded one.  An E_OVERLOAD or E_STALE reply walks ON to
the next replica (another replica can serve NOW) instead of backing
off against the one that just shed us.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..graphstore.store import stable_vid_hash
from ..utils import cancel as _cancel
from ..utils import trace as _trace
from ..utils.admission import is_overload, parse_retry_after
from ..utils.consistency import FOLLOWER, BOUNDED_STALE  # noqa: F401
from ..utils.stats import (current_cost, current_work, stats as _stats,
                           use_cost, use_work)
from .meta_client import MetaClient
from .rpc import (RpcClient, RpcConnError, RpcError, RpcNeverSentError,
                  breaker_for, deadline_sleep, is_idempotent,
                  retry_backoff)


class StorageError(Exception):
    pass


# -- per-peer routing scores (ISSUE 11) --------------------------------------

#: replica_route_score histogram buckets — scores are seconds-shaped
#: (EWMA latency + penalty-window remainders + breaker constants)
_SCORE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0,
                  10.0, 20.0)


class _PeerStat:
    __slots__ = ("ewma_s", "penalty_until")

    def __init__(self):
        self.ewma_s = 0.0
        self.penalty_until = 0.0


_peer_stats: Dict[str, _PeerStat] = {}
_peer_lock = threading.Lock()


def _peer_stat(addr: str) -> _PeerStat:
    with _peer_lock:
        st = _peer_stats.get(addr)
        if st is None:
            st = _peer_stats[addr] = _PeerStat()
        return st


def note_peer_latency(addr: str, seconds: float):
    """Fold one successful call's latency into the peer's EWMA (the
    slow-but-alive signal breakers can't see)."""
    st = _peer_stat(addr)
    st.ewma_s = seconds if st.ewma_s == 0.0 \
        else 0.8 * st.ewma_s + 0.2 * seconds


def note_peer_overload(addr: str, retry_after_s: Optional[float]):
    """An E_OVERLOAD from this peer: treat it as loaded for the hinted
    window — follower-readable routing avoids it until then."""
    st = _peer_stat(addr)
    until = time.monotonic() + (retry_after_s
                                if retry_after_s is not None else 0.5)
    if until > st.penalty_until:
        st.penalty_until = until


def peer_score(addr: str) -> float:
    """Routing cost of sending the next follower-readable read to
    `addr` — lower is better.  Seconds-shaped: latency EWMA, plus the
    remaining E_OVERLOAD penalty window, plus a large constant for an
    open circuit breaker (peer recently unreachable) and a small one
    for half-open (unproven).

    Per-PART load is deliberately not folded in here (this score is
    per-peer); the documented part-granular signal is
    `utils.insights.PartHeatTable.heat_of(space, part)` (ISSUE 16) —
    each storaged's heat rides its heartbeat, so a heat-aware router
    or BALANCE planner reads it from metad's merged hotspot view."""
    st = _peer_stat(addr)
    score = st.ewma_s
    rem = st.penalty_until - time.monotonic()
    if rem > 0:
        score += rem + 0.5
    br = breaker_for(addr)
    if br.state == "open":
        score += 10.0
    elif br.state == "half_open":
        score += 1.0
    return score


def reset_peer_stats():
    """Drop all routing state (test isolation)."""
    with _peer_lock:
        _peer_stats.clear()


class StorageClient:
    def __init__(self, meta: MetaClient, max_fanout: int = 16):
        self.meta = meta
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max_fanout,
                                        thread_name_prefix="storage-fanout")

    def _client(self, addr: str) -> RpcClient:
        # retries=0: _call_part owns retry (replica walk + map refresh);
        # the pooled client multiplexes concurrent per-part calls to
        # this peer over its connections by request id
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient.from_addr(
                    addr, timeout=60.0, retries=0)
            return c

    def close(self):
        self._pool.shutdown(wait=False)
        for c in self._clients.values():
            c.close()

    # -- routing ----------------------------------------------------------

    def part_of(self, space: str, vid: Any) -> int:
        pm = self.meta.parts_of(space)
        return stable_vid_hash(vid) % len(pm)

    def split_by_part(self, space: str, vids: List[Any]
                      ) -> Dict[int, List[Any]]:
        pm = self.meta.parts_of(space)
        n = len(pm)
        out: Dict[int, List[Any]] = {}
        for v in vids:
            out.setdefault(stable_vid_hash(v) % n, []).append(v)
        return out

    def _route(self, replicas: List[str], follower_ok: bool) -> List[str]:
        """Replica try-order for one attempt.  `leader` consistency
        keeps the cached map order (leader-first — the hint write-back
        below keeps that front slot fresh across failovers); follower-
        readable calls rank by per-peer health score so reads land on
        the best live replica first (stable sort: the map order breaks
        score ties, so healthy clusters fan reads out per the map)."""
        if not follower_ok or len(replicas) <= 1:
            return list(replicas)
        return sorted(replicas, key=peer_score)

    def _call_part(self, space: str, pid: int, method: str,
                   params: Dict[str, Any], retries: int = 6) -> Any:
        last: Optional[Exception] = None
        # a (writer_id, seq) idempotency token makes re-sending safe for
        # ANY method: storaged's raft-replicated dedup window returns the
        # recorded outcome instead of double-applying — the mid-call
        # abort below flips into a replica-walk retry (ISSUE 5)
        resendable = is_idempotent(method) or \
            (isinstance(params, dict) and params.get("token") is not None)
        # follower-readable calls (ISSUE 11) carry their consistency in
        # the params — ANY replica may serve them, so routing ranks the
        # replica set by health score instead of walking leader-first
        follower_ok = isinstance(params, dict) and \
            params.get("consistency") in (FOLLOWER, BOUNDED_STALE)
        for attempt in range(retries):
            # between attempts the statement's deadline/kill budget is
            # the authority — a killed query must not keep walking
            _cancel.check()
            pm = self.meta.parts_of(space)
            # leader first, then the rest (covers stale maps); a
            # "part_leader_changed: <addr>" hint extends the walk — a
            # fresh post-failover leader is reachable THIS attempt, long
            # before the heartbeat → metad → refresh pipeline reorders
            # the part map (the upstream storage client's leader walk)
            queue = self._route(pm[pid], follower_ok)
            tried = set()
            qi = 0
            while qi < len(queue):
                addr = queue[qi]
                qi += 1
                if addr in tried:
                    continue
                tried.add(addr)
                t_call = time.monotonic()
                try:
                    r = self._client(addr).call(
                        method, space=space, part=pid, **params)
                except RpcError as ex:
                    last = ex
                    msg = str(ex)
                    if "part_leader_changed" in msg or \
                            "not hosted here" in msg:
                        hint = msg.rsplit(": ", 1)[-1].strip()
                        if ":" in hint:
                            if hint not in tried:
                                queue.append(hint)
                            # leader-hint write-back (ISSUE 11
                            # satellite): remember the hinted leader in
                            # the cached part map so the NEXT statement
                            # goes straight there — one walk total per
                            # failover, not one per call until the
                            # heartbeat→metad→refresh pipeline catches
                            # up
                            self.meta.note_part_leader(space, pid, hint)
                        _stats().inc_labeled("storage_replica_walk_retries",
                                             {"op": method})
                        continue
                    if msg.startswith("E_STALE"):
                        # bounded_stale reject: THIS replica is too far
                        # behind — a fresher one (the leader serves
                        # unconditionally) can answer right now
                        _stats().inc_labeled("storage_replica_walk_retries",
                                             {"op": method})
                        continue
                    if is_overload(msg):
                        # the peer shed the request before its handler
                        # ran (PR 8 bounded inbox): remember the load
                        # signal for routing and — when re-sending is
                        # safe — walk ON to a sibling replica instead
                        # of backing off against the loaded one
                        note_peer_overload(addr, parse_retry_after(msg))
                        if resendable:
                            _stats().inc_labeled(
                                "storage_replica_walk_retries",
                                {"op": method})
                            continue
                    raise StorageError(msg) from None
                except RpcNeverSentError as ex:
                    last = ex           # never reached the peer: walk on
                    _stats().inc_labeled("storage_replica_walk_retries",
                                         {"op": method})
                    continue
                except RpcConnError as ex:
                    last = ex
                    # the request MAY have applied before the connection
                    # died — walking replicas / retrying would re-send
                    # it, so only idempotent methods and tokened
                    # (dedup-protected) writes keep going; everything
                    # else surfaces the at-least-once hazard to the
                    # caller (same gate RpcClient.call applies, one
                    # layer up where the replica walk lives)
                    if resendable:
                        _stats().inc_labeled("storage_replica_walk_retries",
                                             {"op": method})
                        continue
                    raise StorageError(
                        f"{method} to part {pid} of `{space}' failed "
                        f"mid-call; not retried (non-idempotent): {ex}"
                    ) from None
                # success: feed the routing signals — latency EWMA, and
                # the score this serve was chosen at (observability for
                # the steering decision)
                dt = time.monotonic() - t_call
                note_peer_latency(addr, dt)
                if follower_ok:
                    _stats().observe("replica_route_score",
                                     peer_score(addr), {"peer": addr},
                                     buckets=_SCORE_BUCKETS)
                return r
            # election / part creation may be in flight — jittered
            # exponential backoff, clamped to the remaining deadline
            # budget (a herd of retriers after a leader crash must not
            # resynchronize on fixed sleeps)
            deadline_sleep(retry_backoff(attempt, base=0.1))
            self.meta.refresh(force=True)
        raise StorageError(f"part {pid} of `{space}' unreachable: {last}")

    def fanout(self, space: str, by_part: Dict[int, Dict[str, Any]],
               method: str) -> List[Tuple[int, Any]]:
        """Concurrent per-part calls; returns [(pid, result)] sorted.

        The submitting thread's trace context and work-counter target
        are re-established on each pool thread, so per-part spans and
        RPC/wire-byte counts attribute to the query that fanned out."""
        tctx = _trace.current_ctx()
        wc = current_work()
        cc = current_cost()
        kill = _cancel.current_kill()
        dl = _cancel.current_deadline()

        def run(pid, params):
            # cancel context rides to the pool thread like trace/work/
            # cost do: the per-part call clamps its RPC timeouts and
            # backoff to the statement budget, stops walking when
            # killed, and attributes reply-envelope cost records to the
            # plan node that fanned out
            with _trace.use_ctx(tctx), use_work(wc), use_cost(cc), \
                    _cancel.use_cancel(kill=kill, deadline=dl), \
                    _trace.span(f"storage:{method}", part=pid,
                                space=space):
                return self._call_part(space, pid, method, params)

        futs = {pid: self._pool.submit(run, pid, params)
                for pid, params in by_part.items()}
        # kill-aware wait (ISSUE 5 satellite): KILL QUERY during the
        # fan-out must not block on a stalled partition until its RPC
        # timeout — poll the cancel context while waiting.  Context-
        # free callers (admin/balance paths) keep the single cheap
        # blocking collect instead of a 20Hz poll loop
        if kill is None and dl is None:
            return [(pid, f.result()) for pid, f in sorted(futs.items())]
        pending = set(futs.values())
        try:
            while pending:
                done, pending = wait(pending, timeout=0.05,
                                     return_when=FIRST_COMPLETED)
                if pending:
                    _cancel.check()
        except (_cancel.QueryKilled, _cancel.DeadlineExceeded):
            for f in pending:
                f.cancel()          # unstarted parts never dispatch
            raise
        return [(pid, f.result()) for pid, f in sorted(futs.items())]

    def all_parts(self, space: str) -> List[int]:
        return list(range(len(self.meta.parts_of(space))))
