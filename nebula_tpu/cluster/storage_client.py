"""StorageClient — per-partition request routing + fan-out + retry.

Analog of the reference's src/clients/storage StorageClientBase
[UNVERIFIED — empty mount, SURVEY §0]: splits every request by the
partition of its vids (stable hash, same function the store uses),
sends each shard to that part's leader from the cached part map,
retries on leader-change / connection errors after re-pulling the map,
and merges responses.  Fan-out is a thread pool (the folly-futures
analog) over PIPELINED per-peer clients (ISSUE 2): partitions hosted on
the same storaged multiplex over the pooled connection by request id,
so N-partition fan-out to one host is wall-time ≈ max(partition), not
sum.  Per-hop data-plane traffic does NOT ride this in TPU mode
(SURVEY §5 two-plane rule).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..graphstore.store import stable_vid_hash
from ..utils import trace as _trace
from ..utils.stats import current_work, use_work
from .meta_client import MetaClient
from .rpc import (RpcClient, RpcConnError, RpcError, RpcNeverSentError,
                  is_idempotent)


class StorageError(Exception):
    pass


class StorageClient:
    def __init__(self, meta: MetaClient, max_fanout: int = 16):
        self.meta = meta
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max_fanout,
                                        thread_name_prefix="storage-fanout")

    def _client(self, addr: str) -> RpcClient:
        # retries=0: _call_part owns retry (replica walk + map refresh);
        # the pooled client multiplexes concurrent per-part calls to
        # this peer over its connections by request id
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient.from_addr(
                    addr, timeout=60.0, retries=0)
            return c

    def close(self):
        self._pool.shutdown(wait=False)
        for c in self._clients.values():
            c.close()

    # -- routing ----------------------------------------------------------

    def part_of(self, space: str, vid: Any) -> int:
        pm = self.meta.parts_of(space)
        return stable_vid_hash(vid) % len(pm)

    def split_by_part(self, space: str, vids: List[Any]
                      ) -> Dict[int, List[Any]]:
        pm = self.meta.parts_of(space)
        n = len(pm)
        out: Dict[int, List[Any]] = {}
        for v in vids:
            out.setdefault(stable_vid_hash(v) % n, []).append(v)
        return out

    def _call_part(self, space: str, pid: int, method: str,
                   params: Dict[str, Any], retries: int = 4) -> Any:
        last: Optional[Exception] = None
        for attempt in range(retries):
            pm = self.meta.parts_of(space)
            replicas = pm[pid]
            # leader first, then the rest (covers stale maps)
            for addr in replicas:
                try:
                    return self._client(addr).call(
                        method, space=space, part=pid, **params)
                except RpcError as ex:
                    last = ex
                    if "part_leader_changed" in str(ex) or \
                            "not hosted here" in str(ex):
                        continue
                    raise StorageError(str(ex)) from None
                except RpcNeverSentError as ex:
                    last = ex           # never reached the peer: walk on
                    continue
                except RpcConnError as ex:
                    last = ex
                    # the request MAY have applied before the connection
                    # died — walking replicas / retrying would re-send
                    # it, so only idempotent methods keep going (the
                    # same at-least-once gate RpcClient.call applies,
                    # one layer up where the replica walk lives)
                    if is_idempotent(method):
                        continue
                    raise StorageError(
                        f"{method} to part {pid} of `{space}' failed "
                        f"mid-call; not retried (non-idempotent): {ex}"
                    ) from None
            # election / part creation may be in flight — back off briefly
            import time
            time.sleep(0.1 * (attempt + 1))
            self.meta.refresh(force=True)
        raise StorageError(f"part {pid} of `{space}' unreachable: {last}")

    def fanout(self, space: str, by_part: Dict[int, Dict[str, Any]],
               method: str) -> List[Tuple[int, Any]]:
        """Concurrent per-part calls; returns [(pid, result)] sorted.

        The submitting thread's trace context and work-counter target
        are re-established on each pool thread, so per-part spans and
        RPC/wire-byte counts attribute to the query that fanned out."""
        tctx = _trace.current_ctx()
        wc = current_work()

        def run(pid, params):
            with _trace.use_ctx(tctx), use_work(wc), \
                    _trace.span(f"storage:{method}", part=pid,
                                space=space):
                return self._call_part(space, pid, method, params)

        futs = {pid: self._pool.submit(run, pid, params)
                for pid, params in by_part.items()}
        return [(pid, f.result()) for pid, f in sorted(futs.items())]

    def all_parts(self, space: str) -> List[int]:
        return list(range(len(self.meta.parts_of(space))))
