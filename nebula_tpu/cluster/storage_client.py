"""StorageClient — per-partition request routing + fan-out + retry.

Analog of the reference's src/clients/storage StorageClientBase
[UNVERIFIED — empty mount, SURVEY §0]: splits every request by the
partition of its vids (stable hash, same function the store uses),
sends each shard to that part's leader from the cached part map,
retries on leader-change / connection errors after re-pulling the map,
and merges responses.  Fan-out is a thread pool (the folly-futures
analog) over PIPELINED per-peer clients (ISSUE 2): partitions hosted on
the same storaged multiplex over the pooled connection by request id,
so N-partition fan-out to one host is wall-time ≈ max(partition), not
sum.  Per-hop data-plane traffic does NOT ride this in TPU mode
(SURVEY §5 two-plane rule).
"""
from __future__ import annotations

import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..graphstore.store import stable_vid_hash
from ..utils import cancel as _cancel
from ..utils import trace as _trace
from ..utils.stats import (current_cost, current_work, stats as _stats,
                           use_cost, use_work)
from .meta_client import MetaClient
from .rpc import (RpcClient, RpcConnError, RpcError, RpcNeverSentError,
                  deadline_sleep, is_idempotent, retry_backoff)


class StorageError(Exception):
    pass


class StorageClient:
    def __init__(self, meta: MetaClient, max_fanout: int = 16):
        self.meta = meta
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max_fanout,
                                        thread_name_prefix="storage-fanout")

    def _client(self, addr: str) -> RpcClient:
        # retries=0: _call_part owns retry (replica walk + map refresh);
        # the pooled client multiplexes concurrent per-part calls to
        # this peer over its connections by request id
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient.from_addr(
                    addr, timeout=60.0, retries=0)
            return c

    def close(self):
        self._pool.shutdown(wait=False)
        for c in self._clients.values():
            c.close()

    # -- routing ----------------------------------------------------------

    def part_of(self, space: str, vid: Any) -> int:
        pm = self.meta.parts_of(space)
        return stable_vid_hash(vid) % len(pm)

    def split_by_part(self, space: str, vids: List[Any]
                      ) -> Dict[int, List[Any]]:
        pm = self.meta.parts_of(space)
        n = len(pm)
        out: Dict[int, List[Any]] = {}
        for v in vids:
            out.setdefault(stable_vid_hash(v) % n, []).append(v)
        return out

    def _call_part(self, space: str, pid: int, method: str,
                   params: Dict[str, Any], retries: int = 6) -> Any:
        last: Optional[Exception] = None
        # a (writer_id, seq) idempotency token makes re-sending safe for
        # ANY method: storaged's raft-replicated dedup window returns the
        # recorded outcome instead of double-applying — the mid-call
        # abort below flips into a replica-walk retry (ISSUE 5)
        resendable = is_idempotent(method) or \
            (isinstance(params, dict) and params.get("token") is not None)
        for attempt in range(retries):
            # between attempts the statement's deadline/kill budget is
            # the authority — a killed query must not keep walking
            _cancel.check()
            pm = self.meta.parts_of(space)
            # leader first, then the rest (covers stale maps); a
            # "part_leader_changed: <addr>" hint extends the walk — a
            # fresh post-failover leader is reachable THIS attempt, long
            # before the heartbeat → metad → refresh pipeline reorders
            # the part map (the upstream storage client's leader walk)
            queue = list(pm[pid])
            tried = set()
            qi = 0
            while qi < len(queue):
                addr = queue[qi]
                qi += 1
                if addr in tried:
                    continue
                tried.add(addr)
                try:
                    return self._client(addr).call(
                        method, space=space, part=pid, **params)
                except RpcError as ex:
                    last = ex
                    msg = str(ex)
                    if "part_leader_changed" in msg or \
                            "not hosted here" in msg:
                        hint = msg.rsplit(": ", 1)[-1].strip()
                        if ":" in hint and hint not in tried:
                            queue.append(hint)
                        _stats().inc_labeled("storage_replica_walk_retries",
                                             {"op": method})
                        continue
                    raise StorageError(msg) from None
                except RpcNeverSentError as ex:
                    last = ex           # never reached the peer: walk on
                    _stats().inc_labeled("storage_replica_walk_retries",
                                         {"op": method})
                    continue
                except RpcConnError as ex:
                    last = ex
                    # the request MAY have applied before the connection
                    # died — walking replicas / retrying would re-send
                    # it, so only idempotent methods and tokened
                    # (dedup-protected) writes keep going; everything
                    # else surfaces the at-least-once hazard to the
                    # caller (same gate RpcClient.call applies, one
                    # layer up where the replica walk lives)
                    if resendable:
                        _stats().inc_labeled("storage_replica_walk_retries",
                                             {"op": method})
                        continue
                    raise StorageError(
                        f"{method} to part {pid} of `{space}' failed "
                        f"mid-call; not retried (non-idempotent): {ex}"
                    ) from None
            # election / part creation may be in flight — jittered
            # exponential backoff, clamped to the remaining deadline
            # budget (a herd of retriers after a leader crash must not
            # resynchronize on fixed sleeps)
            deadline_sleep(retry_backoff(attempt, base=0.1))
            self.meta.refresh(force=True)
        raise StorageError(f"part {pid} of `{space}' unreachable: {last}")

    def fanout(self, space: str, by_part: Dict[int, Dict[str, Any]],
               method: str) -> List[Tuple[int, Any]]:
        """Concurrent per-part calls; returns [(pid, result)] sorted.

        The submitting thread's trace context and work-counter target
        are re-established on each pool thread, so per-part spans and
        RPC/wire-byte counts attribute to the query that fanned out."""
        tctx = _trace.current_ctx()
        wc = current_work()
        cc = current_cost()
        kill = _cancel.current_kill()
        dl = _cancel.current_deadline()

        def run(pid, params):
            # cancel context rides to the pool thread like trace/work/
            # cost do: the per-part call clamps its RPC timeouts and
            # backoff to the statement budget, stops walking when
            # killed, and attributes reply-envelope cost records to the
            # plan node that fanned out
            with _trace.use_ctx(tctx), use_work(wc), use_cost(cc), \
                    _cancel.use_cancel(kill=kill, deadline=dl), \
                    _trace.span(f"storage:{method}", part=pid,
                                space=space):
                return self._call_part(space, pid, method, params)

        futs = {pid: self._pool.submit(run, pid, params)
                for pid, params in by_part.items()}
        # kill-aware wait (ISSUE 5 satellite): KILL QUERY during the
        # fan-out must not block on a stalled partition until its RPC
        # timeout — poll the cancel context while waiting.  Context-
        # free callers (admin/balance paths) keep the single cheap
        # blocking collect instead of a 20Hz poll loop
        if kill is None and dl is None:
            return [(pid, f.result()) for pid, f in sorted(futs.items())]
        pending = set(futs.values())
        try:
            while pending:
                done, pending = wait(pending, timeout=0.05,
                                     return_when=FIRST_COMPLETED)
                if pending:
                    _cancel.check()
        except (_cancel.QueryKilled, _cancel.DeadlineExceeded):
            for f in pending:
                f.cancel()          # unstarted parts never dispatch
            raise
        return [(pid, f.result()) for pid, f in sorted(futs.items())]

    def all_parts(self, space: str) -> List[int]:
        return list(range(len(self.meta.parts_of(space))))
